/* fastcodec — CPython extension interpreting rpc.codec's wire format.
 *
 * The Python codec (pegasus_tpu/rpc/codec.py) derives encoder/decoder
 * closures from dataclass annotations; profiling the serving path showed
 * ~half the server CPU inside those closures (varints, per-byte bytearray
 * appends, getattr walks). This module executes the SAME wire format from
 * a compact node tree compiled once per dataclass by codec._fast_plan:
 *
 *   int        -> zigzag varint            node 'i'
 *   bool       -> 1 byte                   node 'b'
 *   bytes      -> varint length + raw      node 'y'
 *   str        -> varint length + utf-8    node 's'
 *   IntEnum    -> as int (decode rewraps)  node 'e' (py = enum class)
 *   Optional   -> presence byte + inner    node 'O'
 *   List       -> varint count + items     node 'L'
 *   dataclass  -> varint field count + fields in order   node 'D' (py = Plan)
 *   unsupported-> lazily illegal (empty List / None Optional still fine)
 *                                          node 'X'
 *
 * Byte-for-byte identical to the Python codec (differentially fuzzed by
 * tests/test_fastcodec.py). Ints support the full range the Python
 * encoder produces for this codebase: [-2^63, 2^64) via __int128 zigzag
 * (partition hashes are unsigned 64-bit).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <errno.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>

#ifndef MSG_NOSIGNAL
#define MSG_NOSIGNAL 0
#endif

static PyObject *CodecError; /* set by register_error(); fallback ValueError */

#define RAISE(msg)                                                         \
    do {                                                                   \
        PyErr_SetString(CodecError ? CodecError : PyExc_ValueError, msg);  \
    } while (0)

/* ------------------------------------------------------------------ nodes */

typedef struct Node {
    char kind;
    struct Node *inner; /* O, L */
    PyObject *py;       /* D: Plan (strong), e: enum class (strong) */
} Node;

static void node_free(Node *n)
{
    if (!n)
        return;
    node_free(n->inner);
    Py_XDECREF(n->py);
    PyMem_Free(n);
}

/* ------------------------------------------------------------------- plan */

typedef struct {
    PyObject_HEAD
    PyObject *cls;   /* dataclass constructor */
    PyObject *names; /* tuple of str (interned) */
    Py_ssize_t nfields;
    Node **nodes; /* array[nfields] */
    int ready;
} PlanObject;

static PyTypeObject Plan_Type; /* fwd */

static Node *parse_spec(PyObject *spec)
{
    if (!PyTuple_Check(spec) || PyTuple_GET_SIZE(spec) < 1) {
        RAISE("spec must be a non-empty tuple");
        return NULL;
    }
    PyObject *k = PyTuple_GET_ITEM(spec, 0);
    const char *ks = PyUnicode_AsUTF8(k);
    if (!ks)
        return NULL;
    Node *n = PyMem_Calloc(1, sizeof(Node));
    if (!n) {
        PyErr_NoMemory();
        return NULL;
    }
    n->kind = ks[0];
    switch (n->kind) {
    case 'i':
    case 'b':
    case 'y':
    case 's':
    case 'X':
        return n;
    case 'e':
    case 'D': {
        if (PyTuple_GET_SIZE(spec) != 2)
            goto bad;
        PyObject *payload = PyTuple_GET_ITEM(spec, 1);
        if (n->kind == 'D' && !PyObject_TypeCheck(payload, &Plan_Type))
            goto bad;
        Py_INCREF(payload);
        n->py = payload;
        return n;
    }
    case 'O':
    case 'L': {
        if (PyTuple_GET_SIZE(spec) != 2)
            goto bad;
        n->inner = parse_spec(PyTuple_GET_ITEM(spec, 1));
        if (!n->inner) {
            PyMem_Free(n);
            return NULL;
        }
        return n;
    }
    default:
        goto bad;
    }
bad:
    PyMem_Free(n);
    RAISE("malformed spec");
    return NULL;
}

/* ----------------------------------------------------------------- buffer */

typedef struct {
    unsigned char *p;
    Py_ssize_t len, cap;
} Buf;

static int buf_grow(Buf *b, Py_ssize_t extra)
{
    Py_ssize_t need = b->len + extra;
    if (need <= b->cap)
        return 0;
    Py_ssize_t cap = b->cap ? b->cap : 256;
    while (cap < need)
        cap <<= 1;
    unsigned char *np = PyMem_Realloc(b->p, cap);
    if (!np) {
        PyErr_NoMemory();
        return -1;
    }
    b->p = np;
    b->cap = cap;
    return 0;
}

static inline int buf_byte(Buf *b, unsigned char c)
{
    if (b->len >= b->cap && buf_grow(b, 1) < 0)
        return -1;
    b->p[b->len++] = c;
    return 0;
}

static int buf_varint(Buf *b, unsigned __int128 v)
{
    if (buf_grow(b, 19) < 0) /* 128/7 rounded up */
        return -1;
    while (v >= 0x80) {
        b->p[b->len++] = (unsigned char)(v & 0x7F) | 0x80;
        v >>= 7;
    }
    b->p[b->len++] = (unsigned char)v;
    return 0;
}

static int buf_raw(Buf *b, const char *src, Py_ssize_t n)
{
    if (buf_grow(b, n) < 0)
        return -1;
    memcpy(b->p + b->len, src, n);
    b->len += n;
    return 0;
}

/* ----------------------------------------------------------------- encode */

static int enc_value(Node *n, PyObject *v, Buf *b);

static int enc_int_obj(PyObject *v, Buf *b)
{
    int ovf = 0;
    long long sv = PyLong_AsLongLongAndOverflow(v, &ovf);
    if (sv == -1 && !ovf && PyErr_Occurred())
        return -1;
    unsigned __int128 z;
    if (!ovf) {
        __int128 w = (__int128)sv;
        z = (unsigned __int128)((w << 1) ^ (w >> 63));
    } else if (ovf > 0) {
        unsigned long long uv = PyLong_AsUnsignedLongLong(v);
        if (uv == (unsigned long long)-1 && PyErr_Occurred())
            return -1;
        z = ((unsigned __int128)uv) << 1;
    } else {
        RAISE("int below -2^63 unsupported");
        return -1;
    }
    return buf_varint(b, z);
}

static int enc_struct(PlanObject *p, PyObject *obj, Buf *b)
{
    if (!p->ready) { /* a nested plan must never be an in-flight shell */
        RAISE("plan not initialized");
        return -1;
    }
    if (buf_byte(b, (unsigned char)p->nfields) < 0)
        return -1;
    for (Py_ssize_t i = 0; i < p->nfields; i++) {
        PyObject *v = PyObject_GetAttr(obj, PyTuple_GET_ITEM(p->names, i));
        if (!v)
            return -1;
        int rc = enc_value(p->nodes[i], v, b);
        Py_DECREF(v);
        if (rc < 0)
            return -1;
    }
    return 0;
}

static int enc_value(Node *n, PyObject *v, Buf *b)
{
    switch (n->kind) {
    case 'i':
    case 'e': { /* enums encode as their int value */
        if (PyLong_CheckExact(v))
            return enc_int_obj(v, b);
        PyObject *iv = PyNumber_Index(v);
        if (!iv)
            return -1;
        int rc = enc_int_obj(iv, b);
        Py_DECREF(iv);
        return rc;
    }
    case 'b':
    {
        int t = PyObject_IsTrue(v);
        if (t < 0)
            return -1;
        return buf_byte(b, t ? 1 : 0);
    }
    case 'y': {
        if (PyBytes_Check(v)) {
            Py_ssize_t ln = PyBytes_GET_SIZE(v);
            if (buf_varint(b, (unsigned __int128)ln) < 0)
                return -1;
            return buf_raw(b, PyBytes_AS_STRING(v), ln);
        }
        Py_buffer view;
        if (PyObject_GetBuffer(v, &view, PyBUF_SIMPLE) < 0)
            return -1;
        int rc = buf_varint(b, (unsigned __int128)view.len);
        if (rc == 0)
            rc = buf_raw(b, view.buf, view.len);
        PyBuffer_Release(&view);
        return rc;
    }
    case 's': {
        Py_ssize_t ln;
        const char *u = PyUnicode_AsUTF8AndSize(v, &ln);
        if (!u)
            return -1;
        if (buf_varint(b, (unsigned __int128)ln) < 0)
            return -1;
        return buf_raw(b, u, ln);
    }
    case 'O':
        if (v == Py_None)
            return buf_byte(b, 0);
        if (buf_byte(b, 1) < 0)
            return -1;
        return enc_value(n->inner, v, b);
    case 'L': {
        PyObject *fast = PySequence_Fast(v, "list field expects a sequence");
        if (!fast)
            return -1;
        Py_ssize_t cnt = PySequence_Fast_GET_SIZE(fast);
        if (buf_varint(b, (unsigned __int128)cnt) < 0) {
            Py_DECREF(fast);
            return -1;
        }
        PyObject **items = PySequence_Fast_ITEMS(fast);
        for (Py_ssize_t i = 0; i < cnt; i++) {
            if (enc_value(n->inner, items[i], b) < 0) {
                Py_DECREF(fast);
                return -1;
            }
        }
        Py_DECREF(fast);
        return 0;
    }
    case 'D':
        return enc_struct((PlanObject *)n->py, v, b);
    case 'X':
        RAISE("unsupported field type used with a non-empty value");
        return -1;
    }
    RAISE("corrupt plan");
    return -1;
}

/* ----------------------------------------------------------------- decode */

typedef struct {
    const unsigned char *p;
    Py_ssize_t len, off;
} Rd;

static PyObject *dec_value(Node *n, Rd *r);

static int rd_varint(Rd *r, unsigned __int128 *out)
{
    if (r->off >= r->len) {
        RAISE("truncated varint");
        return -1;
    }
    unsigned char b0 = r->p[r->off];
    if (!(b0 & 0x80)) { /* 1-byte fast path */
        r->off++;
        *out = b0;
        return 0;
    }
    unsigned __int128 val = 0;
    int shift = 0;
    for (;;) {
        if (r->off >= r->len) {
            RAISE("truncated varint");
            return -1;
        }
        unsigned char b = r->p[r->off++];
        val |= ((unsigned __int128)(b & 0x7F)) << shift;
        if (!(b & 0x80))
            break;
        shift += 7;
        if (shift > 63) {
            /* 10 bytes (shifts 0..63) cover every value the encoder can
             * emit for [-2^63, 2^64); a longer varint is corrupt input,
             * and continuing would shift continuation bits off the
             * 128-bit accumulator into a silently-wrong small value.
             * Matching the Python decoder's 10-byte bound, both paths
             * raise on the same malformed frames. */
            RAISE("varint overflow");
            return -1;
        }
    }
    *out = val;
    return 0;
}

static PyObject *dec_int(Rd *r)
{
    unsigned __int128 z;
    if (rd_varint(r, &z) < 0)
        return NULL;
    __int128 res = (__int128)(z >> 1) * ((z & 1) ? -1 : 1) - (__int128)(z & 1);
    /* equivalent to (z >> 1) ^ -(z & 1) in arbitrary precision */
    if (res >= 0) {
        if (res <= (__int128)UINT64_MAX)
            return PyLong_FromUnsignedLongLong((unsigned long long)res);
    } else if (res >= (__int128)INT64_MIN) {
        return PyLong_FromLongLong((long long)res);
    }
    RAISE("int out of range");
    return NULL;
}

static PyObject *dec_struct(PlanObject *p, Rd *r)
{
    if (!p->ready) { /* a nested plan must never be an in-flight shell */
        RAISE("plan not initialized");
        return NULL;
    }
    unsigned __int128 n128;
    if (rd_varint(r, &n128) < 0)
        return NULL;
    Py_ssize_t n = (Py_ssize_t)n128;
    if (n > p->nfields) {
        PyErr_Format(CodecError ? CodecError : PyExc_ValueError,
                     "%s: encoder sent %zd fields, decoder knows %zd",
                     ((PyTypeObject *)p->cls)->tp_name, n, p->nfields);
        return NULL;
    }
    PyObject *args[128];
    Py_ssize_t got = 0;
    for (; got < n; got++) {
        args[got] = dec_value(p->nodes[got], r);
        if (!args[got])
            goto fail;
    }
    PyObject *obj = PyObject_Vectorcall(p->cls, args, (size_t)n, NULL);
    for (Py_ssize_t i = 0; i < got; i++)
        Py_DECREF(args[i]);
    return obj;
fail:
    for (Py_ssize_t i = 0; i < got; i++)
        Py_DECREF(args[i]);
    return NULL;
}

static PyObject *dec_value(Node *n, Rd *r)
{
    switch (n->kind) {
    case 'i':
        return dec_int(r);
    case 'e': {
        PyObject *iv = dec_int(r);
        if (!iv)
            return NULL;
        PyObject *ev = PyObject_CallOneArg(n->py, iv);
        Py_DECREF(iv);
        return ev;
    }
    case 'b': {
        if (r->off >= r->len) {
            RAISE("truncated bool");
            return NULL;
        }
        PyObject *v = r->p[r->off++] ? Py_True : Py_False;
        Py_INCREF(v);
        return v;
    }
    case 'y': {
        unsigned __int128 ln;
        if (rd_varint(r, &ln) < 0)
            return NULL;
        if (ln > (unsigned __int128)(r->len - r->off)) {
            RAISE("truncated bytes");
            return NULL;
        }
        PyObject *v = PyBytes_FromStringAndSize(
            (const char *)r->p + r->off, (Py_ssize_t)ln);
        r->off += (Py_ssize_t)ln;
        return v;
    }
    case 's': {
        unsigned __int128 ln;
        if (rd_varint(r, &ln) < 0)
            return NULL;
        if (ln > (unsigned __int128)(r->len - r->off)) {
            RAISE("truncated str");
            return NULL;
        }
        PyObject *v = PyUnicode_DecodeUTF8(
            (const char *)r->p + r->off, (Py_ssize_t)ln, NULL);
        r->off += (Py_ssize_t)ln;
        return v;
    }
    case 'O': {
        if (r->off >= r->len) {
            RAISE("truncated optional");
            return NULL;
        }
        unsigned char flag = r->p[r->off++];
        if (!flag)
            Py_RETURN_NONE;
        return dec_value(n->inner, r);
    }
    case 'L': {
        unsigned __int128 cnt128;
        if (rd_varint(r, &cnt128) < 0)
            return NULL;
        if (cnt128 > (unsigned __int128)(r->len - r->off)) {
            RAISE("truncated list"); /* every item needs >= 1 byte */
            return NULL;
        }
        Py_ssize_t cnt = (Py_ssize_t)cnt128;
        PyObject *lst = PyList_New(cnt);
        if (!lst)
            return NULL;
        for (Py_ssize_t i = 0; i < cnt; i++) {
            PyObject *item = dec_value(n->inner, r);
            if (!item) {
                Py_DECREF(lst);
                return NULL;
            }
            PyList_SET_ITEM(lst, i, item);
        }
        return lst;
    }
    case 'D':
        return dec_struct((PlanObject *)n->py, r);
    case 'X':
        RAISE("unsupported field type present on the wire");
        return NULL;
    }
    RAISE("corrupt plan");
    return NULL;
}

/* ------------------------------------------------------------ Plan object */

static PyObject *Plan_new(PyTypeObject *type, PyObject *args, PyObject *kw)
{
    PlanObject *self = (PlanObject *)type->tp_alloc(type, 0);
    if (self) {
        self->cls = NULL;
        self->names = NULL;
        self->nodes = NULL;
        self->nfields = 0;
        self->ready = 0;
    }
    return (PyObject *)self;
}

static void Plan_dealloc(PlanObject *self)
{
    for (Py_ssize_t i = 0; i < self->nfields; i++)
        node_free(self->nodes ? self->nodes[i] : NULL);
    PyMem_Free(self->nodes);
    Py_XDECREF(self->cls);
    Py_XDECREF(self->names);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *Plan_init_plan(PlanObject *self, PyObject *args)
{
    PyObject *cls, *names, *specs;
    if (!PyArg_ParseTuple(args, "OO!O!", &cls, &PyTuple_Type, &names,
                          &PyTuple_Type, &specs))
        return NULL;
    if (self->ready) {
        RAISE("plan already initialized");
        return NULL;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(names);
    if (n != PyTuple_GET_SIZE(specs) || n >= 128) {
        RAISE("names/specs mismatch or too many fields");
        return NULL;
    }
    self->nodes = PyMem_Calloc(n, sizeof(Node *));
    if (!self->nodes)
        return PyErr_NoMemory();
    for (Py_ssize_t i = 0; i < n; i++) {
        self->nodes[i] = parse_spec(PyTuple_GET_ITEM(specs, i));
        if (!self->nodes[i]) {
            for (Py_ssize_t j = 0; j < i; j++)
                node_free(self->nodes[j]);
            PyMem_Free(self->nodes);
            self->nodes = NULL;
            return NULL;
        }
    }
    Py_INCREF(cls);
    self->cls = cls;
    Py_INCREF(names);
    self->names = names;
    self->nfields = n;
    self->ready = 1;
    Py_RETURN_NONE;
}

static PyObject *Plan_encode(PlanObject *self, PyObject *obj)
{
    if (!self->ready) {
        RAISE("plan not initialized");
        return NULL;
    }
    Buf b = {NULL, 0, 0};
    if (enc_struct(self, obj, &b) < 0) {
        PyMem_Free(b.p);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.p, b.len);
    PyMem_Free(b.p);
    return out;
}

static PyObject *Plan_decode(PlanObject *self, PyObject *data)
{
    if (!self->ready) {
        RAISE("plan not initialized");
        return NULL;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Rd r = {view.buf, view.len, 0};
    PyObject *obj = dec_struct(self, &r);
    Py_ssize_t left = r.len - r.off;
    PyBuffer_Release(&view);
    if (obj && left) {
        PyErr_Format(CodecError ? CodecError : PyExc_ValueError,
                     "%zd trailing bytes", left);
        Py_DECREF(obj);
        return NULL;
    }
    return obj;
}

static PyObject *Plan_decode_from(PlanObject *self, PyObject *args)
{
    /* mid-buffer decode for Python-plan callers with a C-plan field:
       (data, off) -> (obj, new_off); no trailing-bytes check */
    PyObject *data;
    Py_ssize_t off;
    if (!PyArg_ParseTuple(args, "On", &data, &off))
        return NULL;
    if (!self->ready) {
        RAISE("plan not initialized");
        return NULL;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    if (off < 0 || off > view.len) {
        PyBuffer_Release(&view);
        RAISE("offset out of range");
        return NULL;
    }
    Rd r = {view.buf, view.len, off};
    PyObject *obj = dec_struct(self, &r);
    Py_ssize_t end = r.off;
    PyBuffer_Release(&view);
    if (!obj)
        return NULL;
    PyObject *out = Py_BuildValue("(Nn)", obj, end);
    return out;
}

static PyMethodDef Plan_methods[] = {
    {"init_plan", (PyCFunction)Plan_init_plan, METH_VARARGS,
     "init_plan(cls, names, specs)"},
    {"encode", (PyCFunction)Plan_encode, METH_O, "encode(obj) -> bytes"},
    {"decode", (PyCFunction)Plan_decode, METH_O, "decode(data) -> obj"},
    {"decode_from", (PyCFunction)Plan_decode_from, METH_VARARGS,
     "decode_from(data, off) -> (obj, off)"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject Plan_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fastcodec.Plan",
    .tp_basicsize = sizeof(PlanObject),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = Plan_new,
    .tp_dealloc = (destructor)Plan_dealloc,
    .tp_methods = Plan_methods,
};

/* ------------------------------------------------------- frame wave reader
 *
 * The RPC serving inner loop (rpc/transport.py): u32 LE payload length |
 * u32 LE header length | header | body.  The Python loop re-entered the
 * interpreter per frame (length parse, header decode, body slice — ~4
 * allocations and a dict of closures per frame).  FrameReader drains a
 * socket's whole pipelined wave in C: one recv() (GIL released), then
 * every complete frame in the buffer is parsed and header-decoded without
 * touching Python until the finished (header, body) list is returned.
 */

#define FR_MAX_HOT 8 /* hot-code bins per reader (3 used today) */

typedef struct {
    PyObject_HEAD
    PlanObject *plan;   /* RpcHeader plan (strong) */
    PyObject *hot;      /* tuple of hot code strs (strong), may be NULL */
    unsigned char *buf; /* unparsed bytes */
    Py_ssize_t len, cap, pos;
} FrameReaderObject;

static PyObject *str_code; /* interned "code" attr name (module init) */

static PyObject *FrameReader_new(PyTypeObject *type, PyObject *args,
                                 PyObject *kw)
{
    PyObject *plan, *hot = NULL;
    if (!PyArg_ParseTuple(args, "O!|O!", &Plan_Type, &plan, &PyTuple_Type,
                          &hot))
        return NULL;
    if (hot && PyTuple_GET_SIZE(hot) > FR_MAX_HOT) {
        RAISE("too many hot codes");
        return NULL;
    }
    FrameReaderObject *self = (FrameReaderObject *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    Py_INCREF(plan);
    self->plan = (PlanObject *)plan;
    Py_XINCREF(hot);
    self->hot = hot;
    self->buf = NULL;
    self->len = self->cap = self->pos = 0;
    return (PyObject *)self;
}

static void FrameReader_dealloc(FrameReaderObject *self)
{
    Py_XDECREF(self->plan);
    Py_XDECREF(self->hot);
    PyMem_Free(self->buf);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static int fr_reserve(FrameReaderObject *self, Py_ssize_t extra)
{
    /* compact consumed bytes first so the buffer stays wave-sized */
    if (self->pos) {
        memmove(self->buf, self->buf + self->pos, self->len - self->pos);
        self->len -= self->pos;
        self->pos = 0;
    }
    Py_ssize_t need = self->len + extra;
    if (need <= self->cap)
        return 0;
    Py_ssize_t cap = self->cap ? self->cap : (1 << 16);
    while (cap < need)
        cap <<= 1;
    unsigned char *np = PyMem_Realloc(self->buf, cap);
    if (!np) {
        PyErr_NoMemory();
        return -1;
    }
    self->buf = np;
    self->cap = cap;
    return 0;
}

static PyObject *FrameReader_feed(FrameReaderObject *self, PyObject *data)
{
    /* preload bytes already read elsewhere (adopted-connection leftovers
       from the partition-group router's first-frame peek) */
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    int rc = fr_reserve(self, view.len);
    if (rc == 0) {
        memcpy(self->buf + self->len, view.buf, view.len);
        self->len += view.len;
    }
    PyBuffer_Release(&view);
    if (rc < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* parse ONE complete frame at self->pos into a (header, body) pair.
 * 1 = parsed (pair set, pos advanced), 0 = incomplete, -1 = error. */
static int fr_parse_one(FrameReaderObject *self, PyObject **pair_out)
{
    Py_ssize_t avail = self->len - self->pos;
    if (avail < 8)
        return 0;
    const unsigned char *p = self->buf + self->pos;
    uint32_t plen, hlen;
    memcpy(&plen, p, 4); /* little-endian host assumed (x86/arm) */
    memcpy(&hlen, p + 4, 4);
    if (plen < 4 || (Py_ssize_t)hlen > (Py_ssize_t)plen - 4) {
        RAISE("corrupt frame lengths");
        return -1;
    }
    if (avail < 4 + (Py_ssize_t)plen)
        return 0;
    Rd r = {p + 8, (Py_ssize_t)hlen, 0};
    PyObject *header = dec_struct(self->plan, &r);
    if (!header)
        return -1;
    if (r.off != r.len) {
        Py_DECREF(header);
        RAISE("trailing bytes after header");
        return -1;
    }
    PyObject *body = PyBytes_FromStringAndSize(
        (const char *)p + 8 + hlen, (Py_ssize_t)plen - 4 - hlen);
    if (!body) {
        Py_DECREF(header);
        return -1;
    }
    PyObject *pair = PyTuple_Pack(2, header, body);
    Py_DECREF(header);
    Py_DECREF(body);
    if (!pair)
        return -1;
    self->pos += 4 + (Py_ssize_t)plen;
    *pair_out = pair;
    return 1;
}

/* parse every complete frame at self->pos into `out`; 0 ok, -1 error */
static int fr_parse_frames(FrameReaderObject *self, PyObject *out)
{
    for (;;) {
        PyObject *pair;
        int rc = fr_parse_one(self, &pair);
        if (rc <= 0)
            return rc;
        rc = PyList_Append(out, pair);
        Py_DECREF(pair);
        if (rc < 0)
            return -1;
    }
}

/* The dispatch variant: every complete frame parsed AND binned by hot
 * task code. Output entries are (code str, [(header, body), ...]) in
 * first-arrival order; frames whose code is in self->hot coalesce into
 * the entry opened by their first frame, every other frame gets its own
 * singleton entry — so Python dispatches hot read codes once per BATCH
 * instead of once per frame. */
static int fr_parse_frames_binned(FrameReaderObject *self, PyObject *out)
{
    PyObject *bins[FR_MAX_HOT]; /* borrowed: each list lives in `out` */
    Py_ssize_t nhot = self->hot ? PyTuple_GET_SIZE(self->hot) : 0;
    for (Py_ssize_t i = 0; i < nhot; i++)
        bins[i] = NULL;
    for (;;) {
        PyObject *pair;
        int rc = fr_parse_one(self, &pair);
        if (rc <= 0)
            return rc;
        PyObject *code = PyObject_GetAttr(PyTuple_GET_ITEM(pair, 0),
                                          str_code);
        if (!code) {
            Py_DECREF(pair);
            return -1;
        }
        Py_ssize_t hot_idx = -1;
        for (Py_ssize_t i = 0; i < nhot; i++) {
            int eq = PyObject_RichCompareBool(
                code, PyTuple_GET_ITEM(self->hot, i), Py_EQ);
            if (eq < 0) {
                Py_DECREF(code);
                Py_DECREF(pair);
                return -1;
            }
            if (eq) {
                hot_idx = i;
                break;
            }
        }
        if (hot_idx >= 0 && bins[hot_idx]) {
            rc = PyList_Append(bins[hot_idx], pair);
            Py_DECREF(code);
            Py_DECREF(pair);
            if (rc < 0)
                return -1;
            continue;
        }
        PyObject *lst = PyList_New(0);
        if (!lst || PyList_Append(lst, pair) < 0) {
            Py_XDECREF(lst);
            Py_DECREF(code);
            Py_DECREF(pair);
            return -1;
        }
        Py_DECREF(pair);
        PyObject *entry = PyTuple_Pack(2, code, lst);
        Py_DECREF(code);
        if (!entry) {
            Py_DECREF(lst);
            return -1;
        }
        rc = PyList_Append(out, entry);
        Py_DECREF(entry);
        if (rc < 0) {
            Py_DECREF(lst);
            return -1;
        }
        if (hot_idx >= 0)
            bins[hot_idx] = lst; /* borrowed; `out` keeps it alive */
        Py_DECREF(lst);
    }
}

/* one recv() with the GIL released into the (pre-reserved) buffer tail;
 * 0 ok (len advanced), -1 = Python error already set */
static int fr_recv(FrameReaderObject *self, long fd)
{
    if (fr_reserve(self, 1 << 18) < 0)
        return -1;
    Py_ssize_t n;
    for (;;) {
        Py_BEGIN_ALLOW_THREADS
        n = recv((int)fd, self->buf + self->len,
                 (size_t)(self->cap - self->len), 0);
        Py_END_ALLOW_THREADS
        if (n >= 0 || errno != EINTR)
            break;
        if (PyErr_CheckSignals() < 0)
            return -1;
    }
    if (n == 0) {
        PyErr_SetString(PyExc_ConnectionError, "peer closed");
        return -1;
    }
    if (n < 0) {
        PyErr_SetFromErrno(PyExc_OSError);
        return -1;
    }
    self->len += n;
    return 0;
}

static PyObject *fr_read_loop(FrameReaderObject *self, PyObject *arg,
                              int (*parse)(FrameReaderObject *, PyObject *))
{
    long fd = PyLong_AsLong(arg);
    if (fd == -1 && PyErr_Occurred())
        return NULL;
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    for (;;) {
        if (parse(self, out) < 0) {
            Py_DECREF(out);
            return NULL;
        }
        if (PyList_GET_SIZE(out) > 0)
            return out;
        if (fr_recv(self, fd) < 0) {
            Py_DECREF(out);
            return NULL;
        }
    }
}

static PyObject *FrameReader_read_wave(FrameReaderObject *self, PyObject *arg)
{
    return fr_read_loop(self, arg, fr_parse_frames);
}

static PyObject *FrameReader_read_wave_binned(FrameReaderObject *self,
                                              PyObject *arg)
{
    return fr_read_loop(self, arg, fr_parse_frames_binned);
}

static PyMethodDef FrameReader_methods[] = {
    {"feed", (PyCFunction)FrameReader_feed, METH_O,
     "feed(bytes): preload already-read bytes into the buffer"},
    {"read_wave", (PyCFunction)FrameReader_read_wave, METH_O,
     "read_wave(fd) -> [(header, body), ...]; blocks for >=1 frame"},
    {"read_wave_binned", (PyCFunction)FrameReader_read_wave_binned, METH_O,
     "read_wave_binned(fd) -> [(code, [(header, body), ...]), ...];\n"
     "frames with a hot code coalesce into one entry per wave"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject FrameReader_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "fastcodec.FrameReader",
    .tp_basicsize = sizeof(FrameReaderObject),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = FrameReader_new,
    .tp_dealloc = (destructor)FrameReader_dealloc,
    .tp_methods = FrameReader_methods,
};

/* ------------------------------------------------------- vectored writer */

#ifndef FC_IOV_MAX /* stay under every libc's UIO_MAXIOV (>= 1024) */
#define FC_IOV_MAX 1000
#endif

/* sendmsg_frames(fd, [(header_bytes, body), ...]) -> total bytes sent.
 * Encodes the 8-byte length prefix for every frame into one arena and
 * gathers prefix+header+body iovecs into as few sendmsg() calls as
 * IOV_MAX allows, with the GIL released for the syscalls — the whole
 * response wave leaves in one C call instead of len(wave) Python
 * send()s. */
static PyObject *sendmsg_frames(PyObject *mod, PyObject *args)
{
    long fd;
    PyObject *pairs;
    if (!PyArg_ParseTuple(args, "lO", &fd, &pairs))
        return NULL;
    PyObject *seq = PySequence_Fast(pairs, "pairs must be a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    if (n == 0) {
        Py_DECREF(seq);
        return PyLong_FromLong(0);
    }
    Py_buffer *bufs = PyMem_Calloc((size_t)(2 * n), sizeof(Py_buffer));
    unsigned char *prefix = PyMem_Malloc((size_t)(8 * n));
    struct iovec *iov = PyMem_Malloc((size_t)(3 * n) * sizeof(struct iovec));
    Py_ssize_t nbufs = 0;
    PyObject *result = NULL;
    if (!bufs || !prefix || !iov) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
            PyErr_SetString(PyExc_TypeError,
                            "pairs items must be (header, body) tuples");
            goto done;
        }
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(pair, 0), &bufs[2 * i],
                               PyBUF_SIMPLE) < 0)
            goto done;
        nbufs++;
        if (PyObject_GetBuffer(PyTuple_GET_ITEM(pair, 1), &bufs[2 * i + 1],
                               PyBUF_SIMPLE) < 0)
            goto done;
        nbufs++;
        Py_ssize_t hlen = bufs[2 * i].len, blen = bufs[2 * i + 1].len;
        Py_ssize_t plen = 4 + hlen + blen;
        if (hlen > (Py_ssize_t)UINT32_MAX || plen > (Py_ssize_t)UINT32_MAX) {
            RAISE("frame too large");
            goto done;
        }
        uint32_t w = (uint32_t)plen;
        memcpy(prefix + 8 * i, &w, 4); /* little-endian host assumed */
        w = (uint32_t)hlen;
        memcpy(prefix + 8 * i + 4, &w, 4);
        iov[3 * i].iov_base = prefix + 8 * i;
        iov[3 * i].iov_len = 8;
        iov[3 * i + 1].iov_base = bufs[2 * i].buf;
        iov[3 * i + 1].iov_len = (size_t)hlen;
        iov[3 * i + 2].iov_base = bufs[2 * i + 1].buf;
        iov[3 * i + 2].iov_len = (size_t)blen;
    }
    {
        Py_ssize_t iovcnt = 3 * n, idx = 0;
        unsigned long long total = 0;
        while (idx < iovcnt) {
            /* skip fully-consumed entries so msg_iovlen counts real work */
            if (iov[idx].iov_len == 0) {
                idx++;
                continue;
            }
            Py_ssize_t cnt = iovcnt - idx;
            if (cnt > FC_IOV_MAX)
                cnt = FC_IOV_MAX;
            struct msghdr msg;
            memset(&msg, 0, sizeof(msg));
            msg.msg_iov = iov + idx;
            msg.msg_iovlen = (size_t)cnt;
            ssize_t s;
            Py_BEGIN_ALLOW_THREADS
            s = sendmsg((int)fd, &msg, MSG_NOSIGNAL);
            Py_END_ALLOW_THREADS
            if (s < 0) {
                if (errno == EINTR) {
                    if (PyErr_CheckSignals() < 0)
                        goto done;
                    continue;
                }
                if (errno == EPIPE || errno == ECONNRESET) {
                    PyErr_SetString(PyExc_ConnectionError,
                                    "peer closed during vectored send");
                    goto done;
                }
                PyErr_SetFromErrno(PyExc_OSError);
                goto done;
            }
            total += (unsigned long long)s;
            size_t left = (size_t)s; /* advance past what the kernel took */
            while (left > 0) {
                if (iov[idx].iov_len <= left) {
                    left -= iov[idx].iov_len;
                    iov[idx].iov_len = 0;
                    idx++;
                } else {
                    iov[idx].iov_base = (char *)iov[idx].iov_base + left;
                    iov[idx].iov_len -= left;
                    left = 0;
                }
            }
        }
        result = PyLong_FromUnsignedLongLong(total);
    }
done:
    for (Py_ssize_t i = 0; i < nbufs; i++)
        PyBuffer_Release(&bufs[i]);
    PyMem_Free(iov);
    PyMem_Free(prefix);
    PyMem_Free(bufs);
    Py_DECREF(seq);
    return result;
}

/* ----------------------------------------------------------------- module */

static PyObject *register_error(PyObject *mod, PyObject *exc)
{
    Py_INCREF(exc);
    Py_XSETREF(CodecError, exc);
    Py_RETURN_NONE;
}

static PyMethodDef mod_methods[] = {
    {"register_error", register_error, METH_O,
     "register the CodecError class raised on malformed data"},
    {"sendmsg_frames", sendmsg_frames, METH_VARARGS,
     "sendmsg_frames(fd, [(header, body), ...]) -> bytes sent;\n"
     "vectored frame write with length prefixes, GIL released"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastcodec_module = {
    PyModuleDef_HEAD_INIT, "fastcodec",
    "C interpreter for the rpc.codec wire format", -1, mod_methods,
};

PyMODINIT_FUNC PyInit_fastcodec(void)
{
    if (PyType_Ready(&Plan_Type) < 0 || PyType_Ready(&FrameReader_Type) < 0)
        return NULL;
    str_code = PyUnicode_InternFromString("code");
    if (!str_code)
        return NULL;
    PyObject *m = PyModule_Create(&fastcodec_module);
    if (!m)
        return NULL;
    Py_INCREF(&Plan_Type);
    if (PyModule_AddObject(m, "Plan", (PyObject *)&Plan_Type) < 0) {
        Py_DECREF(&Plan_Type);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&FrameReader_Type);
    if (PyModule_AddObject(m, "FrameReader",
                           (PyObject *)&FrameReader_Type) < 0) {
        Py_DECREF(&FrameReader_Type);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
