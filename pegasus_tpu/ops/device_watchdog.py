"""Device-health watchdog: is the TPU backend alive — and if not, WHERE
did it wedge?

Every red bench round so far recorded only a bare timeout ("tpu lane
exceeded 360s") because nothing distinguished a device tunnel wedged in
backend init from one wedged mid-kernel or mid-transfer. The watchdog
probes backend liveness with a tiny jit round-trip executed in a
SUBORDINATE daemon thread under a timeout, so the probe can hang without
hanging the caller — and a hung probe thread is simply abandoned, never
joined again or force-killed (a TPU-attached thread must not be killed;
the same never-SIGKILL rule bench.py applies to its lane child).

State it records:

  last_ok          wall time of the last successful probe
  wedged_at_stage  the innermost open tracing span (runtime/tracing.py)
                   once fail_threshold CONSECUTIVE probes failed (one
                   starved probe behind a long-but-healthy kernel is an
                   error, not a wedge) — "device_init", "pack", "h2d",
                   "device", "gather", or "idle" when nothing was in
                   flight. This is the stage attribution BENCH_r06+
                   records instead of a bare timeout.

Counters (one registry with everything else — /metrics serves them):
  compact.watchdog.probe_count / probe_failures   rate
  compact.watchdog.probe_us                       percentile
  compact.watchdog.wedged                         gauge (0/1)

start() arms a background loop that re-probes every interval_s and, when
status_path is set, heartbeats the state there as JSON (atomic replace).
The bench parent reads that file when it has to abandon a wedged child,
so the degraded JSON line can name the wedged stage across the process
boundary. probe_fn is injectable for tests (a deliberately-hung fake
backend exercises the timeout path without hardware).
"""

import json
import os
import threading
import time

from ..runtime.perf_counters import counters
from ..runtime.tracing import COMPACT_TRACER

_PROBE_JIT = []  # compiled once; a fresh jit per probe would re-trace


def _default_probe() -> bool:
    """Tiny jit round-trip; blocks iff the backend/tunnel is wedged."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if not _PROBE_JIT:
        _PROBE_JIT.append(jax.jit(lambda x: x + jnp.int32(1)))
    out = np.asarray(_PROBE_JIT[0](jnp.zeros(8, jnp.int32)))
    return int(out[0]) == 1


class DeviceHealthWatchdog:
    def __init__(self, probe_timeout_s: float = 10.0,
                 interval_s: float = 5.0, probe_fn=None,
                 tracer=COMPACT_TRACER, status_path: str = None,
                 fail_threshold: int = 2):
        self.probe_timeout_s = probe_timeout_s
        self.interval_s = interval_s
        self.probe_fn = probe_fn or _default_probe
        self.tracer = tracer
        self.status_path = status_path
        # one slow-but-healthy kernel can legitimately starve a probe past
        # its timeout (device work serializes); only consecutive failures
        # flip the wedged state, so a single starved probe records an
        # error without a false wedge verdict
        self.fail_threshold = fail_threshold
        # False = heartbeat-only: the loop skips probes but keeps writing
        # status. bench.py disarms until ITS thread has done the platform
        # config + jax import — a probe-thread jit racing that init would
        # bind the backend before jax.config.update lands
        self.probes_armed = True
        self._lock = threading.Lock()
        self._probe_thread = None  # in-flight (possibly hung) probe
        self._consec_failures = 0
        self.last_ok = None
        self.last_error = None
        self.wedged_at_stage = None
        self._stop = threading.Event()
        self._loop_thread = None

    # ------------------------------------------------------------- probing

    def probe(self, timeout_s: float = None) -> bool:
        """One liveness round-trip under a timeout. False = wedged (or the
        previous probe never came back — no stacking of hung threads)."""
        timeout = self.probe_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                pass  # previous probe still hung: fail fast below
            else:
                self._probe_thread = None
            hung = self._probe_thread is not None
        counters.rate("compact.watchdog.probe_count").increment()
        if hung:
            self._mark_failed("previous probe still hung")
            return False
        result = {}

        def run():
            try:
                result["ok"] = bool(self.probe_fn())
            except Exception as e:  # noqa: BLE001 - a probe error IS the signal
                result["error"] = repr(e)

        from ..runtime.tasking import spawn_thread

        # never joined on timeout by design: a wedged TPU-attached probe
        # is abandoned, not killed (the registry still tracks it)
        t = spawn_thread(run, daemon=True, name="device-probe", start=False)
        with self._lock:
            self._probe_thread = t
        t0 = time.perf_counter()
        t.start()
        t.join(timeout)
        counters.percentile("compact.watchdog.probe_us").set(
            int((time.perf_counter() - t0) * 1e6))
        if t.is_alive():
            # the probe is wedged inside the backend; leave the daemon
            # thread behind (never kill a TPU-attached thread)
            self._mark_failed(f"probe timed out after {timeout}s")
            return False
        with self._lock:
            self._probe_thread = None
        if result.get("ok"):
            with self._lock:
                self.last_ok = time.time()
                self.last_error = None
                self.wedged_at_stage = None
                self._consec_failures = 0
            counters.number("compact.watchdog.wedged").set(0)
            return True
        self._mark_failed(result.get("error", "probe returned falsy"))
        return False

    def _mark_failed(self, error: str):
        inner = self.tracer.innermost_open()
        with self._lock:
            self.last_error = error
            self._consec_failures += 1
            wedged = self._consec_failures >= self.fail_threshold
            if wedged:
                self.wedged_at_stage = inner[0] if inner else "idle"
        counters.rate("compact.watchdog.probe_failures").increment()
        if wedged:
            counters.number("compact.watchdog.wedged").set(1)

    # -------------------------------------------------------------- state

    def state(self) -> dict:
        with self._lock:
            out = {"last_ok": self.last_ok,
                   "last_error": self.last_error,
                   "wedged_at_stage": self.wedged_at_stage}
        out["open_stages"] = {str(tid): stages for tid, stages
                              in self.tracer.open_stages().items()}
        # the lane guard's breaker/fallback totals ride in every health
        # surface this state feeds: the device-health remote command,
        # /compact/trace, and bench's status-file heartbeat (so a degraded
        # bench line shows whether the run fell back to cpu)
        from ..runtime.lane_guard import LANE_GUARD

        out["lane"] = LANE_GUARD.state()
        return out

    def write_status(self) -> None:
        """Heartbeat the state to status_path (atomic tmp+replace) so a
        PARENT process can read where this one wedged after abandoning it."""
        if not self.status_path:
            return
        payload = dict(self.state(), ts=time.time())
        tmp = f"{self.status_path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.status_path)
        except OSError:
            pass  # a failed heartbeat must never fail the pipeline

    # ----------------------------------------------------------- lifecycle

    def start(self):
        """Arm the background probe+heartbeat loop (idempotent)."""
        with self._lock:
            if self._loop_thread is not None and self._loop_thread.is_alive():
                return self
            from ..runtime.tasking import spawn_thread

            self._stop.clear()
            self._loop_thread = spawn_thread(
                self._loop, daemon=True, name="device-watchdog",
                start=False)
        self._loop_thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        # first heartbeat immediately: a wedge during device init should be
        # attributable even if it happens before the first interval elapses
        while True:
            try:
                if self.probes_armed:
                    self.probe()
            except Exception as e:  # noqa: BLE001 - loop must survive
                print(f"[device-watchdog] probe crashed: {e!r}", flush=True)
            self.write_status()
            if self._stop.wait(self.interval_s):
                return


# process-wide instance: the manual-compact service probes it around tpu
# compactions, bench.py's lane child arms its loop with a status file
WATCHDOG = DeviceHealthWatchdog()
