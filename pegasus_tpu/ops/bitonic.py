"""Bitonic multi-column sort — the TPU-shaped sort primitive.

Why not lax.sort: XLA's TPU sort lowers to a comparator network unrolled per
input size — compile time grows ~linearly with n (measured ~0.3ms/element on
v5e: 65k elements = 22s, 1M would be minutes). A compaction engine sorts
fresh shapes constantly; that cost is fatal.

This implementation emits the classic bitonic network as log2(n)*(log2(n)+1)/2
*vectorized stages*. Each stage reshapes to [blocks, 2, j] so partners (i,
i^j) are adjacent slices — pure strided slice/compare/select, no gathers —
and the whole program is O(log^2 n) HLO ops regardless of n. Runtime is
HBM-bandwidth bound: ~log^2(n) passes over the column set.

Sorts lexicographically by `key_cols` (uint32, first = most significant),
carrying `payload` (the record permutation). Ties keep original relative
pair order per stage; callers guarantee key uniqueness (suffix_rank/key_len
columns) so stability is irrelevant to the contract.

n must be a power of two (the engine pads to pow2 buckets already).
"""

import jax.numpy as jnp


def _lex_less(a_cols, b_cols):
    """Strict a < b over column lists, vectorized."""
    less = jnp.zeros(a_cols[0].shape, dtype=bool)
    eq = jnp.ones(a_cols[0].shape, dtype=bool)
    for a, b in zip(a_cols, b_cols):
        less = less | (eq & (a < b))
        eq = eq & (a == b)
    return less


def bitonic_sort(key_cols, payload):
    """-> (sorted key_cols, sorted payload), ascending lexicographic."""
    n = key_cols[0].shape[0]
    if n & (n - 1):
        raise ValueError(f"bitonic_sort needs power-of-two n, got {n}")
    cols = list(key_cols) + [payload]
    nk = len(key_cols)
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            blocks = n // (2 * j)
            r = [c.reshape(blocks, 2, j) for c in cols]
            a = [rc[:, 0, :] for rc in r]  # slots i (low)
            b = [rc[:, 1, :] for rc in r]  # partners i^j (high)
            # direction is constant per 2j-block: ascending iff block_start&k==0
            starts = jnp.arange(blocks, dtype=jnp.uint32) * jnp.uint32(2 * j)
            up = ((starts & jnp.uint32(k)) == 0)[:, None]
            b_less_a = _lex_less(b[:nk], a[:nk])
            a_less_b = _lex_less(a[:nk], b[:nk])
            swap = jnp.where(up, b_less_a, a_less_b)
            cols = [
                jnp.stack(
                    [jnp.where(swap, bb, aa), jnp.where(swap, aa, bb)], axis=1
                ).reshape(n)
                for aa, bb in zip(a, b)
            ]
            j //= 2
        k *= 2
    return cols[:nk], cols[nk]
