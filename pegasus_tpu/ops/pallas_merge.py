"""Tier-2 merge kernel: merge-path chunking + whole-merge-in-VMEM Pallas.

The XLA networks in ops.device_sort materialize every compare-exchange
stage in HBM: a merge of length L costs ~log2(L) full passes (~24 at 16M).
This kernel cuts that to ~2 HBM passes: the classic GPU "merge path"
decomposition splits the output into fixed-size chunks along cross
diagonals of the merge matrix, and a Pallas program per chunk loads its
two input slices into VMEM, merges them entirely in VMEM, and writes its
finished output chunk once.

  1. diagonal search (plain jnp, outside the kernel): for each output
     position d = p*CHUNK, binary-search the split (ai, bi), ai+bi=d, such
     that A[ai-1] < B[bi] and B[bi-1] < A[ai] in the strict lexicographic
     column order (keys are unique by construction — the packed
     klen<<8|prio column differs across runs).
  2. pallas_call over grid=(P,): program p DMAs the TILE-ALIGNED windows
     A[al : al+W] and B[bl : bl+W] (al = ai rounded down to the 1024-lane
     VMEM tile, W = CHUNK + TILE) from HBM into VMEM scratch, merges the
     2W window bitonically, and stores rows [delta, delta+CHUNK) =
     out[d : d+CHUNK], where delta = d - al - bl.

Why aligned windows: Mosaic requires DMA slice offsets provably
divisible by the memref tiling (1024 elements for i32 1D); arbitrary
merge-path splits are not. Rounding both sides down to the tile keeps
every DMA offset aligned (asserted via pl.multiple_of) at the cost of
merging 2*(CHUNK+TILE) elements instead of 2*CHUNK. The residual
delta = (ai-al) + (bi-bl) is < 2*TILE and congruent to 0 mod 1024
(d is a multiple of CHUNK=2048; al, bl of 1024), so delta is always 0 or
1024 — a whole number of (8,128) rows, making the output window a select
between two static row slices. Correctness of the window trick: by the
merge-path property everything in A[:ai] ∪ B[:bi] strictly precedes
everything in A[ai:] ∪ B[bi:], so the sorted window's first delta
elements are exactly A[al:ai] ∪ B[bl:bi] and the next CHUNK are exactly
out[d : d+CHUNK] (the chunk consumes at most CHUNK from each side, which
the window covers).

Mosaic (real-TPU) lowering notes, learned on hardware (r3):
  - refs in ANY/HBM space cannot be loaded directly; slices must move via
    pltpu.make_async_copy into VMEM scratch, with tile-aligned offsets.
  - per-program split offsets live in SMEM.
  - the in-VMEM merge runs on a 2D (rows, 128) layout: flat element k
    maps to (k // 128, k % 128). Stages with distance j >= 128 permute
    whole sublane rows (slice+concat along axis 0); stages with j < 128
    permute lanes via a 128x128 XOR one-hot matmul on the MXU (u32 split
    into u8 quarters, exact in bf16), built in-kernel from iotas (pallas
    forbids captured constant arrays).
  - no rev primitive (flat reversal = row-order concat + lane-reverse
    matmul); no select between i1 vectors (use boolean algebra); no
    uint32<->bfloat16 casts (route through int32/float32).

In interpret mode (CPU tests) the same windowed body runs with direct
ref loads instead of DMA — the generic interpreter does not model
Mosaic's memory spaces.

Gated by PEGASUS_PALLAS (default OFF; =1 enables). The only LOGGED
hardware session (TPU_SESSION.log 13:49) shows the pre-rework kernel
failing Mosaic lowering; the rework claims hardware byte-equality but
was never re-logged, so the default stays off until a recorded session
proves it (VERDICT-r3 weak 4). bench.py's TPU lane trials the kernel
self-validatingly — byte-equality asserted against the XLA lane's
output — and reports it only when it lowers, matches, and wins.
Correctness is pinned against device_sort.merge_two_sorted by
tests/test_pallas_merge.py (interpret mode) and by the on-hardware
byte-equality stage of tools/tpu_session.py.

Reference seam: the comparator loop inside RocksDB CompactRange
(reference src/server/pegasus_server_impl.cpp:2814-2891).
"""

import functools
import os

import numpy as np

from .device_sort import _partner_concat, lex_cmp


def pallas_enabled() -> bool:
    """Default OFF (see module docstring: the last logged hardware run
    failed Mosaic lowering; flip only with a logged proof).
    PEGASUS_PALLAS=1/0 forces either way."""
    return os.environ.get("PEGASUS_PALLAS") == "1"


CHUNK = 2048   # output rows per program
LANES = 128
TILE = 1024    # Mosaic 1D i32 VMEM tiling: DMA offsets must be multiples
WINDOW = CHUNK + TILE          # elements DMA'd per side per program
MERGE_ROWS = (4 * CHUNK) // LANES  # 2*WINDOW padded up to pow2, in rows
HALF_ROWS = CHUNK // LANES     # rows in one output chunk
WIN_ROWS = WINDOW // LANES


def _lex_less_at(cols_a, ia, cols_b, ib):
    """Strict a[ia] < b[ib], vectorized over index arrays (jnp)."""
    import jax.numpy as jnp

    less = jnp.zeros(ia.shape, dtype=bool)
    eq = jnp.ones(ia.shape, dtype=bool)
    for ca, cb in zip(cols_a, cols_b):
        va = jnp.take(ca, ia, mode="clip")
        vb = jnp.take(cb, ib, mode="clip")
        less = less | (eq & (va < vb))
        eq = eq & (va == vb)
    return less


def _diagonal_splits(a_cols, b_cols, nk, n_chunks):
    """ai[p] for output diagonals d = p*CHUNK (bi = d - ai). Standard
    merge-path binary search on the cross-diagonal predicate."""
    import jax.numpy as jnp

    la = a_cols[0].shape[0]
    lb = b_cols[0].shape[0]
    d = jnp.arange(n_chunks, dtype=jnp.int32) * CHUNK
    lo = jnp.maximum(0, d - lb)
    hi = jnp.minimum(d, la)
    # invariant: the split ai is the count of A-elements among the first d
    # of the merged order = |{i : A[i] < B[d-1-i]}| along the diagonal;
    # binary search the monotone predicate A[mid] < B[d-1-mid]
    steps = max(1, int(np.ceil(np.log2(max(2, min(la, lb) + 1)))) + 1)
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        take_a = _lex_less_at(a_cols[:nk], mid, b_cols[:nk], d - 1 - mid)
        lo = jnp.where(active & take_a, mid + 1, lo)
        hi = jnp.where(active & ~take_a, mid, hi)
    return lo  # == hi


def _lane_permute(c, perm_of_lane):
    """Apply out[.., l] = c[.., p] where perm_of_lane(p) == l, via the
    MXU: multiply by the 128x128 one-hot permutation built in-kernel from
    iotas, u32 split into u8 quarters so bf16 accumulation is exact.
    Mosaic has no uint32<->bfloat16 casts: quarters route through int32
    (bitcast; values 0..255) -> f32 -> bf16, and the f32 matmul result
    back through int32."""
    import jax.numpy as jnp
    from jax import lax

    pr = lax.broadcasted_iota(jnp.uint32, (LANES, LANES), 0)
    pc = lax.broadcasted_iota(jnp.uint32, (LANES, LANES), 1)
    one = jnp.ones((LANES, LANES), jnp.float32)
    p = jnp.where(pr == perm_of_lane(pc), one, 0.0).astype(jnp.bfloat16)
    bits = lax.bitcast_convert_type(c, jnp.uint32)
    out = None
    for s in (0, 8, 16, 24):
        q = (bits >> s) & jnp.uint32(0xFF)
        qf = lax.bitcast_convert_type(q, jnp.int32).astype(
            jnp.float32).astype(jnp.bfloat16)
        sq = lax.dot(qf, p, preferred_element_type=jnp.float32)
        sq = lax.bitcast_convert_type(sq.astype(jnp.int32), jnp.uint32) << s
        out = sq if out is None else out | sq
    return lax.bitcast_convert_type(out, c.dtype)


def _lane_partner(c, j):
    """Partner copy at lane distance j (< 128): XOR-j lane permutation."""
    import jax.numpy as jnp

    return _lane_permute(c, lambda l: l ^ jnp.uint32(j))


def _flat_reverse(c, rows):
    """Reverse a (rows, LANES) buffer in FLAT element order (k -> L-1-k):
    reverse the row order (concat of row slices — Mosaic has no rev
    primitive) then reverse within lanes (one-hot permutation matmul)."""
    import jax.numpy as jnp

    if rows > 1:
        c = jnp.concatenate([c[r : r + 1] for r in range(rows - 1, -1, -1)],
                            axis=0)
    return _lane_permute(c, lambda l: jnp.uint32(LANES - 1) - l)


def _merge_2d(cols, nk, rows):
    """Bitonic merge of a (rows, LANES) bitonic buffer, flat order
    k = row*LANES + lane, ascending in the first nk columns."""
    import jax.numpy as jnp
    from jax import lax

    rows_iota = lax.broadcasted_iota(jnp.uint32, (rows, LANES), 0)
    lanes_iota = lax.broadcasted_iota(jnp.uint32, (rows, LANES), 1)
    j = (rows * LANES) // 2
    while j >= 1:
        if j >= LANES:
            # row-block swap at distance jr: _partner_concat slices the
            # leading axis, so it works unchanged on the (rows, LANES)
            # layout (and avoids tiny-dim reshapes Mosaic lowers poorly)
            jr = j // LANES
            is_high = (rows_iota & jnp.uint32(jr)) != 0
            px = [_partner_concat(c, jr) for c in cols]
        else:
            is_high = (lanes_iota & jnp.uint32(j)) != 0
            px = [_lane_partner(c, j) for c in cols]
        p_lt, p_eq = lex_cmp(px[:nk], cols[:nk])
        p_gt = ~p_lt & ~p_eq
        # boolean algebra, not where(): Mosaic cannot select between i1
        # vectors (i8->i1 trunci is unsupported)
        take_p = (is_high & p_gt) | (~is_high & p_lt)
        cols = [jnp.where(take_p, pc, c) for c, pc in zip(cols, px)]
        j //= 2
    return cols


@functools.lru_cache(maxsize=64)
def _compiled_merge(la, lb, n_ops, nk, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    L_out = la + lb
    n_chunks = -(-L_out // CHUNK)

    def kernel(al_ref, bl_ref, fill_ref, *refs):
        p = pl.program_id(0)
        a_refs = refs[:n_ops]
        b_refs = refs[n_ops : 2 * n_ops]
        out_refs = refs[2 * n_ops : 3 * n_ops]
        # al/bl hold ROW offsets (elements // LANES), multiples of
        # TILE // LANES = 8 — exactly the (8, 128) VMEM tile row count
        ar0 = pl.multiple_of(al_ref[p], TILE // LANES)
        br0 = pl.multiple_of(bl_ref[p], TILE // LANES)
        if interpret:
            a_cols = [ar[pl.ds(ar0, WIN_ROWS)] for ar in a_refs]
            b_cols = [br[pl.ds(br0, WIN_ROWS)] for br in b_refs]
        else:
            from jax.experimental.pallas import tpu as pltpu

            scratch = refs[3 * n_ops : 5 * n_ops]
            sem = refs[5 * n_ops]
            copies = []
            for i in range(n_ops):
                copies.append(pltpu.make_async_copy(
                    a_refs[i].at[pl.ds(ar0, WIN_ROWS)], scratch[i],
                    sem.at[2 * i]))
                copies.append(pltpu.make_async_copy(
                    b_refs[i].at[pl.ds(br0, WIN_ROWS)],
                    scratch[n_ops + i], sem.at[2 * i + 1]))
            for c in copies:
                c.start()
            for c in copies:
                c.wait()
            a_cols = [s[...] for s in scratch[:n_ops]]
            b_cols = [s[...] for s in scratch[n_ops : 2 * n_ops]]
        # bitonic input: A window ascending, pad fill (sorts last), B
        # window reversed in flat order — pow2 total of MERGE_ROWS rows
        pad_rows = MERGE_ROWS - 2 * WIN_ROWS
        cols = []
        for i, (a, b) in enumerate(zip(a_cols, b_cols)):
            fill = jnp.full((pad_rows, LANES), fill_ref[i], a.dtype)
            cols.append(jnp.concatenate(
                [a, fill, _flat_reverse(b, WIN_ROWS)], axis=0))
        cols = _merge_2d(cols, nk, MERGE_ROWS)
        # delta = d - al - bl is 0 or TILE (see module docstring): the
        # output chunk is one of two static row windows
        delta_rows = jnp.int32(p) * HALF_ROWS - ar0 - br0
        hi = delta_rows > 0
        for out_ref, c in zip(out_refs, cols):
            lo_w = c[:HALF_ROWS]
            hi_w = c[TILE // LANES : TILE // LANES + HALF_ROWS]
            out_ref[...] = jnp.where(hi, hi_w, lo_w)

    def row_pad(c, f):
        """Pad so every aligned WINDOW row-range is in bounds, rounded up
        to whole LANES rows, and reshape to (rows, LANES)."""
        import jax.numpy as jnp

        n = c.shape[0]
        total = -(-(n + WINDOW) // LANES) * LANES
        return jnp.concatenate(
            [c, jnp.full((total - n,), f, c.dtype)]).reshape(-1, LANES)

    def fn(a_ops, b_ops, pad_fill):
        # pads sort last and merge-path never assigns them a real chunk
        a_pad = [row_pad(c, f) for c, f in zip(a_ops, pad_fill)]
        b_pad = [row_pad(c, f) for c, f in zip(b_ops, pad_fill)]
        ai = _diagonal_splits(a_ops, b_ops, nk, n_chunks)
        bi = jnp.arange(n_chunks, dtype=jnp.int32) * CHUNK - ai
        # row offsets of the tile-aligned windows
        al = ((ai // TILE) * TILE) // LANES
        bl = ((bi // TILE) * TILE) // LANES
        # per-column pad fill as an SMEM input; i32 bit patterns (the
        # kernel's jnp.full converts back to each column dtype, wrapping)
        fills = jnp.stack(
            [jnp.asarray(f).astype(jnp.int32) for f in pad_fill])

        out_shapes = [
            jax.ShapeDtypeStruct((n_chunks * HALF_ROWS, LANES), c.dtype)
            for c in a_ops
        ]
        out_specs = [
            pl.BlockSpec((HALF_ROWS, LANES), lambda p: (p, 0))
            for _ in a_ops
        ]
        if interpret:
            in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * (3 + 2 * n_ops)
            scratch_shapes = []
        else:
            from jax.experimental.pallas import tpu as pltpu

            in_specs = (
                [pl.BlockSpec(memory_space=pltpu.SMEM)] * 3
                + [pl.BlockSpec(memory_space=pl.ANY)] * (2 * n_ops)
            )
            scratch_shapes = (
                [pltpu.VMEM((WIN_ROWS, LANES), c.dtype) for c in a_ops] * 2
                + [pltpu.SemaphoreType.DMA((2 * n_ops,))]
            )
        merged = pl.pallas_call(
            kernel,
            grid=(n_chunks,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            scratch_shapes=scratch_shapes,
            interpret=interpret,
        )(al, bl, fills, *a_pad, *b_pad)
        return [m.reshape(-1)[:L_out] for m in merged]

    return jax.jit(fn)


def merge_two_sorted_pallas(a_ops, b_ops, nk, pad_fill):
    """Drop-in for device_sort.merge_two_sorted (returns exactly la+lb rows,
    ascending; same strict-total-order requirement on the key columns)."""
    import jax

    la = int(a_ops[0].shape[0])
    lb = int(b_ops[0].shape[0])
    interpret = jax.default_backend() != "tpu"
    fn = _compiled_merge(la, lb, len(a_ops), nk, interpret)
    return fn(tuple(a_ops), tuple(b_ops), tuple(pad_fill))
