"""Tier-2 merge kernel: merge-path chunking + whole-merge-in-VMEM Pallas.

The XLA networks in ops.device_sort materialize every compare-exchange
stage in HBM: a merge of length L costs ~log2(L) full passes (~24 at 16M).
This kernel cuts that to ~2 HBM passes: the classic GPU "merge path"
decomposition splits the output into fixed-size chunks along cross
diagonals of the merge matrix, and a Pallas program per chunk loads its
two input slices into VMEM, runs the ENTIRE bitonic merge there, and
writes its finished output chunk once.

  1. diagonal search (plain jnp, outside the kernel): for each output
     position d = p*CHUNK, binary-search the split (ai, bi), ai+bi=d, such
     that A[ai-1] < B[bi] and B[bi-1] < A[ai] in the strict lexicographic
     column order (keys are unique by construction — the packed
     klen<<8|prio column differs across runs).
  2. pallas_call over grid=(P,): program p loads A[ai : ai+CHUNK] and
     B[bi : bi+CHUNK] (padded loads; merge-path guarantees an output chunk
     consumes at most CHUNK from each side), merges 2*CHUNK elements in
     VMEM via the same compare-exchange stages as ops.device_sort, and
     stores the first CHUNK — exactly out[d : d+CHUNK].

Gated OFF by default (PEGASUS_PALLAS=1 enables): Mosaic lowering has not
been validated on real TPU hardware in this environment (the tunnel was
down); correctness is pinned against merge_two_sorted by interpret-mode
tests (tests/test_pallas_merge.py) on the CPU mesh.
"""

import functools
import os

import numpy as np

from .device_sort import _exchange


def pallas_enabled() -> bool:
    return os.environ.get("PEGASUS_PALLAS", "0") == "1"


CHUNK = 2048  # output rows per program; 2*CHUNK*cols*4B stays well in VMEM


def _lex_less_at(cols_a, ia, cols_b, ib):
    """Strict a[ia] < b[ib], vectorized over index arrays (jnp)."""
    import jax.numpy as jnp

    less = jnp.zeros(ia.shape, dtype=bool)
    eq = jnp.ones(ia.shape, dtype=bool)
    for ca, cb in zip(cols_a, cols_b):
        va = jnp.take(ca, ia, mode="clip")
        vb = jnp.take(cb, ib, mode="clip")
        less = less | (eq & (va < vb))
        eq = eq & (va == vb)
    return less


def _diagonal_splits(a_cols, b_cols, nk, n_chunks):
    """ai[p] for output diagonals d = p*CHUNK (bi = d - ai). Standard
    merge-path binary search on the cross-diagonal predicate."""
    import jax.numpy as jnp

    la = a_cols[0].shape[0]
    lb = b_cols[0].shape[0]
    d = jnp.arange(n_chunks, dtype=jnp.int32) * CHUNK
    lo = jnp.maximum(0, d - lb)
    hi = jnp.minimum(d, la)
    # invariant: the split ai is the count of A-elements among the first d
    # of the merged order = |{i : A[i] < B[d-1-i]}| along the diagonal;
    # binary search the monotone predicate A[mid] < B[d-1-mid]
    steps = max(1, int(np.ceil(np.log2(max(2, min(la, lb) + 1)))) + 1)
    for _ in range(steps):
        active = lo < hi
        mid = (lo + hi) // 2
        take_a = _lex_less_at(a_cols[:nk], mid, b_cols[:nk], d - 1 - mid)
        lo = jnp.where(active & take_a, mid + 1, lo)
        hi = jnp.where(active & ~take_a, mid, hi)
    return lo  # == hi


@functools.lru_cache(maxsize=64)
def _compiled_merge(la, lb, n_ops, nk, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    L_out = la + lb
    n_chunks = -(-L_out // CHUNK)

    def fn(a_ops, b_ops, pad_fill):
        # pad inputs so every CHUNK-window load is in bounds; pads sort last
        # and merge-path never assigns them to a real output chunk
        a_pad = [jnp.concatenate([c, jnp.full((CHUNK,), f, c.dtype)])
                 for c, f in zip(a_ops, pad_fill)]
        b_pad = [jnp.concatenate([c, jnp.full((CHUNK,), f, c.dtype)])
                 for c, f in zip(b_ops, pad_fill)]
        ai = _diagonal_splits(a_ops, b_ops, nk, n_chunks)
        bi = jnp.arange(n_chunks, dtype=jnp.int32) * CHUNK - ai

        # split points + full-array refs with manual dynamic slicing keeps
        # the spec simple across pallas versions
        grid = (n_chunks,)

        def kernel(ai_ref, bi_ref, *refs):
            p = pl.program_id(0)
            a_refs = refs[:n_ops]
            b_refs = refs[n_ops : 2 * n_ops]
            out_refs = refs[2 * n_ops :]
            a0 = ai_ref[p]
            b0 = bi_ref[p]
            cols = []
            for ar, br in zip(a_refs, b_refs):
                a = ar[pl.ds(a0, CHUNK)]
                b = br[pl.ds(b0, CHUNK)]
                cols.append(jnp.concatenate([a, b[::-1]]))
            from jax import lax

            L = 2 * CHUNK
            iota = lax.iota(jnp.uint32, L)
            j = L // 2
            while j >= 1:
                is_high = (iota & jnp.uint32(j)) != 0
                cols = _exchange(cols, nk, j, is_high, mxu=False)
                j //= 2
            for out_ref, c in zip(out_refs, cols):
                out_ref[pl.ds(p * CHUNK, CHUNK)] = c[:CHUNK]

        out_shapes = [jax.ShapeDtypeStruct((n_chunks * CHUNK,), c.dtype)
                      for c in a_ops]
        merged = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (2 + 2 * n_ops),
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_ops,
            out_shape=out_shapes,
            interpret=interpret,
        )(ai, bi, *a_pad, *b_pad)
        return [m[:L_out] for m in merged]

    return jax.jit(fn)


def merge_two_sorted_pallas(a_ops, b_ops, nk, pad_fill):
    """Drop-in for device_sort.merge_two_sorted (returns exactly la+lb rows,
    ascending; same strict-total-order requirement on the key columns)."""
    import jax

    la = int(a_ops[0].shape[0])
    lb = int(b_ops[0].shape[0])
    interpret = jax.default_backend() != "tpu"
    fn = _compiled_merge(la, lb, len(a_ops), nk, interpret)
    return fn(tuple(a_ops), tuple(b_ops), tuple(pad_fill))
