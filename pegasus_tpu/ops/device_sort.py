"""Bitonic merge/sort networks shaped for the TPU memory system.

XLA's native `lax.sort` is unusable here: its TPU lowering unrolls per
element and did not finish compiling a [64, 16384] sort in minutes on v5e.
So the engine emits its own compare-exchange networks as O(log n) /
O(log^2 n) vectorized stages. What makes this file different from a
textbook bitonic sort is that every stage is chosen for how it maps onto
the TPU's (8, 128) tiled memory and compute units, measured on chip:

- Every materialized buffer is FLAT [L]. Round 1 reshaped stages to
  [blocks, 2, j], whose tiny minor dims tile-pad up to 64x and OOM'd HBM
  at 2M records (BENCH_r01). Here the partner operand is produced as a
  flat permuted copy and the compare/select runs full-length elementwise,
  so nothing padded is ever materialized.
- Exchange distance j < 128 (intra-lane) is done on the MXU: a 128x128
  XOR-permutation matrix applied by matmul, with u32 values split into
  u8 quarters so bf16 accumulation is exact. Measured 1.9 ms/stage at
  8M rows x 9 columns (318 GB/s) vs 174 ms for the strided-reshape form.
- Mid-range j uses the strided-reshape partner copy (130-195 GB/s).
- Huge j (fewer than 8 blocks) uses explicit flat slice+concat, which
  lowers to large contiguous copies instead of sublane-padded reshapes.

The networks sort lexicographically by the first `nk` columns (uint32,
most significant first) and carry the remaining columns as payload.
Compaction inputs are already-sorted runs, so the hot path is
`merge_network` — log2(L) stages — not the full log^2 sort; the full
`sort_network` exists for unsorted single runs (memtable flush).

Reference seam: this replaces the comparator loop inside RocksDB
compaction/flush (reference src/server/pegasus_server_impl.cpp:2814
CompactRange; rocksdb memtable sort) with batched device passes.
"""

import functools

import numpy as np

_MXU_MIN_L = 1024  # below this, strided reshapes are cheap enough


def lex_less(a_cols, b_cols):
    """Strict lexicographic a < b over uint32 column lists, vectorized."""
    return lex_cmp(a_cols, b_cols)[0]


def lex_cmp(a_cols, b_cols):
    """(a < b, a == b) lexicographic over uint32 column lists, vectorized.

    Seeded from the first column's comparison rather than boolean constant
    arrays: Mosaic (pallas TPU) cannot materialize i1 vector constants
    (i8->i1 trunci is unsupported), and this form is equivalent."""
    less = a_cols[0] < b_cols[0]
    eq = a_cols[0] == b_cols[0]
    for a, b in zip(a_cols[1:], b_cols[1:]):
        less = less | (eq & (a < b))
        eq = eq & (a == b)
    return less, eq


@functools.lru_cache(maxsize=16)
def _perm_matrix(j: int):
    """128x128 one-hot XOR-j permutation, exact in bf16."""
    p = np.zeros((128, 128), np.float32)
    for k in range(128):
        p[k, k ^ j] = 1.0
    return p


def _partner_mxu(c, j):
    """Partner copy for j < 128 via MXU matmul. u32 split into u8 quarters:
    one-hot rows make each output a single u8 term, exact in bf16."""
    import jax.numpy as jnp
    from jax import lax

    p = jnp.asarray(_perm_matrix(j), dtype=jnp.bfloat16)
    bits = lax.bitcast_convert_type(c, jnp.uint32)
    x = bits.reshape(-1, 128)
    out = None
    for s in (0, 8, 16, 24):
        q = ((x >> s) & jnp.uint32(0xFF)).astype(jnp.bfloat16)
        sq = lax.dot(q, p).astype(jnp.uint32) << s
        out = sq if out is None else out | sq
    return lax.bitcast_convert_type(out.reshape(c.shape), c.dtype)


def _partner_reshape(c, j):
    """Partner copy via [blocks, 2, j] axis flip; flat in/out buffers."""
    L = c.shape[0]
    return c.reshape(L // (2 * j), 2, j)[:, ::-1, :].reshape(L)


def _partner_concat(c, j):
    """Partner copy via explicit flat slice swaps (for <8 blocks: the
    reshape form would sublane-pad; contiguous copies don't)."""
    import jax.numpy as jnp

    L = c.shape[0]
    parts = []
    for b in range(L // (2 * j)):
        lo, hi = 2 * b * j, (2 * b + 1) * j
        parts.append(c[hi : hi + j])
        parts.append(c[lo:hi])
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _partner(c, j, mxu=True):
    L = c.shape[0]
    if mxu and j < 128 and L >= _MXU_MIN_L and _on_tpu():
        # intra-lane exchange: only worth the matmul machinery where lane
        # padding exists; on CPU the strided reshape is cheap and compiles
        # far faster
        return _partner_mxu(c, j)
    if L // (2 * j) < 8:
        return _partner_concat(c, j)
    return _partner_reshape(c, j)


def _exchange(cols, nk, j, flip, mxu=True):
    """One compare-exchange stage at distance j. flip = is_high ^ is_desc.
    Comparisons are strict both ways so equal pairs stay put (a non-strict
    form would copy one element over both slots, corrupting payloads).
    mxu=False forces the reshape/concat partner forms (used inside Pallas
    kernels, where data is already VMEM-resident)."""
    import jax.numpy as jnp

    px = [_partner(c, j, mxu=mxu) for c in cols]
    p_lt, p_eq = lex_cmp(px[:nk], cols[:nk])
    p_gt = ~p_lt & ~p_eq
    take_p = jnp.where(flip, p_gt, p_lt)
    return [jnp.where(take_p, pc, c) for c, pc in zip(cols, px)]


def merge_network(cols, nk):
    """Sort a BITONIC input (ascending run then descending run) ascending.

    log2(L) stages. This is the compaction hot path: two sorted runs
    become bitonic via concat(A, reverse(B)) (pad in the middle stays
    bitonic). L must be a power of two."""
    from jax import lax

    L = cols[0].shape[0]
    if L & (L - 1):
        raise ValueError(f"merge_network needs power-of-two length, got {L}")
    iota = lax.iota(np.uint32, L)
    j = L // 2
    while j >= 1:
        is_high = (iota & np.uint32(j)) != 0
        cols = _exchange(cols, nk, j, is_high)
        j //= 2
    return cols


def sort_network(cols, nk):
    """Full bitonic sort, ascending. log2(L)*(log2(L)+1)/2 stages; used for
    unsorted single runs (flush). L must be a power of two."""
    from jax import lax

    L = cols[0].shape[0]
    if L & (L - 1):
        raise ValueError(f"sort_network needs power-of-two length, got {L}")
    if L == 1:
        return list(cols)
    iota = lax.iota(np.uint32, L)
    k = 2
    while k <= L:
        is_desc = (iota & np.uint32(k)) != 0 if k < L else None
        j = k // 2
        while j >= 1:
            is_high = (iota & np.uint32(j)) != 0
            flip = is_high if is_desc is None else is_high ^ is_desc
            cols = _exchange(cols, nk, j, flip)
            j //= 2
        k *= 2
    return cols


def merge_two_sorted(a_cols, b_cols, nk, pad_fill):
    """Merge two ascending-sorted column sets into one ascending set of
    power-of-two length >= la + lb. Padding (pad_fill per column, which must
    sort after all real rows) is inserted between the ascending and the
    reversed descending half, which preserves bitonicity; pads sort to the
    tail. Returns padded merged columns (caller trims to la + lb)."""
    import jax.numpy as jnp

    la, lb = a_cols[0].shape[0], b_cols[0].shape[0]
    L = 1
    while L < la + lb:
        L <<= 1
    npad = L - la - lb
    merged = []
    for a, b, fill in zip(a_cols, b_cols, pad_fill):
        mid = jnp.full((npad,), fill, dtype=a.dtype)
        merged.append(jnp.concatenate([a, mid, b[::-1]]))
    return merge_network(merged, nk)
