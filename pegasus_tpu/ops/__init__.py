from .packing import pack_key_prefixes, compute_suffix_ranks, DEFAULT_PREFIX_U32
from .compact import CompactOptions, CompactResult, compact_blocks, sort_block, get_backend
from .pipeline import CompactPipeline, pipeline_depth

__all__ = [
    "pack_key_prefixes",
    "compute_suffix_ranks",
    "DEFAULT_PREFIX_U32",
    "CompactOptions",
    "CompactResult",
    "compact_blocks",
    "sort_block",
    "get_backend",
    "CompactPipeline",
    "pipeline_depth",
]
