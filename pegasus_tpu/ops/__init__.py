from .packing import pack_key_prefixes, compute_suffix_ranks, DEFAULT_PREFIX_U32
from .compact import CompactOptions, CompactResult, compact_blocks, sort_block, get_backend
from .device_lookup import build_fence_index, lookup_batch
from .pipeline import CompactPipeline, pipeline_depth

__all__ = [
    "build_fence_index",
    "lookup_batch",
    "pack_key_prefixes",
    "compute_suffix_ranks",
    "DEFAULT_PREFIX_U32",
    "CompactOptions",
    "CompactResult",
    "compact_blocks",
    "sort_block",
    "get_backend",
    "CompactPipeline",
    "pipeline_depth",
]
