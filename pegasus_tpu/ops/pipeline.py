"""Bounded double-buffered compaction pipeline executor.

Every compaction path used to pay ``sum(pack + h2d + device + gather +
sst_write)`` per range/level even though the stages run on disjoint
resources (host CPU, PCIe/tunnel, device, host memcpy, disk). LUDA
(arXiv 2004.03054) shows device-offloaded LSM compaction only wins when
the CPU-side stages are pipelined against device work; RESYSTANCE
(arXiv 2603.05162) shows serialized compaction stages leave large
fractions of the hardware idle. This module is the one executor all
three serial loops thread through:

  - ``ops/compact.py::_compact_blockwise`` — while range *i* runs its
    device merge, range *i+1* packs/uploads on a host worker and range
    *i−1* gathers/post-filters on another;
  - ``engine/db.py`` — the SST write + manifest install of level output
    *k* overlaps the merge of *k+1* (deferred installs), and the
    flush-time device-residency prime rides the pool instead of the
    write path;
  - ``ops/batched_compact.py`` — the next partition batch's host
    stacking prefetches under the current batch's device dispatch.

Shape: ``map(items, prefetch, dispatch, finish)`` runs ``prefetch`` on a
shared host worker pool (``runtime/tasking.ThreadPool``), ``dispatch``
in the CALLING thread (device work — so a lane-guard wrapper around the
whole map keeps its deadline/abandon/fallback semantics, and a single
abandoned thread abandons the whole pipeline), and ``finish`` on a host
worker again. Depth is bounded (``PEGASUS_COMPACT_PIPELINE_DEPTH``,
default 2 = one in-flight prefetch) so HBM headroom per
``max_device_records`` is preserved: at most ``depth`` ranges are
resident at once. Depth 1 degenerates to the serial loop.

Failure contract: any stage error drains the pipeline (bounded waits on
in-flight workers — a wedged worker is abandoned, never joined forever)
and re-raises, so a lane-guard fallback reruns serially on CPU against
quiesced workers. The ``compact.pipeline`` fail point fires in every
pool task for chaos coverage.

Counters (process registry -> /metrics, perf-counters*, collector):
  compact.pipeline.depth                                  gauge
  compact.pipeline.overlap_us / stall_us                  percentile
  compact.pipeline.prefetch_count / drain_count           rate
Per-range overlap additionally lands in the stage-span ring buffer as
``pipeline.overlap`` events (visible in /compact/trace and session
summaries -> bench ``detail.trace``).
"""

import os
import threading
import time

from ..runtime import lockrank
from ..runtime.fail_points import inject as _inject
from ..runtime.perf_counters import counters
from ..runtime.tasking import ThreadPool
from ..runtime.tracing import COMPACT_TRACER as _TRACE

_DEPTH_ENV = "PEGASUS_COMPACT_PIPELINE_DEPTH"
_DEFAULT_DEPTH = 2


def pipeline_depth() -> int:
    """The bounded lookahead (read per call so tests can flip it): depth
    N keeps at most N ranges in flight — 2 = classic double buffering,
    1 = serial (the pipeline disengages)."""
    v = os.environ.get(_DEPTH_ENV)
    try:
        d = int(v) if v not in (None, "") else _DEFAULT_DEPTH
    except ValueError:
        d = _DEFAULT_DEPTH
    return max(1, d)


_POOL = None     #: guarded_by _POOL_LOCK
_IO_POOL = None  #: guarded_by _POOL_LOCK
_POOL_LOCK = lockrank.named_lock("pipeline.pool_global")


def pipeline_pool() -> ThreadPool:
    """The process-wide host-side stage pool shared by the blockwise
    pipeline, the batched prefetch and the async device primes. Fixed
    size (not depth-derived: the pool is created once; deeper configured
    pipelines share workers and queue, which bounds concurrency without
    silently capping correctness). Stages here may touch the DEVICE, so
    a wedge can occupy a worker — never put work a drain must wait on
    here (that is what install_pool is for)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPool("THREAD_POOL_COMPACT_PIPELINE",
                               worker_count=4)
        return _POOL


def install_pool() -> ThreadPool:
    """The engine's deferred-install pool: DISK-ONLY jobs (write_sst,
    manifest, unlinks) that drains wait on. Kept separate from
    pipeline_pool so wedged device work (primes, prefetch stages) can
    never starve an install job and hang flush/compact/close."""
    global _IO_POOL
    with _POOL_LOCK:
        if _IO_POOL is None:
            _IO_POOL = ThreadPool("THREAD_POOL_COMPACT_INSTALL",
                                  worker_count=2)
        return _IO_POOL


class PipelineFuture:
    """Result slot for one pool-side stage; records its execution window
    so overlap against device dispatch windows is computable."""

    __slots__ = ("_ev", "value", "error", "started", "ended")

    def __init__(self):
        self._ev = threading.Event()
        self.value = None
        self.error = None
        self.started = 0.0
        self.ended = 0.0

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout=None) -> bool:
        return self._ev.wait(timeout)

    def result(self):
        self._ev.wait()
        if self.error is not None:
            raise self.error
        return self.value

    def duration_s(self) -> float:
        return max(0.0, self.ended - self.started)


def submit(fn, *args, pool: ThreadPool = None):
    """Run ``fn(*args)`` on the pipeline pool (or an explicit pool) ->
    PipelineFuture. The worker adopts the submitting thread's trace
    sessions AND its active job context for the task (then restores its
    own: pool workers are reused, and a stale adopted session/job would
    aggregate later unrelated spans into a closed run — the job adopt is
    what lets a deferred install's hop land in the compaction job that
    queued it, ISSUE 16)."""
    from ..runtime.job_trace import JOB_TRACER

    fut = PipelineFuture()
    sessions = _TRACE.propagate_sessions()
    job_id = JOB_TRACER.current()

    def run():
        prev = _TRACE.propagate_sessions()
        _TRACE.adopt_sessions(sessions)
        fut.started = time.perf_counter()
        try:
            with JOB_TRACER.adopt(job_id):
                _inject("compact.pipeline")
                fut.value = fn(*args)
        except BaseException as e:  # noqa: BLE001 - crosses the thread boundary
            fut.error = e
        finally:
            fut.ended = time.perf_counter()
            _TRACE.adopt_sessions(prev)
            fut._ev.set()

    (pool or pipeline_pool()).enqueue(run)
    return fut


def submit_install(fn, *args):
    """submit() onto the disk-only install pool (see install_pool)."""
    return submit(fn, *args, pool=install_pool())


def _fut_interval(f):
    """(start, end) of a finished worker future; None if it never ran or
    is still running (a timed-out, abandoned prefetch)."""
    if f is None or f.started == 0.0 or f.ended == 0.0:
        return None
    return (f.started, f.ended)


def _overlap_len(interval, others) -> float:
    """Seconds of ``interval`` during which at least one of the other
    intervals was also executing — summed per other (two concurrent
    overlappers count twice: both are real work hidden behind this one)."""
    t0, t1 = interval
    return sum(max(0.0, min(t1, e) - max(t0, s)) for s, e in others)


class CompactPipeline:
    """One bounded pipelined run over a list of work items. Create one
    instance per run — all state is local, so an abandoned (deadline-
    exceeded) run can never corrupt a later one."""

    def __init__(self, depth: int = None, drain_timeout_s: float = 5.0,
                 prefetch_timeout_s: float = None):
        self.depth = pipeline_depth() if depth is None else max(1, depth)
        self.drain_timeout_s = drain_timeout_s
        # None = wait forever for a prefetch (callers whose WHOLE map runs
        # under a lane guard, which deadline-abandons the stalled thread).
        # A guard-less caller (batched compaction) sets a bound instead:
        # on timeout the wedged worker is abandoned and dispatch receives
        # a TimeoutError MARKER in place of the prefetched value, so its
        # own per-item guard can redo the work inline with fallback.
        self.prefetch_timeout_s = prefetch_timeout_s
        self.stall_s = 0.0
        self.overlap_s = 0.0
        self.drains = 0

    def map(self, items, prefetch, dispatch, finish=None) -> list:
        """For each item i: ``prefetch(item)`` on a pool worker (bounded
        lookahead = depth-1), ``dispatch(i, prefetched)`` in the calling
        thread, ``finish(i, dispatched)`` on a pool worker (at most
        ``depth`` unfinished). Returns the finish (or dispatch) results
        in item order. Any stage error drains in-flight workers (bounded)
        and re-raises."""
        n = len(items)
        counters.number("compact.pipeline.depth").set(self.depth)
        if self.depth <= 1 or n <= 1:
            out = []
            for i, item in enumerate(items):
                d = dispatch(i, prefetch(item))
                out.append(finish(i, d) if finish is not None else d)
            return out
        lookahead = self.depth - 1
        pref = [None] * n
        fin = [None] * n
        results = [None] * n
        windows = []
        t_start = time.perf_counter()
        try:
            for i in range(n):
                for j in range(i, min(n, i + lookahead + 1)):
                    if pref[j] is None:
                        pref[j] = submit(prefetch, items[j])
                        counters.rate(
                            "compact.pipeline.prefetch_count").increment()
                p = self._take(pref[i])
                t0 = time.perf_counter()
                d = dispatch(i, p)
                windows.append((t0, time.perf_counter()))
                if finish is None:
                    results[i] = d
                    continue
                k = i - self.depth
                if k >= 0:
                    self._wait(fin[k])
                fin[i] = submit(finish, i, d)
            if finish is not None:
                for i in range(n):
                    self._wait(fin[i])
                    results[i] = fin[i].result()
        except BaseException:
            self._drain(pref + fin)
            self.drains += 1
            counters.rate("compact.pipeline.drain_count").increment()
            raise
        self._account(windows, pref, fin, time.perf_counter() - t_start)
        return results

    def _wait(self, fut, timeout: float = None) -> None:
        if fut is None or fut.done():
            return
        t0 = time.perf_counter()
        # the open span makes a stalled pipeline attributable: a wedged
        # prefetch worker shows up as `pipeline.stall` in the lane
        # guard's abandon message and the watchdog's wedged_at_stage
        with _TRACE.span("pipeline.stall"):
            fut.wait(timeout)
        self.stall_s += time.perf_counter() - t0

    def _take(self, fut):
        """Pick a prefetch result up, bounded by prefetch_timeout_s: a
        timed-out worker is abandoned and a TimeoutError marker takes the
        value's place (never raised here — the dispatch stage decides)."""
        self._wait(fut, self.prefetch_timeout_s)
        if not fut.done():
            return TimeoutError(
                f"pipeline prefetch exceeded {self.prefetch_timeout_s:.1f}s;"
                " worker abandoned")
        return fut.result()

    def _drain(self, futures) -> None:
        """Quiesce in-flight workers before a serial rerun: bounded wait
        per future — a wedged worker is abandoned (its pool thread frees
        itself whenever the wedge clears), never joined forever."""
        deadline = time.monotonic() + self.drain_timeout_s
        for f in futures:
            if f is None or f.done():
                continue
            f.wait(max(0.0, deadline - time.monotonic()))

    def _account(self, windows, pref, fin, wall_s) -> None:
        futures = pref + fin
        stage_s = wall_s - self.stall_s  # caller-thread time in stages
        stage_s += sum(f.duration_s() for f in futures if f is not None)
        self.overlap_s = max(0.0, stage_s - wall_s)
        counters.percentile("compact.pipeline.overlap_us").set(
            int(self.overlap_s * 1e6))
        counters.percentile("compact.pipeline.stall_us").set(
            int(self.stall_s * 1e6))
        # per-range overlap events: the seconds range i's WORKER stages
        # (its prefetch + finish) executed concurrently with any OTHER
        # work — device dispatch windows or other ranges' workers. This
        # is the host time the pipeline actually hid for that range.
        all_iv = {id(f): _fut_interval(f) for f in futures if f is not None}
        for i in range(len(pref)):
            own = [f for f in (pref[i], fin[i] if i < len(fin) else None)
                   if f is not None and _fut_interval(f) is not None]
            if not own:
                continue
            own_ids = {id(f) for f in own}
            others = list(windows) + [iv for fid, iv in all_iv.items()
                                      if iv is not None
                                      and fid not in own_ids]
            ov = sum(_overlap_len(_fut_interval(f), others) for f in own)
            if ov > 0.0:
                _TRACE.event("pipeline.overlap", ov)
