"""Batched point lookups over HBM-resident SST key columns.

The compaction side of the LSM already lives on the device: flush and
compaction prime each run's packed key columns into HBM (`DeviceRun`,
ops/compact.py) and merge them there. This module serves the OTHER half
of the LSM from the same resident data (CompassDB's argument, PAPERS.md:
build the read index as a byproduct of compaction, exactly when the
sorted key column is already on the chip): `get`/`multi_get`/`batch_get`
point reads become one vmapped probe kernel per SST instead of a Python
binary search per key.

Two pieces:

  1. A per-SST FENCE-POINTER index (`build_fence_index`), computed on
     device from the already-resident sorted first key lane as a
     byproduct of the flush/compaction prime (pack_run_device): every
     `step`-th first-lane value is sampled into a small fence array.
     A query's two searchsorted probes against the fence bound its
     position to one `step`-sized block of the run — the CompassDB
     perfect-hash role, filled by the structure we get for free from
     sortedness. (A true minimal perfect hash over full keys needs a
     host pass over the key bytes; the fence needs nothing the chip
     does not already hold.)
  2. A batched lookup kernel (`lookup_batch`): queries are packed into
     the run's uint32 prefix lanes (the same packing the merge sort
     keys use — DeviceRun runs hold the FULL key in their lanes, so
     lane+klen equality IS full-key equality), fenced, then resolved
     with a fixed-depth vectorized binary search. Returns each query's
     row index in the run, or -1.
  3. A batched range kernel (`range_batch`): the same fence-bounded
     lower_bound run over a batch of (start, stop) bounds, resolving
     each range query to the run's contiguous row interval [lo, hi) in
     one dispatch — the device half of engine scan_range_batch
     (multi_get hash ranges, sortkey_count, scanner batches).

The kernel returns INDICES only; the host materializes values from the
SST's cached block exactly like the host binary search does, so the
device path is byte-identical to `SSTable.find` by construction. Every
batched probe runs under the read lane guard (runtime/lane_guard.py
READ_LANE_GUARD) from engine/db.py — deadline, retry, breaker, host
fallback — and fires the `read.device` fail point for chaos tests.
"""

import functools

import numpy as np

from ..runtime.fail_points import inject as _inject
from ..runtime.perf_counters import counters
from ..runtime.tracing import COMPACT_TRACER as _TRACE
from .compact import _pow2ceil
from .packing import pack_key_prefixes

_FENCE_MAX = 4096     # fence entries per run (16 KiB of HBM at the cap)
_QUERY_MIN_BUCKET = 8  # pad query batches to pow2 buckets >= this

# probe totals resolved once — this path fires per coalesced batch
_C_LOOKUPS = counters.number("read.device.lookup_count")
_C_KEYS = counters.number("read.device.keys")
_C_HITS = counters.number("read.device.hits")


def _fence_lower_bound(jnp, lex_less, padded_len, w, fence_len, steps,
                       cols, klen, fence, n, step, qcols, qklen):
    """Trace-time shared core of the point and range kernels: fence probe
    -> fixed-depth vectorized lower_bound over the full (prefix lanes,
    klen) sort key. Returns each query's lower_bound row index in [0, n]
    (n = every row < query). Runs hold the FULL key in their lanes
    (pack_run_device refuses otherwise), so lane/klen lex order IS byte
    order and the result matches SSTable.lower_bound exactly — including
    for queries LONGER than the 4*w-byte window: such a query's lane
    image ties only with rows that are proper byte prefixes of it, and
    the klen tiebreak orders those below the query, same as bytes."""
    q0 = qcols[0]
    # fence window: rows before sample a-1 are < q0, rows from sample
    # b on are > q0, so the full-key lower_bound lies in [lo, hi)
    a = jnp.searchsorted(fence, q0, side="left").astype(jnp.int32)
    b = jnp.searchsorted(fence, q0, side="right").astype(jnp.int32)
    n1 = n - 1
    lo = jnp.where(a > 0, jnp.minimum((a - 1) * step, n1), 0)
    hi = jnp.where(b < fence_len, jnp.minimum(b * step, n1), n)
    length = jnp.maximum(hi - lo, 0)
    qkey = list(qcols) + [qklen]
    for _ in range(steps):
        half = length >> 1
        mid = lo + half
        midc = jnp.minimum(mid, padded_len - 1)
        row = [jnp.take(cols[j], midc) for j in range(w)] \
            + [jnp.take(klen, midc)]
        less = lex_less(row, qkey)
        active = length > 0
        lo = jnp.where(active & less, mid + 1, lo)
        length = jnp.where(active,
                           jnp.where(less, length - half - 1, half),
                           0)
    return lo


@functools.lru_cache(maxsize=64)
def _compiled_fence_build(padded_len: int, fence_len: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fn(col0, n, step):
        pos = lax.iota(jnp.int32, fence_len) * step
        return jnp.take(col0, jnp.minimum(pos, n - 1))

    return jax.jit(fn)


def build_fence_index(dr) -> bool:
    """Attach the fence-pointer index to a DeviceRun in place (fields
    `fence`, `fence_step`, `fence_len`). Computed on device from the
    resident first key lane — the compaction/flush pass calls this right
    after the upload, so the index is a byproduct of work already done.
    Returns False (and leaves the run index-less, i.e. host-served) on
    any backend failure."""
    import jax.numpy as jnp

    if dr is None or dr.n == 0:
        return False
    fence_len = min(_FENCE_MAX, _pow2ceil(max(1, dr.n // 8), 16))
    step = -(-dr.n // fence_len)  # ceil: fence_len * step >= n
    try:
        fn = _compiled_fence_build(dr.padded_len, fence_len)
        dr.fence = fn(dr.cols[0], jnp.int32(dr.n), jnp.int32(step))
        dr.fence_step = step
        dr.fence_len = fence_len
        return True
    except Exception as e:  # noqa: BLE001 - an index-less run is just host-served
        print(f"[device-lookup] fence build failed: {e!r}", flush=True)
        dr.fence = None
        return False


@functools.lru_cache(maxsize=256)
def _compiled_lookup(padded_len: int, w: int, fence_len: int, qpad: int):
    """Jitted batched point lookup for one (run shape, query bucket):
    fence probe -> fixed-depth vectorized lower_bound over the full
    (prefix lanes, klen) sort key -> exact-equality check. Keyed on the
    padded bucket lengths only, so a live engine's varying run/batch
    sizes share programs (the compaction pipeline's recompile rule)."""
    import jax
    import jax.numpy as jnp

    from .device_sort import lex_less

    steps = max(1, padded_len.bit_length())

    def fn(cols, klen, fence, n, step, qcols, qklen):
        lo = _fence_lower_bound(jnp, lex_less, padded_len, w, fence_len,
                                steps, cols, klen, fence, n, step,
                                qcols, qklen)
        safe = jnp.minimum(lo, padded_len - 1)
        eq = lo < n
        for j in range(w):
            eq &= jnp.take(cols[j], safe) == qcols[j]
        eq &= jnp.take(klen, safe) == qklen
        return jnp.where(eq, lo, jnp.int32(-1))

    return jax.jit(fn)


def pack_queries(keys, w: int):
    """Host-side packing of query keys into a run's lane layout:
    -> (list of w uint32[qpad] lanes, uint32[qpad] klen), zero-padded to
    the pow2 query bucket. A query longer than the run's 4*w-byte window
    truncates in the lanes but keeps its true klen — it can never equal
    a resident key (all <= 4*w bytes), so the equality check still
    returns -1 for it, which is the correct answer."""
    n = len(keys)
    arena = np.frombuffer(b"".join(keys), dtype=np.uint8).copy() \
        if n else np.zeros(0, np.uint8)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int32, count=n)
    offs = np.zeros(n, dtype=np.int64)
    if n:
        np.cumsum(lens[:-1], out=offs[1:])
    pref = pack_key_prefixes(arena, offs, lens, w)
    qpad = _pow2ceil(max(1, n), _QUERY_MIN_BUCKET)
    qcols = []
    for j in range(w):
        col = np.zeros(qpad, np.uint32)
        col[:n] = pref[:, j]
        qcols.append(col)
    qklen = np.zeros(qpad, np.uint32)
    qklen[:n] = lens
    return qcols, qklen


def lookup_batch(dr, keys) -> np.ndarray:
    """Probe `keys` (list of full stored keys, any order) against one
    HBM-resident run. -> np.int32[len(keys)]: the run row index of each
    exact match, -1 for absent keys. Raises on device failure — the
    caller (engine/db.py get_batch) runs this under READ_LANE_GUARD with
    the host binary-search walk as the byte-identical fallback."""
    import jax.numpy as jnp

    if not keys or dr is None or dr.fence is None:
        return np.full(len(keys), -1, np.int32)
    with _TRACE.span("read.device", records=len(keys)):
        _inject("read.device")
        qcols, qklen = pack_queries(keys, dr.w)
        fn = _compiled_lookup(dr.padded_len, dr.w, dr.fence_len,
                              len(qklen))
        out = fn(tuple(dr.cols), dr.klen, dr.fence,
                 jnp.int32(dr.n), jnp.int32(dr.fence_step),
                 tuple(jnp.asarray(c) for c in qcols), jnp.asarray(qklen))
        rows = np.asarray(out)[: len(keys)]
    _C_LOOKUPS.increment()
    _C_KEYS.increment(len(keys))
    _C_HITS.increment(int((rows >= 0).sum()))
    return rows


@functools.lru_cache(maxsize=256)
def _compiled_range(padded_len: int, w: int, fence_len: int, qpad: int):
    """Jitted batched range resolve for one (run shape, query bucket):
    the point kernel's fence-bounded lower_bound run TWICE — once over
    the start keys, once over the stop keys — in one program, yielding
    each query's contiguous row interval [lo, hi). Keyed on the padded
    bucket lengths like _compiled_lookup so live sizes share programs."""
    import jax
    import jax.numpy as jnp

    from .device_sort import lex_less

    steps = max(1, padded_len.bit_length())

    def fn(cols, klen, fence, n, step, scols, sklen, tcols, tklen):
        lo = _fence_lower_bound(jnp, lex_less, padded_len, w, fence_len,
                                steps, cols, klen, fence, n, step,
                                scols, sklen)
        hi = _fence_lower_bound(jnp, lex_less, padded_len, w, fence_len,
                                steps, cols, klen, fence, n, step,
                                tcols, tklen)
        # a stop below the start (empty/inverted range) clamps to empty
        return jnp.stack([lo, jnp.maximum(hi, lo)])

    return jax.jit(fn)


def range_batch(dr, ranges) -> np.ndarray:
    """Resolve each (start_key, stop_key) query against one HBM-resident
    run: -> np.int32[(len(ranges), 2)], each row the run's contiguous
    row interval [lo, hi) holding exactly the keys in [start, stop).
    stop_key None means "to the end of the run". Both bounds resolve in
    ONE kernel dispatch and ONE coalesced download per run per batch.
    Raises on device failure — the caller (engine/db.py
    scan_range_batch) runs this under READ_LANE_GUARD with the host
    SSTable.lower_bound walk as the byte-identical fallback."""
    import jax.numpy as jnp

    nq = len(ranges)
    if not nq or dr is None or dr.fence is None:
        return np.zeros((nq, 2), np.int32)
    starts = [s for s, _ in ranges]
    stops = [(t if t is not None else b"") for _, t in ranges]
    open_stop = np.fromiter((t is None for _, t in ranges),
                            dtype=bool, count=nq)
    with _TRACE.span("read.range", records=nq):
        _inject("read.range")
        scols, sklen = pack_queries(starts, dr.w)
        tcols, tklen = pack_queries(stops, dr.w)
        fn = _compiled_range(dr.padded_len, dr.w, dr.fence_len, len(sklen))
        out = fn(tuple(dr.cols), dr.klen, dr.fence,
                 jnp.int32(dr.n), jnp.int32(dr.fence_step),
                 tuple(jnp.asarray(c) for c in scols), jnp.asarray(sklen),
                 tuple(jnp.asarray(c) for c in tcols), jnp.asarray(tklen))
        iv = np.asarray(out)[:, :nq].T.copy()
    # a None stop packed as b"" would lower_bound to 0; patch to run end
    iv[open_stop, 1] = dr.n
    return iv
