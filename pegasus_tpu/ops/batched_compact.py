"""Batched multi-partition compaction: many merges in ONE device dispatch.

A replica node hosts many partitions whose compactions are independent —
the reference runs them as separate RocksDB CompactRange jobs on a thread
pool (src/server/pegasus_server_impl.cpp manual-compact concurrency knob).
The TPU-native shape is different: vmap the cached-run merge pipeline over
a leading partition axis, so B same-bucket-shape partition compactions
cost ONE kernel launch (amortizing per-dispatch overhead — ~25 ms over a
tunnel, still tens of µs on a local host) and fill the chip at small
per-partition sizes.

Across a multi-chip `jax.sharding.Mesh` the batch axis shards over
devices (dp that MATCHES the partition→replica layout: each chip owns
whole partitions, no cross-chip exchange at all) — the complementary
strategy to parallel.sharded_compact's all_to_all hash routing, which
splits ONE oversized merge across chips.

Partitions are grouped by their shape signature (padded bucket lengths ×
run widths × w); each group is one dispatch. Within a group the per-run
device columns stack on axis 0 (HBM-to-HBM copies; the PCIe upload
already happened when the runs' DeviceRuns were born).
"""

import functools

import numpy as np

from ..runtime.fail_points import inject as _inject
from ..runtime.lane_guard import LANE_GUARD
from ..runtime.tracing import COMPACT_TRACER as _TRACE
from .compact import (CompactOptions, _make_cached_fn, apply_post_filters,
                      gather_device_survivors)


@functools.lru_cache(maxsize=128)
def _compiled_batched_pipeline(padded_lens: tuple, run_ws: tuple, w: int):
    """jit(vmap(cached pipeline)): leading axis = partition. Per-partition
    variation rides as batched args (real run lengths, pidx); table-wide
    knobs broadcast. Pallas is disabled under vmap (pallas_call batching
    is not wired up); the merge networks vmap natively."""
    import jax

    fn = _make_cached_fn(padded_lens, run_ws, w, allow_pallas=False)
    return jax.jit(jax.vmap(fn, in_axes=(0, 0, 0, None, 0, None, None, None)))


def _signature(device_runs):
    return (tuple(r.padded_len for r in device_runs),
            tuple(r.w for r in device_runs),
            max(r.w for r in device_runs))


def _stack_group(jobs):
    """jobs: list of (device_runs, pidx). -> vmapped arg tuple."""
    import jax.numpy as jnp

    K = len(jobs[0][0])
    cached = tuple(
        tuple(jnp.stack([job[0][i].cols[j] for job in jobs])
              for j in range(jobs[0][0][i].w))
        + (jnp.stack([job[0][i].klen for job in jobs]),)
        for i in range(K))
    aux = tuple(
        (jnp.stack([job[0][i].expire for job in jobs]),
         jnp.stack([job[0][i].deleted for job in jobs]),
         jnp.stack([job[0][i].hash32 for job in jobs]))
        for i in range(K))
    real_lens = jnp.asarray([[r.n for r in job[0]] for job in jobs],
                            jnp.int32)
    pidx = jnp.asarray([job[1] for job in jobs], jnp.uint32)
    return cached, aux, real_lens, pidx


def compact_partition_batch(jobs, opts: CompactOptions, mesh=None,
                            post_opts=None):
    """jobs: list of (runs: [KVBlock], device_runs: [DeviceRun], pidx).
    Every job's runs must be sorted and fully device-cached; all jobs in
    one call may have ANY shapes — they are grouped by signature here,
    one dispatch per group. -> list of output KVBlocks (job order).

    mesh: optional jax.sharding.Mesh. Groups whose job count is a
    MULTIPLE of the mesh size shard the batch axis across devices (pure
    dp: each chip compacts its partitions with zero collectives); other
    groups run single-device.

    post_opts: optional per-job CompactOptions for the HOST post passes
    (user rules, default_ttl) when jobs carry different app envs; the
    in-dispatch knobs (partition_mask, bottommost, filter) still come
    from `opts` and broadcast — callers must group jobs accordingly.

    Semantically identical to per-job compact_blocks(runs, opts,
    device_runs) with opts.pidx = job pidx — including the user-rule and
    default-TTL post passes (byte-equal; test-enforced). Groups chunk so
    one dispatch never stacks more than opts.max_device_records rows; a
    SINGLE job beyond that budget routes through compact_blocks, whose
    blockwise path range-decomposes it instead of OOMing one dispatch.

    Chunks pipeline (ops/pipeline.py): the next chunk's host stacking
    prefetches on a pool worker under the current chunk's device
    dispatch, bounded by PEGASUS_COMPACT_PIPELINE_DEPTH.
    """
    from .compact import compact_blocks
    from .pipeline import CompactPipeline

    now = opts.resolved_now()
    outs = [None] * len(jobs)
    groups = {}
    for j, (runs, device_runs, pidx) in enumerate(jobs):
        if not runs or any(d is None for d in device_runs):
            raise ValueError(f"job {j}: all runs must be device-cached")
        if sum(d.padded_len for d in device_runs) > opts.max_device_records:
            from dataclasses import replace

            job_opts = replace(post_opts[j] if post_opts else opts,
                               pidx=pidx, backend="tpu", runs_sorted=True)
            outs[j] = compact_blocks(runs, job_opts,
                                     device_runs=device_runs).block
            continue
        groups.setdefault(_signature(device_runs), []).append(j)
    chunks = []
    for sig, all_idxs in groups.items():
        padded_lens, run_ws, w = sig
        # device budget: one dispatch stacks B x sum(padded_lens) rows —
        # chunk the group rather than OOM HBM (compact_blocks' blockwise
        # guard, adapted to the batch axis)
        per_job = sum(padded_lens)
        max_b = max(1, int(opts.max_device_records // max(1, per_job)))
        if mesh is not None and max_b >= mesh.size:
            # keep chunks mesh-divisible, or the dp sharding silently
            # disengages for every chunk
            max_b -= max_b % mesh.size
        for chunk_at in range(0, len(all_idxs), max_b):
            chunks.append((sig, all_idxs[chunk_at:chunk_at + max_b]))

    def _prefetch(chunk):
        sig, idxs = chunk
        if LANE_GUARD.breaker_open(probe=False):
            # the guard will route this chunk straight to cpu — poking a
            # device the breaker has declared dead from an unguarded
            # worker would only wedge pool workers for nothing
            return RuntimeError("breaker open: prefetch skipped")
        try:
            return _stack_and_place(jobs, idxs, sig, mesh)
        except Exception as e:  # noqa: BLE001 - the guarded dispatch
            # re-stacks inline, so a stacking failure (device error, armed
            # fail point) flows into the lane guard's retry/fallback
            # policy instead of aborting the whole batch
            return e

    def _dispatch(i, prestacked):
        sig, idxs = chunks[i]
        if isinstance(prestacked, Exception):
            prestacked = None
        _run_group(jobs, idxs, sig, opts, now, mesh, outs, post_opts,
                   prestacked=prestacked)

    # this map runs OUTSIDE any lane guard (each chunk's _run_group has
    # its own), so prefetch pickup must be bounded: a wedged stacking
    # worker is abandoned at the lane deadline and the chunk re-stacks
    # inline under its guard — deadline/fallback/breaker all still apply.
    # deadline <= 0 means "deadline disabled": wait unbounded like the
    # guard would, never insta-timeout every prefetch
    eff = LANE_GUARD.effective_deadline_s()
    CompactPipeline(
        prefetch_timeout_s=(eff if eff and eff > 0 else None)
    ).map(chunks, _prefetch, _dispatch)
    return outs


def _stack_and_place(jobs, idxs, sig, mesh):
    """The chunk's "h2d" stage: stack the group's cached runs on the batch
    axis (+ the dp re-placement) — HBM-to-HBM copies (the PCIe upload
    already happened when the DeviceRuns were born), prefetchable on a
    pipeline worker under the previous chunk's device dispatch."""
    import jax

    padded_lens, _, _ = sig
    with _TRACE.span("h2d", records=len(idxs) * sum(padded_lens)):
        _inject("compact.h2d")
        cached, aux, real_lens, pidx_arr = _stack_group(
            [(jobs[j][1], jobs[j][2]) for j in idxs])
        if mesh is not None and len(idxs) % mesh.size == 0:
            from jax.sharding import NamedSharding, PartitionSpec

            axis = mesh.axis_names[0]

            def shard_batch(x):
                spec = PartitionSpec(axis, *([None] * (x.ndim - 1)))
                return jax.device_put(x, NamedSharding(mesh, spec))

            cached = jax.tree_util.tree_map(shard_batch, cached)
            aux = jax.tree_util.tree_map(shard_batch, aux)
            real_lens = shard_batch(real_lens)
            pidx_arr = shard_batch(pidx_arr)
    return cached, aux, real_lens, pidx_arr


def _run_group(jobs, idxs, sig, opts, now, mesh, outs, post_opts=None,
               prestacked=None):
    """One dispatch: stack the group's cached runs (or consume the
    pipeline's prefetched stack), run jit(vmap), gather + post-filter
    each row's survivors into outs[job]. The whole dispatch runs under
    the lane guard: a wedge/failure falls back to per-job cpu
    compactions (byte-identical by contract)."""

    def _device_group() -> dict:
        nonlocal prestacked
        import jax.numpy as jnp

        from ..engine.block import KVBlock

        padded_lens, run_ws, w = sig
        fn = _compiled_batched_pipeline(padded_lens, run_ws, w)
        if prestacked is not None:
            cached, aux, real_lens, pidx_arr = prestacked
            prestacked = None  # a retry re-stacks: the stack may be the fault
        else:
            cached, aux, real_lens, pidx_arr = _stack_and_place(
                jobs, idxs, sig, mesh)
        # np.asarray(counts) syncs on the whole batched dispatch
        with _TRACE.span("device", records=len(idxs) * sum(padded_lens)):
            _inject("compact.device")
            out_idx, counts = fn(cached, aux, real_lens, jnp.uint32(now),
                                 pidx_arr, jnp.uint32(opts.partition_mask),
                                 jnp.asarray(bool(opts.bottommost)),
                                 jnp.asarray(bool(opts.filter)))
            counts = np.asarray(counts)
        group_outs = {}
        for row, j in enumerate(idxs):
            runs = jobs[j][0]
            concat = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
            out = gather_device_survivors(concat, out_idx[row],
                                          int(counts[row]))
            group_outs[j] = apply_post_filters(
                out, post_opts[j] if post_opts else opts, now)
        return group_outs

    def _cpu_group() -> dict:
        from dataclasses import replace

        from .compact import compact_blocks

        group_outs = {}
        for j in idxs:
            runs, _, pidx = jobs[j]
            job_opts = replace(
                post_opts[j] if post_opts else opts,
                pidx=pidx, backend="cpu", runs_sorted=True, now=now,
                partition_mask=opts.partition_mask,
                bottommost=opts.bottommost, filter=opts.filter)
            group_outs[j] = compact_blocks(runs, job_opts).block
        return group_outs

    results = LANE_GUARD.run(_device_group, _cpu_group, op="batched_compact")
    for j, block in results.items():
        outs[j] = block
