"""Host-side packing: variable-length keys -> fixed-width device sort keys.

Device sorts need fixed-width keys. A stored key's first `4*W` bytes are
packed big-endian into W uint32 lanes, so unsigned u32 lexicographic order
over the lanes == byte order over the prefix (shorter keys zero-pad, and the
[u16 len] prefix of the key format guarantees a shorter hash_key never
zero-pad-collides with a longer one's real bytes except when one key is a
strict prefix of another — exactly the cases `compute_suffix_ranks` breaks).

The full device sort key is (prefix_lanes..., suffix_rank, key_len):

  - suffix_rank breaks ties between *long* keys (> window) sharing a prefix
    window: collision groups are found on host (rare — needs identical first
    4*W bytes), full keys compared within the group, and a dense rank
    assigned. Equal full keys share a rank, which the dedup kernel relies on.
  - key_len breaks the remaining ties exactly: two short keys with equal
    padded windows differ only in trailing 0x00 bytes (shorter is
    byte-smaller), and a short key whose window matches long keys is their
    strict byte prefix (sorts first; key_len < window bytes < long key_len).

So (window, rank, len) equality <=> full-key equality, and its order is full
byte order — no host comparisons outside collision groups.
"""

import json
import struct

import numpy as np

DEFAULT_PREFIX_U32 = 8  # 32-byte prefix window

# ---------------------------------------------------------------- run wire
# The pack/serialize boundary for shipping whole runs between processes
# (ISSUE 14 compaction offload): a KVBlock flattened to one deterministic
# byte string — tiny json header (column dtypes/shapes) + the raw column
# buffers in declaration order. Distinct from the SST file format on
# purpose: no bloom, no engine meta, no fsync — this is a TRANSFER form
# whose md5 is a content-address, not a storage format.

_RUN_MAGIC = b"PGRN1\n"
_RUN_COLUMNS = (
    ("key_arena", np.uint8), ("key_off", np.int64), ("key_len", np.int32),
    ("val_arena", np.uint8), ("val_off", np.int64), ("val_len", np.int32),
    ("expire_ts", np.uint32), ("hash32", np.uint32), ("deleted", np.bool_),
)


def pack_run_bytes(block) -> bytes:
    """One KVBlock -> deterministic wire bytes (same block, same bytes —
    the offload resume/dedup key is the md5 of this)."""
    cols = {}
    parts = []
    for name, dtype in _RUN_COLUMNS:
        arr = np.ascontiguousarray(getattr(block, name), dtype=dtype)
        raw = arr.tobytes()
        cols[name] = {"dtype": np.dtype(dtype).str, "shape": list(arr.shape),
                      "nbytes": len(raw)}
        parts.append(raw)
    hdr = json.dumps({"n": int(block.n), "cols": cols},
                     sort_keys=True).encode()
    return b"".join([_RUN_MAGIC, struct.pack("<I", len(hdr)), hdr] + parts)


def unpack_run_bytes(data: bytes):
    """Wire bytes -> KVBlock (inverse of pack_run_bytes)."""
    from ..engine.block import KVBlock

    if data[:len(_RUN_MAGIC)] != _RUN_MAGIC:
        raise ValueError("bad run wire magic")
    (hlen,) = struct.unpack_from("<I", data, len(_RUN_MAGIC))
    base = len(_RUN_MAGIC) + 4
    hdr = json.loads(data[base:base + hlen])
    off = base + hlen
    kwargs = {}
    for name, _ in _RUN_COLUMNS:
        sec = hdr["cols"][name]
        raw = data[off:off + sec["nbytes"]]
        if len(raw) != sec["nbytes"]:
            raise ValueError(f"truncated run wire column {name}")
        kwargs[name] = np.frombuffer(raw, dtype=np.dtype(sec["dtype"])) \
            .reshape(sec["shape"]).copy()
        off += sec["nbytes"]
    return KVBlock(**kwargs)


def pack_sbytes(prefix_cols, klen, rank=None):
    """Fixed-width big-endian byte string per record: (prefix cols..,
    [rank,] klen) -> numpy 'S' array whose memcmp order equals the device
    sort order (prio excluded — callers order equal keys by run priority).

    numpy 'S' comparison strips trailing NULs then compares
    lexicographically, which for equal itemsize is memcmp-equivalent
    (first differing byte decides either way; all-equal iff identical).
    """
    cols = list(prefix_cols) + ([rank] if rank is not None else []) + [klen]
    n = len(klen)
    packed = np.zeros((n, len(cols)), dtype=">u4")
    for i, c in enumerate(cols):
        packed[:, i] = c
    return packed.view(f"S{4 * len(cols)}").ravel()


def pack_key_prefixes(key_arena, key_off, key_len, width_u32: int = DEFAULT_PREFIX_U32):
    """-> uint32[n, width_u32], big-endian packed, zero-padded."""
    from .. import native

    n = len(key_off)
    w_bytes = width_u32 * 4
    if n == 0:
        return np.zeros((0, width_u32), np.uint32)
    if native.available():
        return native.pack_prefixes(key_arena, key_off, key_len, width_u32)
    pos = np.arange(w_bytes, dtype=np.int64)
    idx = key_off[:, None] + pos[None, :]
    valid = pos[None, :] < key_len[:, None]
    b = np.where(valid, key_arena[np.minimum(idx, len(key_arena) - 1)], 0).astype(np.uint32)
    b = b.reshape(n, width_u32, 4)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def compute_suffix_ranks(block, width_u32: int = DEFAULT_PREFIX_U32, prefixes=None):
    """-> uint32[n]: dense order rank among records sharing a prefix window.

    0 for records with a unique prefix (the common case: the loop below only
    touches collision groups). Equal full keys map to the same rank.
    """
    n = block.n
    ranks = np.zeros(n, np.uint32)
    over = np.nonzero(block.key_len > width_u32 * 4)[0]
    if len(over) == 0:
        return ranks
    if prefixes is None:
        prefixes = pack_key_prefixes(block.key_arena, block.key_off, block.key_len, width_u32)
    # only long keys need ranks: short-key ties are resolved by the key_len
    # sort column (see module docstring)
    groups = {}
    for i in over:
        groups.setdefault(prefixes[i].tobytes(), []).append(int(i))
    for g in groups.values():
        if len(g) < 2:
            continue
        keyed = sorted((block.key(i), i) for i in g)
        rank = 0
        prev = None
        for k, i in keyed:
            if prev is not None and k != prev:
                rank += 1
            ranks[i] = rank
            prev = k
    return ranks
