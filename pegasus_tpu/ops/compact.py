"""Sort / k-way merge / filter: the compaction_backend={cpu,tpu} kernels.

This is the TPU seam of the whole build (SURVEY.md §2.3, BASELINE.json): the
work RocksDB does record-at-a-time inside CompactRange — comparator sort,
level merge, TTL/version dedup filtering (reference:
src/server/key_ttl_compaction_filter.h:36-115, manual compact executor
src/server/pegasus_server_impl.cpp:2814) — runs here as batched kernels
over KVBlock columns:

  1. k-way merge of already-sorted runs into full byte order of stored
     keys, newest run first within equal keys. Compaction inputs are
     sorted (SSTs are written sorted), so both backends merge — they do
     not re-sort: the CPU backend computes the merge permutation with
     vectorized binary search (np.searchsorted per run pair), the TPU
     backend with log2(n)-stage bitonic merge networks (ops.device_sort).
  2. dedup: keep only the first (= newest) version of each key;
  3. filter: drop expired-TTL records, tombstones at the bottommost level,
     and keys no longer owned by this partition after a split.

Both backends implement identical semantics on the same total order, so
output SSTs are byte-stable across cpu/tpu — the determinism requirement
that lets learner checksums and backup digests agree (SURVEY.md §7 hard
part d). tests/test_compact_ops.py asserts byte equality, and bench.py
asserts it at bench scale.

The kernels return the survivor indices (into the concatenated input) in
sorted order. Variable-length key/value bytes never touch the device: the
host gathers arenas by those indices when writing the output SST.

Uniqueness contract: within one run, keys are unique (LSM invariant — a
memtable is a map, an SST is a deduped flush/compaction output). Across
runs, duplicates are expected and resolved newest-run-first.
"""

import functools
from dataclasses import dataclass, field

import numpy as np

from ..base.utils import epoch_now
from ..engine.block import KVBlock
from ..runtime.fail_points import inject as _inject
from ..runtime.tracing import COMPACT_TRACER as _TRACE
from .packing import DEFAULT_PREFIX_U32, compute_suffix_ranks, pack_key_prefixes, pack_sbytes

_U32_MAX = np.uint32(0xFFFFFFFF)
_MIN_BUCKET = 256  # pad runs to pow2 buckets >= this to bound jit recompiles


@dataclass
class CompactOptions:
    now: int = None                # epoch (2016-based) seconds; default wall clock
    pidx: int = 0                  # this partition's index
    partition_mask: int = 0        # partition_version mask; 0 = no split GC
    bottommost: bool = True        # tombstones may be dropped only at bottom
    filter: bool = True            # False = flush path (pure sort, no drops)
    default_ttl: int = 0           # table-level default_ttl app-env (seconds)
    prefix_u32: int = DEFAULT_PREFIX_U32   # max prefix window, in u32 lanes
    backend: str = "cpu"           # "cpu" | "tpu"
    runs_sorted: bool = None       # None = detect; True skips the host check
    user_ops: tuple = ()           # parsed engine.compaction_rules Operations

    # device merges bigger than this split into disjoint key ranges that
    # compact independently (the bigger-than-HBM blockwise path, SURVEY
    # §5.7 long-context analogue). Sized so sort columns + aux + merge
    # temporaries of one range fit comfortably in 16 GB HBM.
    max_device_records: int = 128 << 20

    def resolved_now(self) -> int:
        return epoch_now() if self.now is None else self.now


@dataclass
class CompactResult:
    block: KVBlock
    stats: dict = field(default_factory=dict)


def _pow2ceil(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


@dataclass
class PackedRuns:
    """Host-side packed state for one compaction: per-run fixed-width sort
    columns plus the concatenated auxiliary columns the filters need.
    Runs are newest-first; each run is ascending by key after packing
    (unsorted inputs are locally argsorted here, remapping gidx)."""

    w: int                      # prefix lanes actually used
    has_rank: bool
    cols: list                  # per run: list of w uint32[n_i] prefix cols
    rank: list                  # per run: uint32[n_i] or None
    klen: list                  # per run: uint32[n_i]
    gidx: list                  # per run: int32[n_i] global concat index
    sbytes: list                # per run: S-dtype[n_i] (lazy; may hold None)
    lens: tuple                 # per run real lengths
    blocks: list                # the source KVBlocks (for lazy global aux)
    run_aux: list               # per run: (expire, deleted, hash32) in ROW
                                # order — lets the device fold the TTL/
                                # stale/tomb filter elementwise before the
                                # merge instead of gathering by gidx after

    # global-index-order aux, built lazily: only the CPU backend's
    # post-merge filter reads these; the TPU path consumes run_aux, so
    # eager concatenation would copy ~9B/record for nothing
    @property
    def expire(self) -> np.ndarray:
        if self._expire is None:
            self._expire = np.concatenate([b.expire_ts for b in self.blocks])
        return self._expire

    @property
    def deleted(self) -> np.ndarray:
        if self._deleted is None:
            self._deleted = np.concatenate([b.deleted for b in self.blocks])
        return self._deleted

    @property
    def hash32(self) -> np.ndarray:
        if self._hash32 is None:
            self._hash32 = np.concatenate([b.hash32 for b in self.blocks])
        return self._hash32

    def __post_init__(self):
        self._expire = self._deleted = self._hash32 = None


def pack_runs(runs, opts: CompactOptions, need_sbytes: bool) -> PackedRuns:
    with _TRACE.span("pack", records=sum(b.n for b in runs),
                     nbytes=sum(b.key_bytes_total + b.val_bytes_total
                                for b in runs)):
        _inject("compact.pack")
        return _pack_runs_impl(runs, opts, need_sbytes)


def _pack_runs_impl(runs, opts: CompactOptions, need_sbytes: bool) -> PackedRuns:
    max_klen = max(int(b.key_len.max()) for b in runs)
    if max_klen >= 1 << 24:
        raise ValueError("keys >= 16MiB unsupported")
    w = max(1, min(-(-min(max_klen, 4 * opts.prefix_u32) // 4), opts.prefix_u32))
    has_rank = max_klen > 4 * w
    ranks_all = None
    if has_rank:
        concat = KVBlock.concat(runs)
        ranks_all = compute_suffix_ranks(concat, w)
    offsets = np.cumsum([0] + [b.n for b in runs])
    cols, rank_l, klen_l, gidx_l, sb_l, aux_l = [], [], [], [], [], []
    sorted_known = bool(opts.runs_sorted)
    for i, b in enumerate(runs):
        pref = pack_key_prefixes(b.key_arena, b.key_off, b.key_len, w)
        kl = b.key_len.astype(np.uint32)
        rk = ranks_all[offsets[i] : offsets[i + 1]] if has_rank else None
        gi = np.arange(offsets[i], offsets[i + 1], dtype=np.int32)
        ex, de, hs = b.expire_ts, b.deleted, b.hash32
        sb = None
        if need_sbytes or not sorted_known:
            sb = pack_sbytes([pref[:, j] for j in range(w)], kl, rk)
            if not sorted_known and not _is_sorted(sb):
                order = np.argsort(sb, kind="stable")
                pref, kl, gi, sb = pref[order], kl[order], gi[order], sb[order]
                ex, de, hs = ex[order], de[order], hs[order]
                if rk is not None:
                    rk = rk[order]
        # LSM runs are intra-run UNIQUE (flush dedups, compaction outputs
        # dedup, ingest requires dedup); inputs that violate that (tests,
        # raw external sets) get first-wins dedup HERE, on EVERY backend —
        # the device merge networks are not stable, so duplicate
        # (key, prio) rows would survive nondeterministically. Sorted runs
        # have duplicates adjacent, so the check is one vector compare
        # (over sbytes when packed, else over the raw sort columns).
        n_run = len(kl)
        dup = np.zeros(n_run, dtype=bool)
        if sb is not None:
            dup[1:] = sb[1:] == sb[:-1]
        elif n_run > 1:
            same = np.all(pref[1:] == pref[:-1], axis=1) & (kl[1:] == kl[:-1])
            if rk is not None:
                same &= rk[1:] == rk[:-1]
            dup[1:] = same
        if dup.any():
            keep_rows = ~dup
            pref, kl, gi = pref[keep_rows], kl[keep_rows], gi[keep_rows]
            ex, de, hs = ex[keep_rows], de[keep_rows], hs[keep_rows]
            if sb is not None:
                sb = sb[keep_rows]
            if rk is not None:
                rk = rk[keep_rows]
        cols.append([np.ascontiguousarray(pref[:, j]) for j in range(w)])
        rank_l.append(rk)
        klen_l.append(kl)
        gidx_l.append(gi)
        sb_l.append(sb)
        aux_l.append((ex, de, hs))
    return PackedRuns(
        w=w, has_rank=has_rank, cols=cols, rank=rank_l, klen=klen_l,
        gidx=gidx_l, sbytes=sb_l,
        # post-dedup lengths (gidx still indexes the ORIGINAL concat)
        lens=tuple(len(g) for g in gidx_l),
        blocks=list(runs), run_aux=aux_l,
    )


def _is_sorted(sb: np.ndarray) -> bool:
    return bool(np.all(sb[1:] >= sb[:-1])) if len(sb) > 1 else True


def _filter_keep(keep, gidx, packed: PackedRuns, now, pidx, pmask, bottommost):
    expire = packed.expire[gidx]
    keep &= ~((expire > 0) & (expire <= now))
    if pmask:
        keep &= (packed.hash32[gidx] & np.uint32(pmask)) == np.uint32(pidx)
    if bottommost:
        keep &= ~packed.deleted[gidx]
    return keep


class CpuBackend:
    """Vectorized numpy merge — the honest CPU baseline for bench. Exploits
    run-sortedness exactly like RocksDB's heap merge does, but batched:
    each record's merged rank = own position + count of smaller records in
    every other run (binary search), then a scatter materializes the merge.
    """

    name = "cpu"

    def survivors(self, packed: PackedRuns, now, pidx, pmask, bottommost,
                  do_filter) -> np.ndarray:
        # "device" = the merge+dedup+filter stage on whichever backend runs
        # it — same stage name as the tpu path so traces compare 1:1
        with _TRACE.span("device", records=sum(packed.lens)):
            return self._survivors(packed, now, pidx, pmask, bottommost,
                                   do_filter)

    def _survivors(self, packed: PackedRuns, now, pidx, pmask, bottommost,
                   do_filter) -> np.ndarray:
        K = len(packed.lens)
        if K == 1:
            merged_sb, merged_gidx = packed.sbytes[0], packed.gidx[0]
        else:
            total = sum(packed.lens)
            merged_sb = np.empty(total, dtype=packed.sbytes[0].dtype)
            merged_gidx = np.empty(total, dtype=np.int32)
            from .. import native

            use_native = native.available()
            for i in range(K):
                r = np.arange(packed.lens[i], dtype=np.int64)
                for j in range(K):
                    if j == i:
                        continue
                    # equal keys order newest-run (lowest index) first
                    side = "right" if j < i else "left"
                    if use_native:
                        # galloping two-pointer pass over both sorted runs
                        r += native.merge_counts(packed.sbytes[i],
                                                 packed.sbytes[j], side)
                    else:
                        r += np.searchsorted(packed.sbytes[j], packed.sbytes[i],
                                             side=side)
                merged_sb[r] = packed.sbytes[i]
                merged_gidx[r] = packed.gidx[i]
        same = np.zeros(len(merged_sb), dtype=bool)
        same[1:] = merged_sb[1:] == merged_sb[:-1]
        keep = ~same
        if do_filter:
            keep = _filter_keep(keep, merged_gidx, packed, now, pidx, pmask,
                                bottommost)
        return merged_gidx[keep]


@dataclass
class DevicePacked:
    """Device-resident compaction inputs. In the engine's hot path these
    live in HBM across the LSM lifecycle — uploaded once when a run is
    born (flush / previous compaction output), so compaction reads HBM,
    not PCIe (SURVEY.md §5.7c 'HBM-resident key blocks')."""

    run_cols: tuple   # per run: (w [+rank] prefix cols, klen, gidx) jax arrays
    aux: tuple        # per run: (expire, deleted, hash32) jax arrays,
                      # ROW-aligned and padded like run_cols (feeds the
                      # pre-merge filter fold; NOT concat order)
    padded_lens: tuple
    w: int
    has_rank: bool


@dataclass
class DeviceRun:
    """One run's cacheable device-resident packed columns — the engine's
    'HBM-resident key blocks' (SURVEY §5.7c): an SSTable packs + uploads
    these ONCE (flush prime or first device compaction) and every later
    compaction it joins reads HBM, not PCIe. Runs whose keys exceed the
    prefix window (suffix-rank merges) are not cacheable: ranks are global
    to a merge set.

    EVERY column is padded to the pow2 bucket so the jitted merge is keyed
    only on (padded_lens, run widths) — real lengths travel as traced
    scalars and distinct run sizes in one bucket share one XLA program
    (the same recompile bound the host path gets from _MIN_BUCKET)."""

    cols: tuple       # w jnp.uint32 arrays, padded to padded_len (pads 0xFF)
    klen: object      # jnp.uint32[padded_len] (pads 0xFFFFFFFF)
    expire: object    # jnp.uint32[padded_len] (pads 0)
    deleted: object   # jnp.bool_[padded_len] (pads False)
    hash32: object    # jnp.uint32[padded_len] (pads 0)
    n: int
    padded_len: int
    w: int
    # value-residency extension (uniform-layout runs only): the run's value
    # rows live in HBM too, so compaction output values materialize on
    # device instead of the host arena gather (VERDICT-r3 item 3)
    val2d: object = None   # jnp.uint8[padded_len, vl0] or None
    vl0: int = 0
    # per-SST read index (ISSUE 7): fence-pointer samples of the first
    # key lane, built on device as a byproduct of this prime
    # (ops/device_lookup.py build_fence_index); None = host-served reads
    fence: object = None   # jnp.uint32[fence_len] or None
    fence_step: int = 0
    fence_len: int = 0

    def nbytes(self) -> int:
        base = (len(self.cols) + 3) * 4 * self.padded_len + self.padded_len
        if self.val2d is not None:
            base += self.padded_len * self.vl0
        if self.fence is not None:
            base += 4 * self.fence_len
        return base


def pack_run_device(block, prefix_u32: int = DEFAULT_PREFIX_U32,
                    with_values: bool = False):
    """-> DeviceRun, or None when this run cannot be cached (keys longer
    than the prefix window need per-merge suffix ranks). The run must be
    sorted (SSTs are born sorted). with_values additionally pins the value
    rows in HBM when the layout is uniform (value residency)."""
    import jax.numpy as jnp

    if block.n == 0:
        return None
    max_klen = int(block.key_len.max())
    w = max(1, min(-(-min(max_klen, 4 * prefix_u32) // 4), prefix_u32))
    if max_klen > 4 * w:
        return None
    padded = _pow2ceil(block.n, _MIN_BUCKET)
    pref = pack_key_prefixes(block.key_arena, block.key_off, block.key_len, w)

    def zpad(a):
        out = np.zeros(padded, dtype=a.dtype)
        out[: len(a)] = a
        return jnp.asarray(out)

    cols = tuple(jnp.asarray(_pad_to(np.ascontiguousarray(pref[:, j]), padded))
                 for j in range(w))
    klen = jnp.asarray(_pad_to(block.key_len.astype(np.uint32), padded))
    val2d, vl0 = None, 0
    if with_values:
        uni = block.uniform_layout()
        if uni is not None:
            vl0 = uni[1]
            rows = np.zeros((padded, vl0), np.uint8)
            rows[: block.n] = block.val_arena.reshape(block.n, vl0)
            val2d = jnp.asarray(rows)
    dr = DeviceRun(
        cols=cols, klen=klen,
        expire=zpad(block.expire_ts),
        deleted=zpad(block.deleted),
        hash32=zpad(block.hash32),
        n=block.n, padded_len=padded, w=w, val2d=val2d, vl0=vl0)
    # read index as a byproduct of the compaction/flush prime: the sorted
    # key column is on the chip RIGHT NOW, so the fence build is one tiny
    # device gather (CompassDB's moment to build the point-read index)
    from .device_lookup import build_fence_index

    build_fence_index(dr)
    return dr


class TpuBackend:
    """JAX device pipeline; jit-cached per (padded run lengths, width)."""

    name = "tpu"

    def survivors_cached_device(self, device_runs, now, pidx, pmask,
                                bottommost, do_filter, want_padded=False):
        """The engine hot path: merge cached DeviceRuns (newest first)
        without any host packing or re-upload. Returns the survivor index
        still ON DEVICE (+ count) so the caller can overlap its download
        with the host arena gather. want_padded additionally returns the
        padded-concat survivor index (the per-run value gather's input):
        (mapped, padded, count) instead of (mapped, count)."""
        import jax.numpy as jnp

        w = max(r.w for r in device_runs)
        lens = tuple(r.padded_len for r in device_runs)
        ws = tuple(r.w for r in device_runs)
        fn = (_compiled_pipeline_cached_padded(lens, ws, w) if want_padded
              else _compiled_pipeline_cached(lens, ws, w))
        cached = tuple(tuple(r.cols) + (r.klen,) for r in device_runs)
        aux = tuple((r.expire, r.deleted, r.hash32) for r in device_runs)
        real_lens = jnp.asarray([r.n for r in device_runs], jnp.int32)
        # the int(count) below syncs on the kernel, so the span's wall time
        # covers dispatch + device execution
        with _TRACE.span("device", records=sum(r.n for r in device_runs)):
            _inject("compact.device")
            out = fn(cached, aux, real_lens,
                     jnp.uint32(now), jnp.uint32(pidx),
                     jnp.uint32(pmask), jnp.asarray(bool(bottommost)),
                     jnp.asarray(bool(do_filter)))
            return (*out[:-1], int(out[-1]))

    def survivors_cached(self, device_runs, now, pidx, pmask, bottommost,
                         do_filter) -> np.ndarray:
        out_idx, count = self.survivors_cached_device(
            device_runs, now, pidx, pmask, bottommost, do_filter)
        return np.asarray(out_idx[:count])

    def prepare(self, packed: PackedRuns) -> DevicePacked:
        with _TRACE.span("h2d", records=sum(packed.lens)) as sp:
            _inject("compact.h2d")
            prep = self._prepare(packed)
            sp["bytes"] = sum(
                sum(int(a.size) * a.dtype.itemsize for a in rc)
                for rc in prep.run_cols)
            return prep

    def _prepare(self, packed: PackedRuns) -> DevicePacked:
        import jax.numpy as jnp

        padded_lens = tuple(_pow2ceil(n, _MIN_BUCKET) for n in packed.lens)
        run_cols = []
        aux = []
        for i in range(len(packed.lens)):
            arrays = list(packed.cols[i])
            if packed.has_rank:
                arrays.append(packed.rank[i])
            arrays.append(packed.klen[i])
            arrays.append(packed.gidx[i])
            run_cols.append(tuple(
                jnp.asarray(_pad_to(a, padded_lens[i])) for a in arrays
            ))
            # per-run ROW-aligned aux, zero-padded (pads are already
            # excluded by gidx == -1, so their filter bits are moot)
            ex, de, hs = packed.run_aux[i]
            aux.append(tuple(
                jnp.asarray(_zpad_to(a, padded_lens[i]))
                for a in (ex, de, hs)))
        return DevicePacked(tuple(run_cols), tuple(aux), padded_lens,
                            packed.w, packed.has_rank)

    def survivors_device(self, packed, now, pidx, pmask, bottommost,
                         do_filter):
        """-> (device survivor index, count): keep the index on device so
        the download can overlap the host gather."""
        import jax.numpy as jnp

        prep = packed if isinstance(packed, DevicePacked) else self.prepare(packed)
        fn = _compiled_pipeline(prep.padded_lens, prep.w, prep.has_rank)
        # int(count) syncs on the kernel: the span covers dispatch + device
        with _TRACE.span("device", records=sum(prep.padded_lens)):
            _inject("compact.device")
            out_idx, count = fn(
                prep.run_cols, prep.aux,
                jnp.uint32(now), jnp.uint32(pidx), jnp.uint32(pmask),
                jnp.asarray(bool(bottommost)), jnp.asarray(bool(do_filter)),
            )
            return out_idx, int(count)

    def survivors(self, packed, now, pidx, pmask, bottommost,
                  do_filter) -> np.ndarray:
        out_idx, count = self.survivors_device(packed, now, pidx, pmask,
                                               bottommost, do_filter)
        return np.asarray(out_idx[:count])


@dataclass
class DeviceVals:
    """Device-resident value rows for a uniform-layout block, uploaded at
    flush time like the key columns (SURVEY §7c: the host-side arena
    gather of 10M variable-length values was the 1.27s bottleneck at the
    r3 best — value rows living in HBM let survivors materialize on
    device and come back as one contiguous transfer)."""

    val2d: object  # jnp.uint8[n, vl0]
    vl0: int
    n: int

    def nbytes(self) -> int:
        return self.n * self.vl0


def prepare_values(block: KVBlock) -> "DeviceVals | None":
    """Upload a uniform-layout block's value rows to device; None when the
    layout is not uniform (variable-width values stay host-gathered)."""
    import jax.numpy as jnp

    uni = block.uniform_layout()
    if uni is None:
        return None
    _, vl0 = uni
    return DeviceVals(jnp.asarray(block.val_arena.reshape(block.n, vl0)),
                      vl0, block.n)


@functools.lru_cache(maxsize=64)
def _compiled_val_gather(n: int, vl0: int, bucket: int):
    import jax
    import jax.numpy as jnp

    def fn(val2d, idx):
        # idx rows past the real count carry -1; clip to row 0 (discarded
        # by the host-side [:count] slice)
        safe = jnp.clip(idx, 0, np.int32(n - 1))
        return jnp.take(val2d, safe, axis=0)

    return jax.jit(fn)


def _finish_overlapped(concat: KVBlock, out_dev, real_idx, count: int,
                       kl0: int, vl0: int) -> KVBlock:
    """Shared tail of both value-residency materializers: start the value
    download, gather keys+aux on the host while it is in flight (native
    fused loop, numpy fallback), assemble the uniform output block."""
    with _TRACE.span("gather", records=count,
                     nbytes=count * (kl0 + vl0)):
        _inject("compact.gather")
        return _finish_overlapped_impl(concat, out_dev, real_idx, count,
                                       kl0, vl0)


def _finish_overlapped_impl(concat: KVBlock, out_dev, real_idx, count: int,
                            kl0: int, vl0: int) -> KVBlock:
    try:
        out_dev.copy_to_host_async()
    except AttributeError:
        pass
    idx = np.asarray(real_idx[:count]).astype(np.int32, copy=False)
    # device-derived indices feed unchecked native pointer arithmetic (and
    # numpy fancy indexing would silently wrap a -1): a pipeline defect
    # must be loud, not memory corruption
    if count and (int(idx.min()) < 0 or int(idx.max()) >= concat.n):
        raise ValueError(
            "survivor index outside concat rows — device pipeline bug "
            f"(min {int(idx.min())}, max {int(idx.max())}, n {concat.n})")
    from .. import native

    out_k = np.empty((count, kl0), np.uint8)
    out_e = np.empty(count, np.uint32)
    out_h = np.empty(count, np.uint32)
    out_d = np.empty(count, np.bool_)
    if not native.gather_keys_uniform(
            concat.key_arena, kl0, concat.expire_ts, concat.hash32,
            concat.deleted, idx, out_k.reshape(-1), out_e, out_h, out_d):
        key2d = concat.key_arena.reshape(concat.n, kl0)
        out_k[:] = key2d[idx]
        out_e[:] = concat.expire_ts[idx]
        out_h[:] = concat.hash32[idx]
        out_d[:] = concat.deleted[idx]
    out_v = np.asarray(out_dev)[:count]
    return KVBlock(
        out_k.reshape(-1), np.arange(count, dtype=np.int64) * kl0,
        np.full(count, kl0, np.int32),
        out_v.reshape(-1), np.arange(count, dtype=np.int64) * vl0,
        np.full(count, vl0, np.int32),
        out_e, out_h, out_d)


def materialize_device_survivors(concat: KVBlock, dev_vals: DeviceVals,
                                 dev_idx, count: int) -> KVBlock:
    """Materialize the compaction output with the value rows gathered ON
    DEVICE and downloaded as one contiguous block, overlapped with the
    host-side keys+aux gather — the two halves pay max() instead of sum().
    Requires uniform layout and a resident DeviceVals; anything else falls
    back to the host-gather path."""
    if count == 0:
        return KVBlock.empty()
    uni = concat.uniform_layout()
    if uni is None or dev_vals is None or dev_vals.n != concat.n \
            or uni[1] != dev_vals.vl0:
        return gather_device_survivors(concat, dev_idx, count)
    kl0, vl0 = uni
    bucket = min(_pow2ceil(count, 1 << 16), int(dev_idx.shape[0]))
    fn = _compiled_val_gather(dev_vals.n, vl0, bucket)
    out_dev = fn(dev_vals.val2d, dev_idx[:bucket])
    return _finish_overlapped(concat, out_dev, dev_idx, count, kl0, vl0)


def gather_device_survivors(concat: KVBlock, dev_idx, count: int,
                            chunks: int = 8) -> KVBlock:
    """Materialize concat.gather(survivors) while the survivor index is
    still in flight: the device index splits into chunks whose host copies
    all start asynchronously up front, so the arena gather of chunk i
    overlaps the transfer of chunks i+1.. (VERDICT-r2 item 3 — on this
    box the index download and the memcpy-bound gather are comparable
    costs; overlapped they pay max() instead of sum()).

    Preallocating the output requires the uniform-record contiguous-arena
    layout (the same precondition _gather_arena's fast path keys on);
    anything else falls back to the one-shot download + gather."""
    if count == 0:
        return KVBlock.empty()
    with _TRACE.span("gather", records=count):
        _inject("compact.gather")
        return _gather_device_survivors_impl(concat, dev_idx, count, chunks)


def _gather_device_survivors_impl(concat: KVBlock, dev_idx, count: int,
                                  chunks: int) -> KVBlock:
    n = concat.n
    uni = concat.uniform_layout() if (count >= (1 << 16) and chunks > 1
                                      and n < (1 << 31)) else None
    if uni is None:
        return concat.gather(np.asarray(dev_idx[:count]))
    kl0, vl0 = uni
    from .. import native

    use_native = native.available()
    key2d = concat.key_arena.reshape(n, kl0)
    val2d = concat.val_arena.reshape(n, vl0)
    out_k = np.empty((count, kl0), np.uint8)
    out_v = np.empty((count, vl0), np.uint8)
    out_e = np.empty(count, np.uint32)
    out_h = np.empty(count, np.uint32)
    out_d = np.empty(count, np.bool_)
    bounds = [count * i // chunks for i in range(chunks + 1)]
    parts = []
    for a, b in zip(bounds, bounds[1:]):
        if a == b:
            continue
        part = dev_idx[a:b]
        try:
            part.copy_to_host_async()
        except AttributeError:
            pass
        parts.append((a, b, part))
    for a, b, part in parts:
        idx = np.asarray(part)
        if use_native and native.gather_block_uniform(
                concat.key_arena, kl0, concat.val_arena, vl0,
                concat.expire_ts, concat.hash32, concat.deleted,
                idx.astype(np.int32, copy=False),
                out_k[a:b], out_v[a:b], out_e[a:b], out_h[a:b], out_d[a:b]):
            continue
        out_k[a:b] = key2d[idx]
        out_v[a:b] = val2d[idx]
        out_e[a:b] = concat.expire_ts[idx]
        out_h[a:b] = concat.hash32[idx]
        out_d[a:b] = concat.deleted[idx]
    return KVBlock(
        out_k.reshape(-1), np.arange(count, dtype=np.int64) * kl0,
        np.full(count, kl0, np.int32),
        out_v.reshape(-1), np.arange(count, dtype=np.int64) * vl0,
        np.full(count, vl0, np.int32),
        out_e, out_h, out_d)


def _pad_to(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    fill = -1 if a.dtype == np.int32 else _U32_MAX
    out = np.full(n, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


def _zpad_to(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    out = np.zeros(n, dtype=a.dtype)
    out[: len(a)] = a
    return out


def _pipeline_body(run_cols, aux_runs, padded_lens, nk, use_pallas,
                   now, pidx, pmask, bottommost, do_filter):
    """Traced merge→dedup→filter→compact body shared by both jitted entry
    points (host-packed and device-cached runs).

    Sort key per record: (w prefix lanes, [suffix rank,] klen<<8|prio).
    Pads carry 0xFFFFFFFF keys / idx -1 and sort to the tail of every
    merge; they are excluded by the idx >= 0 guard at the end.

    aux_runs holds each run's ROW-aligned (expire, deleted, hash32): the
    TTL/stale/tombstone filter folds into the idx column BEFORE the merge
    (filtered rows get idx -1, elementwise — no post-merge aux gathers,
    which cost ~0.5s at 16M on hardware). Row-equivalent to the old
    post-merge form: a key's duplicates are masked by `same` regardless of
    the newest version's filter bit, so a filtered newest still shadows
    (and drops) its older versions, exactly as before."""
    import jax.numpy as jnp

    from .device_sort import merge_two_sorted
    from .pallas_merge import merge_two_sorted_pallas

    items = []
    for i, rc in enumerate(run_cols):
        *kcols, klen, idx = rc
        expire, deleted, hash32 = aux_runs[i]
        expired = (expire > 0) & (expire <= now)
        stale = jnp.where(pmask > 0, (hash32 & pmask) != pidx, False)
        filt = expired | stale | (deleted & bottommost)
        idx = jnp.where(do_filter & filt, np.int32(-1), idx)
        kp = (klen << jnp.uint32(8)) | jnp.uint32(i)
        items.append((padded_lens[i], list(kcols) + [kp, idx]))
    pad_fill = tuple([_U32_MAX] * nk + [np.int32(-1)])
    while len(items) > 1:
        items.sort(key=lambda t: t[0])
        (la, a), (lb, b) = items[0], items[1]
        if use_pallas:
            # tier-2 kernel: whole merge in VMEM, ~2 HBM passes
            merged = merge_two_sorted_pallas(a, b, nk, pad_fill)
        else:
            merged = merge_two_sorted(a, b, nk, pad_fill)
            lm = _pow2ceil(la + lb)
            if lm > la + lb:
                merged = [c[: la + lb] for c in merged]
        items = items[2:] + [(la + lb, merged)]
    _, cols = items[0]
    idx = cols[-1]
    kp = cols[nk - 1]
    key_eq_cols = cols[: nk - 1] + [kp >> jnp.uint32(8)]
    same_tail = functools.reduce(
        jnp.logical_and, [c[1:] == c[:-1] for c in key_eq_cols]
    )
    same = jnp.concatenate([jnp.zeros(1, dtype=bool), same_tail])
    keep = (idx >= 0) & ~same
    n = idx.shape[0]
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    count = pos[-1] + 1
    tgt = jnp.where(keep, pos, n)
    out_idx = jnp.full((n,), -1, jnp.int32).at[tgt].set(idx, mode="drop")
    return out_idx, count


@functools.lru_cache(maxsize=256)
def _compiled_pipeline(padded_lens: tuple, w: int, has_rank: bool):
    """Jitted pipeline over host-packed runs (prepare() uploads)."""
    import jax

    from .pallas_merge import pallas_enabled

    nk = w + (1 if has_rank else 0) + 1
    use_pallas = pallas_enabled()

    def fn(run_cols, aux, now, pidx, pmask, bottommost, do_filter):
        return _pipeline_body(run_cols, aux, padded_lens, nk, use_pallas,
                              now, pidx, pmask, bottommost, do_filter)

    return jax.jit(fn)


def _make_cached_fn(padded_lens: tuple, run_ws: tuple, w: int,
                    allow_pallas: bool = True, want_padded: bool = False):
    """Build the (unjitted) traceable pipeline over CACHED device runs.

    Each input run arrives as its cached fully-padded device columns —
    packed+uploaded ONCE when the SST was born or first joined a device
    compaction. Everything a specific merge needs beyond that is derived
    INSIDE the trace (fused, no extra dispatches): missing prefix lanes
    for runs with shorter keys (all-zero by construction, 0xFFFFFFFF in
    the pad tail), the concat index, and the aux concatenation.

    Real run lengths are TRACED scalars, so compile caches key on
    (padded bucket lengths, run widths) only — a live engine's endlessly
    varying run sizes share programs per bucket instead of recompiling
    per compaction. Internally the merge works in PADDED-concat index
    space (aligned with the padded aux concat); the last step maps
    survivor indices back to real-concat space for the host gather.

    Used directly by _compiled_pipeline_cached (one merge) and under vmap
    by the batched multi-partition pipeline (ops.batched_compact)."""
    import jax.numpy as jnp
    from jax import lax

    from .pallas_merge import pallas_enabled

    nk = w + 1  # cached runs never carry a suffix-rank column
    use_pallas = pallas_enabled() and allow_pallas
    padded_offsets = np.cumsum([0] + list(padded_lens))

    def fn(cached_runs, aux_runs, real_lens, now, pidx, pmask, bottommost,
           do_filter):
        run_cols = []
        for i, rc in enumerate(cached_runs):
            *kcols, klen = rc
            iota = lax.iota(jnp.int32, padded_lens[i])
            in_run = iota < real_lens[i].astype(jnp.int32)
            # pads must keep 0xFF keys even in synthesized zero lanes, and
            # a real record whose cached klen pad says 0xFF cannot occur
            # (in_run covers exactly the packed rows)
            for _ in range(w - run_ws[i]):
                kcols.append(jnp.where(in_run, jnp.uint32(0), _U32_MAX))
            gidx = jnp.where(in_run, iota + np.int32(padded_offsets[i]),
                             np.int32(-1))
            run_cols.append(tuple(kcols + [klen, gidx]))
        # aux_runs are already per-run ROW-aligned padded columns — exactly
        # what the pre-merge filter fold consumes (pad rows carry zeros,
        # and their gidx is -1 regardless)
        out_idx, count = _pipeline_body(
            run_cols, aux_runs, padded_lens, nk, use_pallas,
            now, pidx, pmask, bottommost, do_filter)
        # padded-concat -> real-concat index mapping: subtract each run's
        # accumulated pad slack (static boundaries, traced deltas)
        real_off = jnp.cumsum(jnp.concatenate(
            [jnp.zeros(1, jnp.int32), real_lens.astype(jnp.int32)]))
        mapped = out_idx
        for i in range(len(padded_lens)):
            d_i = np.int32(padded_offsets[i]) - real_off[i]
            mapped = jnp.where(out_idx >= np.int32(padded_offsets[i]),
                               out_idx - d_i, mapped)
        mapped = jnp.where(out_idx >= 0, mapped, -1)
        if want_padded:
            # the padded-concat index addresses each run's padded device
            # arrays directly — what the per-run value gather consumes
            return mapped, out_idx, count
        return mapped, count

    return fn


@functools.lru_cache(maxsize=256)
def _compiled_pipeline_cached(padded_lens: tuple, run_ws: tuple, w: int):
    """Jitted single-merge pipeline over cached device runs (see
    _make_cached_fn for the full contract)."""
    import jax

    return jax.jit(_make_cached_fn(padded_lens, run_ws, w))


@functools.lru_cache(maxsize=256)
def _compiled_pipeline_cached_padded(padded_lens: tuple, run_ws: tuple,
                                     w: int):
    """As _compiled_pipeline_cached but also returning the padded-concat
    survivor index (value-residency materialization needs it)."""
    import jax

    return jax.jit(_make_cached_fn(padded_lens, run_ws, w, want_padded=True))


@functools.lru_cache(maxsize=64)
def _compiled_cached_val_gather(padded_lens: tuple, vl0: int, bucket: int):
    """Per-run masked value-row gather by PADDED-concat survivor index:
    run i owns indices [offs[i], offs[i]+padded_lens[i]). K clipped
    gathers + masked select — all HBM-bound, trivial next to the download."""
    import jax
    import jax.numpy as jnp

    offs = np.cumsum([0] + list(padded_lens))

    def fn(val2ds, idx):
        out = jnp.zeros((bucket, vl0), jnp.uint8)
        for i, v in enumerate(val2ds):
            local = idx - np.int32(offs[i])
            ok = (local >= 0) & (local < np.int32(padded_lens[i]))
            rows = jnp.take(v, jnp.clip(local, 0, np.int32(padded_lens[i] - 1)),
                            axis=0)
            out = jnp.where(ok[:, None], rows, out)
        return out

    return jax.jit(fn)


def materialize_cached_survivors(concat: KVBlock, device_runs, mapped_idx,
                                 padded_idx, count: int) -> KVBlock:
    """Cached-run analogue of materialize_device_survivors: value rows are
    gathered per-run on device by padded-concat index and downloaded as one
    block, overlapped with the host keys+aux gather by real-concat index.
    Preconditions (caller-checked): every run has val2d with one shared
    vl0, and concat has uniform layout matching it."""
    if count == 0:
        return KVBlock.empty()
    kl0, vl0 = concat.uniform_layout()
    padded_lens = tuple(r.padded_len for r in device_runs)
    bucket = min(_pow2ceil(count, 1 << 16), int(padded_idx.shape[0]))
    fn = _compiled_cached_val_gather(padded_lens, vl0, bucket)
    out_dev = fn(tuple(r.val2d for r in device_runs), padded_idx[:bucket])
    return _finish_overlapped(concat, out_dev, mapped_idx, count, kl0, vl0)


_BACKENDS = {"cpu": CpuBackend(), "tpu": TpuBackend(), "jax": TpuBackend()}


def get_backend(name: str):
    return _BACKENDS[name]


def compact_blocks(blocks, opts: CompactOptions,
                   device_runs=None) -> CompactResult:
    """Merge K runs (newest first) into one sorted, deduped, filtered block.

    blocks[0] is the newest run (e.g. the freshest L0 file), blocks[-1] the
    oldest — matching LSM level semantics where a version in a newer run
    shadows the same key in an older one.

    device_runs: optional parallel list of cached DeviceRuns (entries may
    be None). When the backend is tpu and EVERY non-empty run has one, the
    merge consumes HBM-resident columns directly — no host packing, no
    re-upload (the engine's device-resident run cache, VERDICT-r2 item 4).
    """
    with _TRACE.span("compact",
                     records=sum(b.n for b in blocks)) as sp:
        result = _compact_blocks_impl(blocks, opts, device_runs)
        sp["records"] = result.stats.get("input_records", sp["records"])
        return result


def _compact_blocks_impl(blocks, opts: CompactOptions,
                         device_runs=None) -> CompactResult:
    if device_runs is not None:
        device_runs = [d for b, d in zip(blocks, device_runs) if b.n]
    runs = [b for b in blocks if b.n]
    if not runs:
        return CompactResult(KVBlock.empty(), _stats(0, 0))
    # bigger-than-device merges: split the key space into disjoint ranges
    # and compact each independently — dedup and every filter are per-key,
    # so range outputs concatenate into exactly the whole-merge result
    # (byte-equal; test-enforced). The reference handles the analogous
    # "input exceeds memory" case by iterating RocksDB's merge cursor;
    # a device kernel needs resident inputs, so capacity comes from
    # range decomposition instead.
    # (sorted runs only: the range cuts binary-search each run, so an
    # unsorted input — bulk-load ingest sets — must take the normal path,
    # whose pack step sorts runs locally before any device work)
    total_in = sum(b.n for b in runs)
    if (opts.backend != "cpu" and opts.runs_sorted
            and total_in > opts.max_device_records):
        return _compact_blockwise(runs, opts, total_in)
    # run priority travels in 8 bits of the packed (klen<<8 | prio) sort
    # column; wider merges pre-combine the newest runs (no filtering — only
    # the final merge may drop tombstones/expired) to stay within it
    while len(runs) > 255:
        head = compact_blocks(runs[:200], CompactOptions(
            now=opts.now, prefix_u32=opts.prefix_u32, backend=opts.backend,
            filter=False, runs_sorted=opts.runs_sorted))
        runs = [head.block] + runs[200:]
        device_runs = None
    backend = get_backend(opts.backend)
    now = opts.resolved_now()
    fargs = (now, opts.pidx, opts.partition_mask,
             bool(opts.bottommost), bool(opts.filter))

    def _cpu_lane() -> KVBlock:
        packed = pack_runs(runs, opts, need_sbytes=True)
        survivors = get_backend("cpu").survivors(packed, *fargs)
        concat = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
        with _TRACE.span("gather", records=len(survivors)):
            return concat.gather(survivors)

    def _device_lane() -> KVBlock:
        if (device_runs is not None and len(device_runs) == len(runs)
                and all(d is not None for d in device_runs)):
            concat = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
            # cheap checks first: uniform_layout() is four O(n) reductions,
            # wasted work whenever value residency is off (the default)
            vl0s = {d.vl0 for d in device_runs} \
                if all(d.val2d is not None for d in device_runs) else set()
            uni = concat.uniform_layout() if len(vl0s) == 1 else None
            if uni is not None and uni[1] == next(iter(vl0s)):
                # value residency: output values materialize on device
                mapped, padded, count = backend.survivors_cached_device(
                    device_runs, *fargs, want_padded=True)
                return materialize_cached_survivors(concat, device_runs,
                                                    mapped, padded, count)
            dev_idx, count = backend.survivors_cached_device(device_runs,
                                                             *fargs)
            return gather_device_survivors(concat, dev_idx, count)
        packed = pack_runs(runs, opts, need_sbytes=False)
        dev_idx, count = backend.survivors_device(packed, *fargs)
        concat = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
        return gather_device_survivors(concat, dev_idx, count)

    if backend.name == "tpu":
        # the lane guard owns every device failure mode: deadline-abandoned
        # wedges, bounded retry on transient errors, byte-identical cpu
        # fallback, and the breaker that routes around a dead device
        from ..runtime.lane_guard import LANE_GUARD

        out = LANE_GUARD.run(_device_lane, _cpu_lane, op="compact")
    else:
        out = _cpu_lane()
    out = apply_post_filters(out, opts, now)
    # stats count RAW input rows (pre any pack-time intra-run dedup) so
    # every path — cpu, device, cached, sharded, blockwise — reports the
    # same input_records for the same inputs
    return CompactResult(out, _stats(sum(b.n for b in runs), out.n))


def apply_post_filters(out: KVBlock, opts: CompactOptions,
                       now: int) -> KVBlock:
    """Host-side post passes shared by every merge entry point (single,
    blockwise, batched): user-specified compaction rules run before the
    TTL rewrite, like KeyWithTTLCompactionFilter runs user ops first
    (:36-105), then the table default_ttl rewrite."""
    if opts.filter and opts.user_ops:
        from ..engine.compaction_rules import apply_operations

        drop, _ = apply_operations(out, opts.user_ops, now)
        if drop.any():
            out = out.gather(np.nonzero(~drop)[0])
    if opts.filter and opts.default_ttl > 0:
        _apply_default_ttl(out, now + opts.default_ttl)
    return out


def _slice_block(b: KVBlock, lo: int, hi: int) -> KVBlock:
    """Zero-copy row slice: arenas shared, columns sliced (offsets remain
    valid into the full arena; gather compacts later)."""
    return KVBlock(b.key_arena, b.key_off[lo:hi], b.key_len[lo:hi],
                   b.val_arena, b.val_off[lo:hi], b.val_len[lo:hi],
                   b.expire_ts[lo:hi], b.hash32[lo:hi], b.deleted[lo:hi])


def _compact_blockwise(runs, opts: CompactOptions,
                       total_in: int) -> CompactResult:
    """Range-decomposed compaction for merges too big for device memory:
    boundary keys from the largest run's quantiles cut EVERY run into
    aligned disjoint key ranges; each range merges/dedups/filters
    independently on the device and outputs concatenate in key order.

    With PEGASUS_COMPACT_PIPELINE_DEPTH > 1 (default 2) the ranges run
    double-buffered (ops/pipeline.py): range i+1 packs/uploads on a host
    worker and range i-1 gathers/post-filters while range i runs its
    device merge — the stages pay max() instead of sum()."""
    from .pipeline import pipeline_depth

    n_ranges = max(2, -(-total_in // opts.max_device_records))
    pivot = max(runs, key=lambda b: b.n)
    boundaries = []
    for j in range(1, n_ranges):
        k = pivot.key(min(pivot.n - 1, j * pivot.n // n_ranges))
        if not boundaries or k > boundaries[-1]:
            boundaries.append(k)
    cuts = [[0] * len(runs)]
    for k in boundaries:
        cuts.append([b.lower_bound(k) for b in runs])
    cuts.append([b.n for b in runs])
    # long keys trigger pack_runs' suffix-rank path, which CONCATENATES its
    # inputs — zero-copy slices would drag the full shared arenas into
    # every range (n_ranges x total memory, on exactly the bounded-memory
    # path). Compact such slices down to their own rows first.
    long_keys = max(int(b.key_len.max()) for b in runs) > 4 * opts.prefix_u32
    jobs = []  # (non-empty range_runs, range_total, direct)
    for lo_cut, hi_cut in zip(cuts, cuts[1:]):
        range_runs = [_slice_block(b, lo, hi)
                      for b, lo, hi in zip(runs, lo_cut, hi_cut)]
        if long_keys:
            range_runs = [rb.gather(np.arange(rb.n, dtype=np.int64))
                          for rb in range_runs]
        range_runs = [rb for rb in range_runs if rb.n]
        range_total = sum(rb.n for rb in range_runs)
        if range_total == 0:
            continue
        # direct ranges re-enter compact_blocks whole (with its own lane
        # guard) instead of the split pack/device/gather stages: degenerate
        # non-shrinking ranges, ranges still over budget (skewed keys ->
        # recursive blockwise), and >255-run merges (pre-combine path)
        direct = (range_total >= total_in
                  or range_total > opts.max_device_records
                  or len(range_runs) > 255)
        jobs.append((range_runs, range_total, direct))
    if len(jobs) > 1 and pipeline_depth() > 1:
        return _compact_blockwise_pipelined(jobs, opts, total_in)
    out_blocks = []
    n_out = 0
    for range_runs, range_total, _ in jobs:
        res = compact_blocks(range_runs,
                             _range_opts(opts, range_total, total_in))
        if res.block.n:
            out_blocks.append(res.block)
            n_out += res.block.n
    out = (KVBlock.concat(out_blocks) if len(out_blocks) != 1
           else out_blocks[0])
    return CompactResult(out, _stats(total_in, n_out))


def _range_opts(opts: CompactOptions, range_total: int,
                total_in: int) -> CompactOptions:
    """Per-range CompactOptions: a degenerate key distribution (e.g. one
    repeated key) cannot shrink its range — merge it directly with a
    raised budget rather than recurse forever."""
    if range_total >= total_in:
        from dataclasses import replace

        return replace(opts, max_device_records=range_total + 1)
    return opts


def _compact_blockwise_pipelined(jobs, opts: CompactOptions,
                                 total_in: int) -> CompactResult:
    """Double-buffered range loop. The WHOLE pipelined run executes under
    one lane guard: the device stages run in the guard's worker thread
    (so a wedge anywhere — including a wedged prefetch the caller is
    stalled on — is deadline-abandoned with stage attribution), and the
    fallback drains the pipeline's in-flight workers before rerunning
    every range serially on the cpu backend, byte-identical by the
    backend contract."""
    from dataclasses import replace

    from .pipeline import CompactPipeline

    # pin `now` once: the device attempt and a cpu rerun must filter
    # against the same clock or a fallback could drop a different TTL set
    now = opts.resolved_now()
    opts = replace(opts, now=now)
    fargs = (now, opts.pidx, opts.partition_mask,
             bool(opts.bottommost), bool(opts.filter))
    backend = get_backend(opts.backend)

    def _device_pipelined() -> list:
        pipe = CompactPipeline()

        def _prefetch(job):
            range_runs, _, direct = job
            if direct:
                return None
            packed = pack_runs(range_runs, opts, need_sbytes=False)
            return backend.prepare(packed)  # h2d upload on the worker

        def _dispatch(i, prep):
            range_runs, range_total, direct = jobs[i]
            if direct:
                return compact_blocks(
                    range_runs, _range_opts(opts, range_total, total_in)
                ).block
            return backend.survivors_device(prep, *fargs)

        def _finish(i, disp):
            range_runs, _, direct = jobs[i]
            if direct:
                return disp
            dev_idx, count = disp
            concat = (range_runs[0] if len(range_runs) == 1
                      else KVBlock.concat(range_runs))
            out = gather_device_survivors(concat, dev_idx, count)
            return apply_post_filters(out, opts, now)

        return pipe.map(jobs, _prefetch, _dispatch, _finish)

    def _cpu_serial() -> list:
        return [
            compact_blocks(
                range_runs,
                replace(_range_opts(opts, range_total, total_in),
                        backend="cpu")).block
            for range_runs, range_total, _ in jobs]

    if backend.name == "tpu":
        from ..runtime.lane_guard import LANE_GUARD

        # the guard covers the WHOLE pipelined run, so its deadline must
        # scale with the number of ranges — a large healthy compaction's
        # legitimate device time is ~per-range time x n, and a fixed
        # per-range deadline would falsely abandon it (and walk the
        # breaker open). A wedge still aborts within n x deadline.
        # eff <= 0 = deadline disabled, preserved by the multiply.
        eff = LANE_GUARD.effective_deadline_s()
        scaled = eff * len(jobs) if eff and eff > 0 else eff
        blocks = LANE_GUARD.run(_device_pipelined, _cpu_serial,
                                op="compact", deadline_s=scaled)
    else:
        blocks = _device_pipelined()
    out_blocks = [b for b in blocks if b.n]
    n_out = sum(b.n for b in out_blocks)
    out = (KVBlock.concat(out_blocks) if len(out_blocks) != 1
           else out_blocks[0])
    return CompactResult(out, _stats(total_in, n_out))


def sort_block(block: KVBlock, opts: CompactOptions = None) -> KVBlock:
    """Flush path: sort one run by key, newest-wins dedup, no filtering
    (RocksDB flush writes every live memtable record; the reference's TTL
    filter only runs at compaction)."""
    opts = opts or CompactOptions()
    flush_opts = CompactOptions(
        now=opts.now, prefix_u32=opts.prefix_u32, backend=opts.backend,
        filter=False, runs_sorted=False,
    )
    return compact_blocks([block], flush_opts).block


def merge_body(cols, rank, klen, prio, expire, deleted, hash32, valid,
               now, pidx, pmask, bottommost, do_filter, pos=None):
    """Single-array device merge: full sort + dedup + filter on jnp arrays.

    Used by the shard_map'd multi-chip path (parallel.sharded_compact),
    whose all_to_all routing scrambles run order, and by the driver's
    single-chip compile check. Returns (perm, keep) in sorted order.
    Input length must be a power of two (callers pad).

    `pos` (uint32) is the LAST sort key: the tie-break among rows with
    identical (key, prio) — i.e. duplicate keys within one run. Sort
    networks are not stable, so without a keyed position the surviving
    version of an intra-run duplicate is nondeterministic (and the
    sharded path's all_to_all re-orders rows, so its local iota is NOT
    original order). Callers with scrambled layouts pass the original
    concat index; None = rows are in original order, use iota.
    """
    import jax.numpy as jnp
    from jax import lax

    from .device_sort import sort_network

    n = rank.shape[0]
    big = jnp.uint32(0xFFFFFFFF)
    key_cols = [jnp.where(valid, c, big) for c in cols]
    key_cols.append(jnp.where(valid, rank, big))
    key_cols.append(jnp.where(valid, klen, big))
    iota = lax.iota(jnp.int32, n)
    if pos is None:
        pos = iota.astype(jnp.uint32)
    sort_ops = key_cols + [jnp.where(valid, prio, big),
                           jnp.where(valid, pos, big)]
    out = sort_network(sort_ops + [iota], nk=len(sort_ops))
    s_key_cols = out[: len(key_cols)]
    perm = out[-1]
    same_tail = functools.reduce(
        jnp.logical_and, [c[1:] == c[:-1] for c in s_key_cols]
    )
    same = jnp.concatenate([jnp.zeros(1, dtype=bool), same_tail])
    keep = valid[perm] & ~same
    s_expire = expire[perm]
    s_deleted = deleted[perm]
    s_hash = hash32[perm]
    expired = (s_expire > 0) & (s_expire <= now)
    stale = jnp.where(pmask > 0, (s_hash & pmask) != pidx, False)
    tomb = s_deleted & bottommost
    keep_f = keep & ~expired & ~stale & ~tomb
    keep = jnp.where(do_filter, keep_f, keep)
    return perm, keep


def _apply_default_ttl(block: KVBlock, new_expire: int) -> None:
    """Rewrite expire_ts=0 records to the table default TTL, in place.

    Mirrors KeyWithTTLCompactionFilter's value rewrite when a table-level
    default_ttl app-env is set (src/server/key_ttl_compaction_filter.h:56-76).
    expire_ts sits at value offset 0 (v0/v1) or 1 (self-describing v2).
    """
    targets = np.nonzero((block.expire_ts == 0) & ~block.deleted)[0]
    if len(targets) == 0:
        return
    off = block.val_off[targets]
    vlen = block.val_len[targets]
    has_hdr = vlen > 0
    first = np.where(has_hdr, block.val_arena[np.minimum(off, len(block.val_arena) - 1)], 0)
    hdr = (first & 0x80) != 0
    # the 4-byte BE field must fit inside THIS record's value bytes: a
    # value shorter than its own expire_ts field (truncated ingest, raw
    # test fixtures) is skipped outright — rewriting it would scribble
    # into the neighboring record's arena bytes (or off the arena end)
    fits = vlen >= np.where(hdr, 5, 4)
    if not bool(fits.all()):
        targets, off, hdr = targets[fits], off[fits], hdr[fits]
        if len(targets) == 0:
            return
    off = off + np.where(hdr, 1, 0)
    be = np.array(
        [(new_expire >> 24) & 0xFF, (new_expire >> 16) & 0xFF,
         (new_expire >> 8) & 0xFF, new_expire & 0xFF],
        dtype=np.uint8,
    )
    for j in range(4):
        block.val_arena[off + j] = be[j]
    block.expire_ts[targets] = np.uint32(new_expire)


def _stats(n_in: int, n_out: int) -> dict:
    return {"input_records": n_in, "output_records": n_out, "dropped": n_in - n_out}
