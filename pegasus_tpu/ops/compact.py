"""Sort / k-way merge / filter: the compaction_backend={cpu,tpu} kernels.

This is the TPU seam of the whole build (SURVEY.md §2.3, BASELINE.json): the
work RocksDB does record-at-a-time inside CompactRange — comparator sort,
level merge, TTL/version dedup filtering (reference:
src/server/key_ttl_compaction_filter.h:36-115, manual compact executor
src/server/pegasus_server_impl.cpp:2814) — runs here as one batched kernel
over KVBlock columns:

  1. lexicographic sort by (prefix lanes, suffix_rank, key_len, run_priority)
     — full byte order of stored keys, newest run first within equal keys;
  2. dedup: keep only the first (= newest) version of each key;
  3. filter: drop expired-TTL records, tombstones at the bottommost level,
     and keys no longer owned by this partition after a split.

Both backends implement identical semantics on the same columns, so output
SSTs are byte-stable across cpu/tpu — the determinism requirement that lets
learner checksums and backup digests agree (SURVEY.md §7 hard part d).

The kernel returns (perm, keep) — the record permutation and survival mask.
Variable-length key/value bytes never touch the device: the host gathers
arenas by perm[keep] when writing the output SST.
"""

import functools
from dataclasses import dataclass, field

import numpy as np

from ..base.utils import epoch_now
from ..engine.block import KVBlock
from .bitonic import bitonic_sort
from .packing import DEFAULT_PREFIX_U32, compute_suffix_ranks, pack_key_prefixes

_U32_MAX = np.uint32(0xFFFFFFFF)


@dataclass
class CompactOptions:
    now: int = None                # epoch (2016-based) seconds; default wall clock
    pidx: int = 0                  # this partition's index
    partition_mask: int = 0        # partition_version mask; 0 = no split GC
    bottommost: bool = True        # tombstones may be dropped only at bottom
    filter: bool = True            # False = flush path (pure sort, no drops)
    default_ttl: int = 0           # table-level default_ttl app-env (seconds)
    prefix_u32: int = DEFAULT_PREFIX_U32
    backend: str = "cpu"           # "cpu" | "tpu"

    def resolved_now(self) -> int:
        return epoch_now() if self.now is None else self.now


@dataclass
class CompactResult:
    block: KVBlock
    stats: dict = field(default_factory=dict)


def _next_bucket(n: int) -> int:
    """Pad to power-of-two buckets >= 1024 to bound jit recompilations."""
    b = 1024
    while b < n:
        b <<= 1
    return b


class CpuBackend:
    """Vectorized numpy reference — also the honest CPU baseline for bench."""

    name = "cpu"

    def merge(self, cols, rank, klen, prio, expire, deleted, hash32, valid,
              now, pidx, pmask, bottommost, do_filter):
        big = _U32_MAX
        key_cols = [np.where(valid, c, big) for c in cols]
        key_cols.append(np.where(valid, rank, big))
        key_cols.append(np.where(valid, klen, big))
        sort_keys = key_cols + [np.where(valid, prio, big)]
        # np.lexsort: last key is primary
        perm = np.lexsort(tuple(reversed(sort_keys))).astype(np.int32)
        s_key_cols = [c[perm] for c in key_cols]
        same = np.ones(len(perm), dtype=bool)
        for c in s_key_cols:
            same[1:] &= c[1:] == c[:-1]
        same[0] = False
        keep = valid[perm] & ~same
        if do_filter:
            s_expire = expire[perm]
            s_deleted = deleted[perm]
            s_hash = hash32[perm]
            keep &= ~((s_expire > 0) & (s_expire <= now))
            if pmask:
                keep &= (s_hash & np.uint32(pmask)) == np.uint32(pidx)
            if bottommost:
                keep &= ~s_deleted
        return perm, keep


class TpuBackend:
    """JAX implementation; jit-cached per (n_padded, width). Runs on whatever
    platform JAX is on (TPU in prod, host CPU devices in tests)."""

    name = "tpu"

    def merge(self, cols, rank, klen, prio, expire, deleted, hash32, valid,
              now, pidx, pmask, bottommost, do_filter):
        import jax.numpy as jnp

        fn = _jitted_merge(len(cols), len(rank))
        perm, keep = fn(
            [jnp.asarray(c) for c in cols],
            jnp.asarray(rank), jnp.asarray(klen), jnp.asarray(prio),
            jnp.asarray(expire), jnp.asarray(deleted), jnp.asarray(hash32),
            jnp.asarray(valid),
            jnp.uint32(now), jnp.uint32(pidx), jnp.uint32(pmask),
            jnp.asarray(bottommost), jnp.asarray(do_filter),
        )
        return np.asarray(perm), np.asarray(keep)


def merge_body(cols, rank, klen, prio, expire, deleted, hash32, valid,
               now, pidx, pmask, bottommost, do_filter):
    """The device merge: sort + dedup + filter on jnp arrays of one shard.

    Shared by the single-chip jitted kernel and the shard_map'd multi-chip
    path (parallel.sharded_compact). Returns (perm, keep) in sorted order.
    """
    import jax.numpy as jnp
    from jax import lax

    n = rank.shape[0]
    big = jnp.uint32(0xFFFFFFFF)
    key_cols = [jnp.where(valid, c, big) for c in cols]
    key_cols.append(jnp.where(valid, rank, big))
    key_cols.append(jnp.where(valid, klen, big))
    sort_ops = key_cols + [jnp.where(valid, prio, big)]
    iota = jnp.arange(n, dtype=jnp.int32)
    if n & (n - 1) == 0:
        # bitonic network: O(log^2 n) HLO regardless of n — lax.sort's TPU
        # lowering unrolls per element and takes minutes to compile at
        # engine sizes (see ops.bitonic docstring)
        sorted_ops, perm = bitonic_sort(sort_ops, iota)
        s_key_cols = sorted_ops[: len(key_cols)]
    else:
        out = lax.sort(tuple(sort_ops) + (iota,), num_keys=len(sort_ops))
        s_key_cols = out[: len(key_cols)]
        perm = out[-1]
    same_tail = functools.reduce(
        jnp.logical_and, [c[1:] == c[:-1] for c in s_key_cols]
    )
    same = jnp.concatenate([jnp.zeros(1, dtype=bool), same_tail])
    keep = valid[perm] & ~same
    s_expire = expire[perm]
    s_deleted = deleted[perm]
    s_hash = hash32[perm]
    expired = (s_expire > 0) & (s_expire <= now)
    stale = jnp.where(pmask > 0, (s_hash & pmask) != pidx, False)
    tomb = s_deleted & bottommost
    keep_f = keep & ~expired & ~stale & ~tomb
    keep = jnp.where(do_filter, keep_f, keep)
    return perm, keep


@functools.lru_cache(maxsize=64)
def _jitted_merge(width: int, n: int):
    import jax

    return jax.jit(merge_body)


_BACKENDS = {"cpu": CpuBackend(), "tpu": TpuBackend(), "jax": TpuBackend()}


def get_backend(name: str):
    return _BACKENDS[name]


def compact_blocks(blocks, opts: CompactOptions) -> CompactResult:
    """Merge K runs (newest first) into one sorted, deduped, filtered block.

    blocks[0] is the newest run (e.g. the freshest L0 file), blocks[-1] the
    oldest — matching LSM level semantics where a version in a newer run
    shadows the same key in an older one.
    """
    runs = [b for b in blocks if b.n]
    if not runs:
        return CompactResult(KVBlock.empty(), _stats(0, 0))
    block = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
    prio = np.repeat(
        np.arange(len(runs), dtype=np.uint32),
        [b.n for b in runs],
    )
    n = block.n
    n_pad = _next_bucket(n)
    w = opts.prefix_u32

    prefixes = pack_key_prefixes(block.key_arena, block.key_off, block.key_len, w)
    rank = compute_suffix_ranks(block, w, prefixes)

    def pad(a, fill=0):
        if n_pad == n:
            return a
        out = np.full(n_pad, fill, dtype=a.dtype)
        out[:n] = a
        return out

    cols = [pad(np.ascontiguousarray(prefixes[:, j])) for j in range(w)]
    valid = pad(np.ones(n, dtype=bool), False)
    now = opts.resolved_now()

    backend = get_backend(opts.backend)
    perm, keep = backend.merge(
        cols, pad(rank), pad(block.key_len.astype(np.uint32)), pad(prio),
        pad(block.expire_ts), pad(block.deleted), pad(block.hash32), valid,
        now, opts.pidx, opts.partition_mask,
        bool(opts.bottommost), bool(opts.filter),
    )
    out_idx = perm[keep]
    out = block.gather(out_idx)
    if opts.filter and opts.default_ttl > 0:
        _apply_default_ttl(out, now + opts.default_ttl)
    return CompactResult(out, _stats(n, out.n))


def sort_block(block: KVBlock, opts: CompactOptions = None) -> KVBlock:
    """Flush path: sort one run by key, newest-wins dedup, no filtering
    (RocksDB flush writes every live memtable record; the reference's TTL
    filter only runs at compaction)."""
    opts = opts or CompactOptions()
    flush_opts = CompactOptions(
        now=opts.now, prefix_u32=opts.prefix_u32, backend=opts.backend, filter=False
    )
    return compact_blocks([block], flush_opts).block


def _apply_default_ttl(block: KVBlock, new_expire: int) -> None:
    """Rewrite expire_ts=0 records to the table default TTL, in place.

    Mirrors KeyWithTTLCompactionFilter's value rewrite when a table-level
    default_ttl app-env is set (src/server/key_ttl_compaction_filter.h:56-76).
    expire_ts sits at value offset 0 (v0/v1) or 1 (self-describing v2).
    """
    targets = np.nonzero((block.expire_ts == 0) & ~block.deleted)[0]
    if len(targets) == 0:
        return
    off = block.val_off[targets]
    has_hdr = block.val_len[targets] > 0
    first = np.where(has_hdr, block.val_arena[np.minimum(off, len(block.val_arena) - 1)], 0)
    off = off + np.where((first & 0x80) != 0, 1, 0)
    be = np.array(
        [(new_expire >> 24) & 0xFF, (new_expire >> 16) & 0xFF,
         (new_expire >> 8) & 0xFF, new_expire & 0xFF],
        dtype=np.uint8,
    )
    for j in range(4):
        block.val_arena[off + j] = be[j]
    block.expire_ts[targets] = np.uint32(new_expire)


def _stats(n_in: int, n_out: int) -> dict:
    return {"input_records": n_in, "output_records": n_out, "dropped": n_in - n_out}
