"""Causal job tracing for the background planes (ISSUE 16).

PR 3's RequestTracer (runtime/tracing.py) gave every FOREGROUND request
one causal timeline; the work that moves the most bytes — compaction
jobs, offload ships/merges, learn block ships, scheduler token
deliveries, duplicator ship windows — was only visible as disjoint local
stage spans and counters. This module is the background-plane twin: a
``JobTracer`` assigns every background unit of work a CLUSTER-UNIQUE job
id (node seed + counter, so two nodes can never mint the same id) and
records per-hop spans into a bounded per-job timeline. The id is
PROPAGATED across RPC hops:

  - the cluster compaction scheduler mints an id per (gpid, tick)
    decision and rides it inside the delivered ``compact-sched-policy``
    lease (collector/compact_scheduler.py);
  - the engine adopts the token's id when the token fires its L0
    trigger, or mints a local id for engine-local triggers
    (engine/db.py _maybe_trigger_l0 / _merge_to_level / the deferred
    install drain);
  - the pipeline pool and the lane guards carry the active job context
    across their thread hops (ops/pipeline.py submit,
    runtime/lane_guard.py), and lane retry/fallback/breaker transitions
    land in the job timeline tagged with which lane;
  - the compaction-offload wire carries the id in the
    ``RPC_COMPACT_OFFLOAD_*`` messages; the service records its own
    ship/merge hops against the id and returns them in the merge
    response, and the originating node STITCHES them into its timeline
    (origin-tagged) — one timeline spanning two hosts;
  - each streamed learn is a job whose prepare / fetch-waves /
    digest-proof / swap hops land in one timeline
    (replication/replica.py, replication/learn.py), with the id carried
    in the learn wire messages so the serving primary can attribute its
    pins;
  - the duplicator notes its ship windows into a per-duplicator job.

Cross-process semantics mirror RequestTracer: each process records the
hops IT closes, keyed by the shared id. In a onebox (one process, one
global JOB_TRACER) every plane writes into ONE record, which is the
acceptance shape tests/test_job_trace.py pins; across real hosts each
side holds its local view and the offload plane additionally stitches
the remote view home.

Surfaces: the ``job-trace`` remote command (pid-keyed, so a partition-
group router's structural merge keeps every worker's timelines), GET
/jobs on every service app, shell ``job_trace``, and the flight recorder
embeds in-window job timelines into incident artifacts so a first-cause
event can name the job it wedged.

Counters: ``job.active`` (gauge), ``job.completed`` (rate),
``job.spans_dropped`` (rate: hops past the per-job cap).
"""

import collections
import os
import random
import threading
import time
from contextlib import contextmanager

from .perf_counters import counters


class JobTracer:
    MAX_ACTIVE = 1024   # leaked/abandoned job guard (oldest evicted)
    MAX_HOPS = 256      # per-job hop cap (a long-lived duplicator job
    # keeps its bounded head; overflow counts in job.spans_dropped)

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ring = collections.deque(maxlen=capacity)  # completed jobs
        self._active = {}   # job_id -> open timeline record
        # node seed: pid + random salt — two processes (or two boots of
        # one) can never mint colliding ids, which is what lets a remote
        # service record hops against an id it did not mint
        self._seed = f"{os.getpid():x}-{random.getrandbits(24):06x}"
        self._seq = 0
        self._c_active = counters.number("job.active")
        self._c_completed = counters.rate("job.completed")
        self._c_dropped = counters.rate("job.spans_dropped")

    # ------------------------------------------------------------ identity

    def mint(self) -> str:
        """A fresh cluster-unique job id: ``j<node-seed>-<counter>``."""
        with self._lock:
            self._seq += 1
            return f"j{self._seed}-{self._seq:x}"

    def current(self):
        """The job id active in this thread, or None."""
        return getattr(self._local, "job", None)

    # ----------------------------------------------------------- lifecycle

    def begin(self, kind: str, job_id: str = None, **attrs) -> str:
        """Open (or join) a job timeline. With ``job_id`` the record is
        keyed by a propagated id (the scheduler's token, an offload
        begin request); without one a fresh local id is minted. Joining
        an id this process already opened is idempotent — the existing
        record keeps its start time and kind."""
        jid = job_id or self.mint()
        with self._lock:
            e = self._active.get(jid)
            if e is None:
                while len(self._active) >= self.MAX_ACTIVE:
                    self._active.pop(next(iter(self._active)))
                e = {"job_id": jid, "kind": kind, "ts": time.time(),
                     "hops": [], "attrs": dict(attrs), "dropped": 0}
                self._active[jid] = e
            else:
                e["attrs"].update(attrs)
            self._c_active.set(len(self._active))
        return jid

    def finish(self, job_id: str, status: str = "ok", **attrs) -> None:
        """Close a job: the record moves to the completed ring with its
        end-to-end duration. Unknown/already-finished ids no-op (a
        propagated finish can race a MAX_ACTIVE eviction)."""
        with self._lock:
            e = self._active.pop(job_id, None)
            self._c_active.set(len(self._active))
        if e is None:
            return
        e["attrs"].update(attrs)
        e["status"] = status
        e["duration_us"] = int((time.time() - e["ts"]) * 1e6)
        with self._lock:
            self._ring.append(e)
        self._c_completed.increment()

    @contextmanager
    def job(self, kind: str, job_id: str = None, **attrs):
        """begin + activate in this thread + finish at exit — the owning
        scope of a background unit of work (a streamed learn, a traced
        compaction). Nested inside an already-active job this records a
        plain hop instead of a second job."""
        if self.current() is not None:
            with self.hop(f"{kind}.nested"):
                yield self.current()
            return
        jid = self.begin(kind, job_id=job_id, **attrs)
        self._local.job = jid
        try:
            yield jid
        except BaseException:
            self.finish(jid, status="error")
            raise
        else:
            self.finish(jid)
        finally:
            self._local.job = None

    @contextmanager
    def adopt(self, job_id):
        """Install an existing job id in THIS thread (pipeline-pool and
        lane-guard worker hops, the engine trigger adopting the
        scheduler token) without owning its finish. job_id may be None
        (untraced caller) — then this is a no-op."""
        if job_id is None:
            yield None
            return
        prev = getattr(self._local, "job", None)
        self._local.job = job_id
        try:
            yield job_id
        finally:
            self._local.job = prev

    # ---------------------------------------------------------------- hops

    def _append_hop(self, job_id: str, rec: dict) -> None:
        with self._lock:
            e = self._active.get(job_id)
            if e is None:
                return
            if len(e["hops"]) >= self.MAX_HOPS:
                e["dropped"] += 1
            else:
                e["hops"].append(rec)
                return
        self._c_dropped.increment()

    @contextmanager
    def hop(self, name: str, **attrs):
        """Record one timed hop of the thread's active job (no-op
        without one). Yields the mutable attr dict so counts discovered
        mid-hop can be added before it closes."""
        jid = self.current()
        if jid is None:
            yield attrs
            return
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield attrs
        finally:
            rec = {"name": name, "ts": ts,
                   "duration_us": int((time.perf_counter() - t0) * 1e6)}
            rec.update(attrs)
            self._append_hop(jid, rec)

    def note(self, name: str, job_id: str = None, **attrs) -> None:
        """Record a zero-duration hop (a point event: a scheduler
        decision, a token delivery, a lane fallback). With an explicit
        ``job_id`` the hop lands on that job — opening a remote-view
        record if this process has not seen the id yet (how a serving
        primary attributes its learn pins); without one it lands on the
        thread's active job and no-ops if there is none."""
        jid = job_id or self.current()
        if jid is None:
            return
        if job_id is not None:
            with self._lock:
                known = jid in self._active
            if not known:
                self.begin("remote", job_id=jid)
        rec = {"name": name, "ts": time.time(), "duration_us": 0}
        rec.update(attrs)
        self._append_hop(jid, rec)

    def stitch(self, job_id: str, hops, origin: str = "") -> None:
        """Merge hops recorded by ANOTHER process (the offload service's
        ship/merge spans, returned in the merge response) into this
        process's timeline for the job, each tagged with its origin —
        one timeline spanning two hosts. Malformed entries are dropped,
        never raised: the remote view is diagnostic, the merge result
        is not."""
        if not hops:
            return
        for h in hops:
            if not isinstance(h, dict) or "name" not in h:
                continue
            rec = dict(h)
            rec.setdefault("ts", time.time())
            rec.setdefault("duration_us", 0)
            if origin:
                rec["origin"] = origin
            self._append_hop(job_id, rec)

    # ------------------------------------------------------------ read API

    def _json_ready(self, e: dict) -> dict:
        out = {"job_id": e["job_id"], "kind": e["kind"], "ts": e["ts"],
               "hops": list(e["hops"]), "attrs": dict(e["attrs"])}
        if e.get("dropped"):
            out["hops_dropped"] = e["dropped"]
        if "status" in e:
            out["status"] = e["status"]
            out["duration_us"] = e["duration_us"]
        return out

    def jobs(self, last: int = 50, active: bool = True) -> list:
        """The most recent completed job timelines (oldest first), plus
        — with active=True — the still-open ones, JSON-ready."""
        with self._lock:
            done = [self._json_ready(e) for e in list(self._ring)[-last:]]
            live = ([self._json_ready(e) for e in self._active.values()]
                    if active else [])
        return done + live

    def find(self, job_id: str):
        """One timeline by id — active records first (the job being
        hunted is usually the one still wedged)."""
        with self._lock:
            e = self._active.get(job_id)
            if e is not None:
                return self._json_ready(e)
            for t in reversed(self._ring):
                if t["job_id"] == job_id:
                    return self._json_ready(t)
        return None

    def window(self, seconds: float = None) -> list:
        """Timelines that overlap the trailing window (the flight
        recorder's incident scrape); None = everything retained."""
        if seconds is None:
            return self.jobs(last=len(self._ring))
        floor = time.time() - seconds
        return [j for j in self.jobs(last=len(self._ring))
                if j["ts"] >= floor
                or any(h.get("ts", 0) >= floor for h in j["hops"])]


# process-wide tracer, like COMPACT_TRACER / REQUEST_TRACER: scheduler,
# engine, ops, replication planes and the duplicator all record into this
# instance (one process = one local timeline view)
JOB_TRACER = JobTracer()
