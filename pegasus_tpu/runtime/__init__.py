from .config import Config, define_flag, get_flag
from .fail_points import (FailPointError, fail_point,
                          setup as failpoint_setup, cfg as failpoint_cfg,
                          teardown as failpoint_teardown)
from .lane_guard import LANE_GUARD, LaneError, LaneGuard, LaneGuardConfig
from .perf_counters import PerfCounters, counters
from .tasking import TaskPools, ThreadPool, Timer

__all__ = [
    "Config",
    "define_flag",
    "get_flag",
    "FailPointError",
    "fail_point",
    "failpoint_setup",
    "failpoint_cfg",
    "failpoint_teardown",
    "LANE_GUARD",
    "LaneError",
    "LaneGuard",
    "LaneGuardConfig",
    "PerfCounters",
    "counters",
    "TaskPools",
    "ThreadPool",
    "Timer",
]
