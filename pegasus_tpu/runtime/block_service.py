"""Block service: pluggable remote file store for backup / bulk load.

The rDSN block-service surface (SURVEY.md §2.4 'Block service + NFS';
reference config.ini [block_service.*], HDFS/local providers): cold backup
uploads checkpoints to it, restore and bulk load read from it, learner
catch-up copies files through the same interface. Providers register by
name; `local_service` ships (the onebox/filesystem provider the reference
also uses for tests); an object-store provider plugs in the same way.
"""

import os
import shutil


class BlockService:
    """Interface: paths are provider-namespace keys (posix-style)."""

    def upload(self, local_path: str, remote_path: str) -> None:
        raise NotImplementedError

    def download(self, remote_path: str, local_path: str) -> None:
        raise NotImplementedError

    def list_dir(self, remote_dir: str) -> list:
        raise NotImplementedError

    def exists(self, remote_path: str) -> bool:
        raise NotImplementedError

    def read(self, remote_path: str) -> bytes:
        raise NotImplementedError

    def write(self, remote_path: str, data: bytes) -> None:
        raise NotImplementedError

    def upload_dir(self, local_dir: str, remote_dir: str) -> int:
        n = 0
        for name in sorted(os.listdir(local_dir)):
            src = os.path.join(local_dir, name)
            if os.path.isfile(src):
                self.upload(src, f"{remote_dir}/{name}")
                n += 1
        return n

    def download_dir(self, remote_dir: str, local_dir: str) -> int:
        os.makedirs(local_dir, exist_ok=True)
        n = 0
        for name in self.list_dir(remote_dir):
            self.download(f"{remote_dir}/{name}", os.path.join(local_dir, name))
            n += 1
        return n


class LocalBlockService(BlockService):
    """Filesystem provider rooted at `root` (the reference's local_service)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, remote_path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, remote_path.lstrip("/")))
        if not p.startswith(os.path.abspath(self.root)):
            raise ValueError(f"path escapes block-service root: {remote_path}")
        return p

    def upload(self, local_path: str, remote_path: str) -> None:
        dst = self._abs(remote_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(local_path, dst)

    def download(self, remote_path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        shutil.copy2(self._abs(remote_path), local_path)

    def list_dir(self, remote_dir: str) -> list:
        d = self._abs(remote_dir)
        if not os.path.isdir(d):
            return []
        return sorted(n for n in os.listdir(d)
                      if os.path.isfile(os.path.join(d, n)))

    def exists(self, remote_path: str) -> bool:
        return os.path.exists(self._abs(remote_path))

    def read(self, remote_path: str) -> bytes:
        with open(self._abs(remote_path), "rb") as f:
            return f.read()

    def write(self, remote_path: str, data: bytes) -> None:
        dst = self._abs(remote_path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        with open(dst, "wb") as f:
            f.write(data)


_PROVIDERS = {"local_service": LocalBlockService}


def register_provider(name: str, cls) -> None:
    _PROVIDERS[name] = cls


def create_block_service(provider: str, root: str) -> BlockService:
    cls = _PROVIDERS.get(provider)
    if cls is None:
        raise ValueError(f"unknown block service provider {provider!r}")
    return cls(root)
