"""Layered configuration: ini files with %{var} substitution + typed flags.

Mirrors the rDSN config surface Pegasus consumes (SURVEY.md §5.6):
  (a) ini sections read via ``Config.get_*`` (dsn_config_get_value_* analogue,
      reference call sites: src/server/pegasus_server_impl_init.cpp:112-500);
  (b) typed process-wide flags with validators (DSN_DEFINE_* analogue,
      src/server/pegasus_server_impl_init.cpp:36-77);
  (c) dynamic per-table app-envs live in the engine, not here.
"""

import configparser
import re
import threading

_VAR_RE = re.compile(r"%\{([^}]+)\}")


class Config:
    """An ini config with %{var} substitution.

    Variables resolve against a substitution dict passed at load (the
    reference substitutes launch-time variables like %{cluster.name}).
    """

    def __init__(self, path: str = None, text: str = None, variables: dict = None):
        self._parser = configparser.ConfigParser(
            interpolation=None, strict=False, delimiters=("=",),
            # rDSN-style inis comment inline ("key = value  # why"); without
            # this the comment travels INTO the value and e.g.
            # compaction_backend = "tpu   # ..." KeyErrors at first merge
            inline_comment_prefixes=("#", ";"),
        )
        self._parser.optionxform = str  # case-sensitive keys like rDSN
        self._variables = dict(variables or {})
        if path is not None:
            with open(path) as f:
                text = f.read()
        if text is not None:
            self._parser.read_string(self._substitute(text))

    def _substitute(self, text: str) -> str:
        return _VAR_RE.sub(lambda m: str(self._variables.get(m.group(1), m.group(0))), text)

    def sections(self):
        return self._parser.sections()

    def has_section(self, section: str) -> bool:
        return self._parser.has_section(section)

    def keys(self, section: str):
        return list(self._parser[section]) if self.has_section(section) else []

    def get_string(self, section: str, key: str, default: str = "") -> str:
        try:
            return self._parser.get(section, key)
        except (configparser.NoSectionError, configparser.NoOptionError):
            return default

    def get_int(self, section: str, key: str, default: int = 0) -> int:
        v = self.get_string(section, key, None)
        return default if v is None or not v.strip() else int(v)

    def get_float(self, section: str, key: str, default: float = 0.0) -> float:
        v = self.get_string(section, key, None)
        return default if v is None or not v.strip() else float(v)

    def get_bool(self, section: str, key: str, default: bool = False) -> bool:
        v = self.get_string(section, key, None)
        if v is None:
            return default
        return v.strip().lower() in ("true", "1", "yes", "on")

    def get_list(self, section: str, key: str, default=()):
        v = self.get_string(section, key, None)
        if v is None:
            return list(default)
        return [s.strip() for s in v.split(",") if s.strip()]

    def set(self, section: str, key: str, value) -> None:
        if not self._parser.has_section(section):
            self._parser.add_section(section)
        self._parser.set(section, key, str(value))


class _FlagRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._flags = {}       # name -> value
        self._validators = {}  # name -> callable

    def define(self, name, default, validator=None, help=""):
        with self._lock:
            if validator is not None and not validator(default):
                raise ValueError(f"flag {name}: default {default!r} fails validation")
            self._flags[name] = default
            if validator is not None:
                self._validators[name] = validator
        return default

    def get(self, name):
        return self._flags[name]

    def set(self, name, value):
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"undefined flag {name}")
            v = self._validators.get(name)
            if v is not None and not v(value):
                raise ValueError(f"flag {name}: value {value!r} fails validation")
            self._flags[name] = value

    def load_from_config(self, config: Config, section: str = "flags"):
        for key in config.keys(section):
            if key in self._flags:
                cur = self._flags[key]
                raw = config.get_string(section, key)
                if isinstance(cur, bool):
                    val = raw.strip().lower() in ("true", "1", "yes", "on")
                elif isinstance(cur, int):
                    val = int(raw)
                elif isinstance(cur, float):
                    val = float(raw)
                else:
                    val = raw
                self.set(key, val)


FLAGS = _FlagRegistry()


def define_flag(name, default, validator=None, help=""):
    """DSN_DEFINE_{int64,bool,...} analogue with optional validator."""
    return FLAGS.define(name, default, validator, help)


def get_flag(name):
    return FLAGS.get(name)


def set_flag(name, value):
    FLAGS.set(name, value)
