"""Perf counter registry: number / volatile number / rate / percentile.

The four counter kinds the reference uses everywhere via perf_counter_wrapper
(SURVEY.md §5.5; e.g. 30+ counters in src/server/pegasus_server_impl.h:427-464),
scrapable by name (shell `perf-counters[-by-substr/-by-prefix]` remote command,
src/shell/command_helper.h:891-1146).
"""

import bisect
import threading
import time


class Counter:
    KIND = "number"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def increment(self, by: int = 1):
        with self._lock:
            self._value += by

    def add(self, by):
        self.increment(by)

    def set(self, value):
        with self._lock:
            self._value = value

    def value(self):
        with self._lock:
            return self._value


class VolatileCounter(Counter):
    """Reads reset the count (per-interval deltas, rDSN volatile_number)."""

    KIND = "volatile_number"

    def value(self):
        with self._lock:
            v, self._value = self._value, 0
            return v


class RateCounter(Counter):
    """Events per second over a rolling window. Reads are NON-destructive:
    the destructive reset-on-read design meant concurrent scrapers
    (/metrics, remote commands, the info collector) each stole a fraction
    of the window and all reported a fraction of the true rate. Instead
    the counter accumulates into a timestamped window; a read rolls the
    window only once it is at least MIN_WINDOW old and republishes the
    finished window's rate until the next roll — so any number of
    concurrent scrapers observe the same value."""

    KIND = "rate"
    MIN_WINDOW = 1.0  # seconds a window must cover before it can roll

    def __init__(self, name: str):
        super().__init__(name)
        self._window_start = time.monotonic()
        self._last_rate = 0.0
        self._rolled = False
        self._total = 0

    def increment(self, by: int = 1):
        with self._lock:
            self._value += by
            self._total += by

    def add(self, by):
        self.increment(by)

    def total(self) -> int:
        """Monotone event count since process start. Unlike the raw
        window accumulator, this never resets on a read — the stable
        thing to assert on when any concurrent scraper (collector,
        /metrics, the metric-history sampler) may roll the window."""
        with self._lock:
            return self._total

    def value(self):
        with self._lock:
            now = time.monotonic()
            dt = now - self._window_start
            if dt >= self.MIN_WINDOW:
                self._last_rate = self._value / dt
                self._value = 0
                self._window_start = now
                self._rolled = True
            elif not self._rolled and self._value:
                # no window ever completed (freshly started process):
                # report the partial window instead of 0. ONLY then — an
                # idle-then-burst transition must keep publishing finished
                # windows, or a scrape 10ms into the burst would divide by
                # 10ms and report a 100x-inflated spike
                return self._value / max(dt, 1e-9)
            return self._last_rate


class PercentileCounter(Counter):
    """Sliding-window percentiles (p50/p90/p95/p99/p999)."""

    KIND = "percentile"
    WINDOW = 5000

    def __init__(self, name: str):
        super().__init__(name)
        self._samples = []
        self._idx = 0

    def set(self, value):
        with self._lock:
            if len(self._samples) < self.WINDOW:
                self._samples.append(value)
            else:
                self._samples[self._idx] = value
                self._idx = (self._idx + 1) % self.WINDOW

    add = set
    increment = set

    PCTS = (("p50", 0.50), ("p90", 0.90), ("p95", 0.95),
            ("p99", 0.99), ("p999", 0.999))

    def percentile(self, p: float):
        with self._lock:
            if not self._samples:
                return 0
            s = sorted(self._samples)
            k = min(len(s) - 1, int(p * len(s)))
            return s[k]

    def percentiles(self) -> dict:
        """One sort for the whole p50/p90/p95/p99/p999 dict (snapshot()
        exports this instead of the bare p99)."""
        with self._lock:
            s = sorted(self._samples)
        if not s:
            return {name: 0 for name, _ in self.PCTS}
        return {name: s[min(len(s) - 1, int(p * len(s)))]
                for name, p in self.PCTS}

    def value(self):
        return self.percentile(0.99)


_KINDS = {c.KIND: c for c in (Counter, VolatileCounter, RateCounter, PercentileCounter)}


class PerfCounters:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}

    def get(self, name: str, kind: str = "number"):
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = _KINDS[kind](name)
                self._counters[name] = c
            elif c.KIND != kind:
                raise TypeError(
                    f"counter {name!r} already registered as {c.KIND}, requested {kind}"
                )
            return c

    def number(self, name):
        return self.get(name, "number")

    def volatile_number(self, name):
        return self.get(name, "volatile_number")

    def rate(self, name):
        return self.get(name, "rate")

    def percentile(self, name):
        return self.get(name, "percentile")

    def snapshot(self, substr: str = None, prefix: str = None) -> dict:
        """perf-counters[-by-substr/-by-prefix] scrape. Percentile
        counters export their full {p50,p90,p95,p99,p999} dict (a single
        p99 hid the tail shape every latency investigation starts from);
        every other kind exports a scalar."""
        with self._lock:
            items = list(self._counters.items())
        out = {}
        for name, c in items:
            if substr is not None and substr not in name:
                continue
            if prefix is not None and not name.startswith(prefix):
                continue
            out[name] = (c.percentiles() if c.KIND == "percentile"
                         else c.value())
        return out

    def remove(self, name: str):
        with self._lock:
            self._counters.pop(name, None)


# process-wide registry, like rDSN's global counter table
counters = PerfCounters()
