"""Perf counter registry: number / volatile number / rate / percentile.

The four counter kinds the reference uses everywhere via perf_counter_wrapper
(SURVEY.md §5.5; e.g. 30+ counters in src/server/pegasus_server_impl.h:427-464),
scrapable by name (shell `perf-counters[-by-substr/-by-prefix]` remote command,
src/shell/command_helper.h:891-1146).
"""

import bisect
import threading
import time


class Counter:
    KIND = "number"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def increment(self, by: int = 1):
        with self._lock:
            self._value += by

    def add(self, by):
        self.increment(by)

    def set(self, value):
        with self._lock:
            self._value = value

    def value(self):
        with self._lock:
            return self._value


class VolatileCounter(Counter):
    """Reads reset the count (per-interval deltas, rDSN volatile_number)."""

    KIND = "volatile_number"

    def value(self):
        with self._lock:
            v, self._value = self._value, 0
            return v


class RateCounter(Counter):
    """Events per second since the last read."""

    KIND = "rate"

    def __init__(self, name: str):
        super().__init__(name)
        self._last_read = time.monotonic()

    def value(self):
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._last_read, 1e-9)
            v, self._value, self._last_read = self._value, 0, now
            return v / dt


class PercentileCounter(Counter):
    """Sliding-window percentiles (p50/p90/p95/p99/p999)."""

    KIND = "percentile"
    WINDOW = 5000

    def __init__(self, name: str):
        super().__init__(name)
        self._samples = []
        self._idx = 0

    def set(self, value):
        with self._lock:
            if len(self._samples) < self.WINDOW:
                self._samples.append(value)
            else:
                self._samples[self._idx] = value
                self._idx = (self._idx + 1) % self.WINDOW

    add = set
    increment = set

    def percentile(self, p: float):
        with self._lock:
            if not self._samples:
                return 0
            s = sorted(self._samples)
            k = min(len(s) - 1, int(p * len(s)))
            return s[k]

    def value(self):
        return self.percentile(0.99)


_KINDS = {c.KIND: c for c in (Counter, VolatileCounter, RateCounter, PercentileCounter)}


class PerfCounters:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}

    def get(self, name: str, kind: str = "number"):
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = _KINDS[kind](name)
                self._counters[name] = c
            elif c.KIND != kind:
                raise TypeError(
                    f"counter {name!r} already registered as {c.KIND}, requested {kind}"
                )
            return c

    def number(self, name):
        return self.get(name, "number")

    def volatile_number(self, name):
        return self.get(name, "volatile_number")

    def rate(self, name):
        return self.get(name, "rate")

    def percentile(self, name):
        return self.get(name, "percentile")

    def snapshot(self, substr: str = None, prefix: str = None) -> dict:
        """perf-counters[-by-substr/-by-prefix] scrape."""
        with self._lock:
            items = list(self._counters.items())
        out = {}
        for name, c in items:
            if substr is not None and substr not in name:
                continue
            if prefix is not None and not name.startswith(prefix):
                continue
            out[name] = c.value()
        return out

    def remove(self, name: str):
        with self._lock:
            self._counters.pop(name, None)


# process-wide registry, like rDSN's global counter table
counters = PerfCounters()
