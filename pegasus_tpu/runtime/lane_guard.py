"""Lane guard: the ONE failure policy for every device-backed compaction.

Every benched wedge so far was survived only by bench.py's out-of-process
360 s lane kill; PR 1's watchdog can name the wedged stage, but in-process
the server still hung forever, and the engine handled device failure with
scattered ad-hoc ``except Exception: degrade`` branches. Both compaction
backends guarantee byte-identical output (tests/test_compact_ops.py, bench
digest handshake), so the TPU lane is an *optimization* that must never be
an availability risk — LUDA (PAPERS.md) makes the same argument for GPU
compaction offload. This module centralizes that contract:

  1. DEADLINE — a device call runs in a worker thread under an in-process
     deadline derived from the watchdog heartbeat; exceeding it abandons
     the worker (never killed: a TPU-attached thread must not be killed,
     the same rule bench.py applies to its lane child) and reports the
     wedged stage from the worker's open span stack.
  2. RETRY — transient device errors retry with bounded exponential
     backoff (deterministic, no jitter). A deadline abandon does NOT
     retry: the lane is wedged, and retrying would stack more abandoned
     device threads against one wedged tunnel.
  3. FALLBACK — exhausted retries (or a wedge) rerun the compaction on
     the cpu backend, byte-identical by contract.
  4. CIRCUIT BREAKER — after `breaker_threshold` CONSECUTIVE device
     failures/wedges every guarded compaction routes straight to cpu for
     `breaker_cooldown_s`; when the cooldown lapses the breaker re-probes
     the device via the watchdog (half-open) and only a passing probe
     closes it.

Call sites: ops/compact.py (single merge), ops/batched_compact.py (one
vmapped dispatch per shape group), parallel/sharded_compact.py (multi-chip
all_to_all merge), bench.py's timed lane (fallback disabled there — a
bench must report the device number or fail loudly, never silently time
the cpu path as "tpu").

Counters (process registry -> /metrics, perf-counters*, collector):
  compact.lane.fallback_count / retry_count /
  compact.lane.deadline_abandon_count / breaker_trip_count     rate
  compact.lane.breaker_open                                    gauge (0/1)

Monotonic totals (rate counters reset on read) live in state(), which
rides in the device-health remote command, /compact/trace, the watchdog
status-file heartbeat, query_compact_state, and bench's detail.lane.

Env knobs (read once at import for the process-wide LANE_GUARD):
  PEGASUS_LANE_DEADLINE_S / PEGASUS_LANE_MAX_RETRIES /
  PEGASUS_LANE_BREAKER_THRESHOLD / PEGASUS_LANE_BREAKER_COOLDOWN_S

Since ISSUE 7 there are TWO lanes sharing this policy class but nothing
else: the compaction lane (LANE_GUARD, counters `compact.lane.*`) and the
serving read lane (READ_LANE_GUARD, counters `read.lane.*`) guarding the
device point-lookup path (ops/device_lookup.py via engine/db.py
get_batch). Separate instances mean separate breakers: a wedged read
probe routes READS to the host walk without pushing compactions off the
device, and vice versa (test-enforced in tests/test_lane_guard.py).
Read-lane knobs: PEGASUS_READ_LANE_DEADLINE_S (default 30 — reads are
latency-sensitive; the host fallback is always available) /
PEGASUS_READ_LANE_MAX_RETRIES / PEGASUS_READ_LANE_BREAKER_THRESHOLD /
PEGASUS_READ_LANE_BREAKER_COOLDOWN_S.
"""

import os
import threading
import time
from dataclasses import dataclass

from . import events, lockrank
from .perf_counters import counters
from .tracing import COMPACT_TRACER


class _LaneWorker(threading.Thread):  #: untracked_ok abandoned-by-design deadline workers: a wedged TPU-attached thread is never joined/killed, so the tracked registry's join_all must not see it
    """Reusable deadline worker: the guard hands it one call at a time
    and waits with a timeout. On timeout the caller ABANDONS it (never
    killed — a TPU-attached thread must not be killed) and the worker
    re-joins the guard's idle pool only after the stale call eventually
    finishes; a truly wedged worker simply never comes back, and the
    pool spawns a fresh one on demand. This keeps the per-call cost of a
    guarded attempt at an Event round-trip instead of a thread spawn —
    the read lane puts the guard on the serving hot path."""

    def __init__(self, guard):
        super().__init__(daemon=True, name=f"lane-{guard.metric_prefix}")
        self._guard = guard
        self._ready = threading.Event()
        self._job = None

    def submit(self, fn, box, done, sessions, job_id=None) -> None:
        self._job = (fn, box, done, sessions, job_id)
        self._ready.set()

    def run(self):
        from .job_trace import JOB_TRACER

        while True:
            self._ready.wait()
            self._ready.clear()
            fn, box, done, sessions, job_id = self._job
            self._job = None
            self._guard.tracer.adopt_sessions(sessions)
            try:
                with JOB_TRACER.adopt(job_id):
                    box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 - crosses the thread boundary
                box["error"] = e
            done.set()
            with self._guard._lock:
                self._guard._idle_workers.append(self)


class LaneError(RuntimeError):
    """Device lane failed and no fallback was provided."""


class LaneDeadlineExceeded(LaneError):
    """The device call outlived its deadline and was abandoned."""


def _env_float(name, default):
    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


def _env_int(name, default):
    v = os.environ.get(name)
    return default if v in (None, "") else int(v)


@dataclass
class LaneGuardConfig:
    # None = derive from the watchdog heartbeat at call time (see
    # LaneGuard.effective_deadline_s); <= 0 disables the deadline (the
    # device call runs inline in the caller's thread)
    deadline_s: float = None
    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 30.0

    @classmethod
    def from_env(cls, env_prefix: str = "PEGASUS_LANE",
                 deadline_s: float = None, max_retries: int = 2,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0) -> "LaneGuardConfig":
        return cls(
            deadline_s=_env_float(f"{env_prefix}_DEADLINE_S", deadline_s),
            max_retries=_env_int(f"{env_prefix}_MAX_RETRIES", max_retries),
            breaker_threshold=_env_int(f"{env_prefix}_BREAKER_THRESHOLD",
                                       breaker_threshold),
            breaker_cooldown_s=_env_float(f"{env_prefix}_BREAKER_COOLDOWN_S",
                                          breaker_cooldown_s),
        )


class LaneGuard:
    def __init__(self, config: LaneGuardConfig = None, tracer=COMPACT_TRACER,
                 probe_fn=None, metric_prefix: str = "compact.lane"):
        self.config = config or LaneGuardConfig()
        self.tracer = tracer
        # counter namespace: "compact.lane" for the compaction lane,
        # "read.lane" for the serving read lane (see module docstring)
        self.metric_prefix = metric_prefix
        # injectable half-open probe (tests); default = the watchdog's
        # liveness round-trip, lazily bound to avoid a runtime->ops import
        # at module load
        self.probe_fn = probe_fn
        self._lock = lockrank.named_lock(f"laneguard.{metric_prefix}")
        # serializes the half-open re-probe: exactly one thread pays the
        # probe timeout against a possibly-wedged device; concurrent
        # callers keep routing to cpu meanwhile
        self._half_open_lock = lockrank.named_lock(
            f"laneguard.half_open.{metric_prefix}")
        # reusable deadline workers (LIFO)
        self._idle_workers = []  #: guarded_by self._lock
        self.fallback_count = 0  #: guarded_by self._lock
        self.retry_count = 0     #: guarded_by self._lock
        self.deadline_abandon_count = 0  #: guarded_by self._lock
        self.breaker_trip_count = 0      #: guarded_by self._lock
        self.device_failure_count = 0    #: guarded_by self._lock
        self._consec_failures = 0        #: guarded_by self._lock
        self._breaker_open_until = 0.0   # monotonic  #: guarded_by self._lock
        # {"op", "error", "stage", "ts"}
        self.last_failure = None   #: guarded_by self._lock
        # {"op", "reason", "ts"}
        self.last_fallback = None  #: guarded_by self._lock

    # ------------------------------------------------------------ plumbing

    @staticmethod
    def _watchdog():
        from ..ops.device_watchdog import WATCHDOG

        return WATCHDOG

    def _probe(self) -> bool:
        if self.probe_fn is not None:
            return bool(self.probe_fn())
        return self._watchdog().probe()

    def effective_deadline_s(self) -> float:
        """The in-process deadline, derived from the watchdog heartbeat
        when not configured: long enough that `fail_threshold` heartbeat
        cycles can independently flip wedged_at_stage first (attribution
        beats abandonment), floored generously so a cold jit compile over
        a slow tunnel is never mistaken for a wedge."""
        if self.config.deadline_s is not None:
            return self.config.deadline_s
        wd = self._watchdog()
        return max(120.0, (wd.probe_timeout_s + wd.interval_s)
                   * (wd.fail_threshold + 2))

    # ------------------------------------------------------------- breaker

    def breaker_open(self, probe: bool = True) -> bool:
        """True while device work must be skipped. When the cooldown has
        lapsed this HALF-OPENS: one watchdog probe decides — pass closes
        the breaker, fail re-arms the full cooldown. Only ONE thread
        probes at a time (a probe against a wedged device blocks for its
        timeout); everyone else keeps routing to cpu meanwhile.

        probe=False is the passive check for paths that must never block
        on a device probe (the engine's HBM prime): an open breaker stays
        open to them until a guarded compaction's half-open probe passes.
        """
        with self._lock:
            if self._consec_failures < self.config.breaker_threshold:
                return False
            cooling = time.monotonic() < self._breaker_open_until
        if cooling or not probe:
            return True
        if not self._half_open_lock.acquire(blocking=False):
            return True  # someone else is probing right now
        try:
            with self._lock:  # re-check: the prior prober may have closed it
                if self._consec_failures < self.config.breaker_threshold:
                    return False
                if time.monotonic() < self._breaker_open_until:
                    return True
            if self._probe():
                with self._lock:
                    self._consec_failures = 0
                    self._breaker_open_until = 0.0
                counters.number(self.metric_prefix + ".breaker_open").set(0)
                events.emit("lane.breaker_close", lane=self.metric_prefix,
                            via="half_open_probe")
                return False
            with self._lock:
                self._breaker_open_until = (time.monotonic()
                                            + self.config.breaker_cooldown_s)
            return True
        finally:
            self._half_open_lock.release()

    def record_device_failure(self, op: str, error: str, stage: str = None,
                              breaker: bool = True) -> None:
        """Count one device failure — the single policy the engine's
        former ad-hoc degrade branches now feed. breaker=False records
        the failure (totals, last_failure) WITHOUT advancing the breaker:
        capacity-local conditions (one oversized sst OOMing its HBM
        prime) are not evidence the device is dead, and must not flap all
        compactions onto cpu."""
        tripped = False
        with self._lock:
            self.device_failure_count += 1
            self.last_failure = {"op": op, "error": str(error)[:400],
                                 "stage": stage, "ts": time.time()}
            if breaker:
                self._consec_failures += 1
                tripped = (self._consec_failures
                           == self.config.breaker_threshold)
                if tripped:
                    self.breaker_trip_count += 1
                    self._breaker_open_until = (
                        time.monotonic() + self.config.breaker_cooldown_s)
        if tripped:
            counters.rate(self.metric_prefix + ".breaker_trip_count").increment()
            counters.number(self.metric_prefix + ".breaker_open").set(1)
            events.emit("lane.breaker_trip", severity="error",
                        lane=self.metric_prefix, op=op,
                        error=str(error)[:200], stage=stage)
            # the trip lands in the active job's timeline too (ISSUE 16):
            # the job that pushed the breaker over names the transition
            from .job_trace import JOB_TRACER

            JOB_TRACER.note("lane.breaker_trip", lane=self.metric_prefix,
                            op=op)

    def record_device_ok(self) -> None:
        with self._lock:
            was_open = self._consec_failures >= self.config.breaker_threshold
            self._consec_failures = 0
            self._breaker_open_until = 0.0
        if was_open:
            counters.number(self.metric_prefix + ".breaker_open").set(0)
            events.emit("lane.breaker_close", lane=self.metric_prefix,
                        via="clean_device_attempt")

    # ----------------------------------------------------------------- run

    def run(self, device_fn, fallback_fn=None, op: str = "compact",
            deadline_s: float = None):
        """Run `device_fn` under the policy; on failure run `fallback_fn`
        (the cpu path, byte-identical by contract). fallback_fn=None means
        the caller wants the device result or the error (bench)."""
        if fallback_fn is not None and self.breaker_open():
            return self._fallback(fallback_fn, op, "breaker open")
        deadline = (self.effective_deadline_s() if deadline_s is None
                    else deadline_s)
        attempts = max(1, self.config.max_retries + 1)
        delay = self.config.backoff_base_s
        last_err = None
        for attempt in range(attempts):
            failures_before = self.device_failure_count  #: unguarded_ok racy snapshot: compared against itself below to detect NESTED failures; a concurrent lane's failure only makes the breaker-reset more conservative
            try:
                result = self._attempt(device_fn, deadline, op)
            except LaneDeadlineExceeded as e:
                last_err = e
                break  # wedged: never stack retries onto a wedged tunnel
            except Exception as e:  # noqa: BLE001 - every device error is policy input
                last_err = e
                self.record_device_failure(op, repr(e))
                if attempt + 1 < attempts:
                    with self._lock:
                        self.retry_count += 1
                    counters.rate(self.metric_prefix + ".retry_count").increment()
                    from .job_trace import JOB_TRACER

                    JOB_TRACER.note("lane.retry", lane=self.metric_prefix,
                                    op=op, attempt=attempt + 1,
                                    error=repr(e)[:200])
                    time.sleep(min(delay, self.config.backoff_max_s))
                    delay *= 2
                    continue
                break
            else:
                # only a CLEAN attempt resets the breaker: a nested
                # guarded call (sharded reassembly sorts re-enter
                # compact_blocks) may have "succeeded" via its own cpu
                # fallback, and crediting that as device health would
                # keep a dead device's breaker from ever accumulating
                if self.device_failure_count == failures_before:  #: unguarded_ok racy snapshot compare (see failures_before above)
                    self.record_device_ok()
                return result
        if fallback_fn is None:
            raise last_err
        return self._fallback(fallback_fn, op,
                              f"device lane failed: {last_err!r}")

    def _attempt(self, fn, deadline_s: float, op: str):
        if not deadline_s or deadline_s <= 0:
            return fn()
        from .job_trace import JOB_TRACER

        box = {}
        done = threading.Event()
        sessions = self.tracer.propagate_sessions()
        with self._lock:
            t = self._idle_workers.pop() if self._idle_workers else None
        if t is None:
            t = _LaneWorker(self)
            t.start()
        t.submit(fn, box, done, sessions, job_id=JOB_TRACER.current())
        if not done.wait(deadline_s):
            # abandoned in its thread, never killed; its span stays open so
            # the watchdog keeps attributing the wedge after we move on
            # (the worker rejoins the pool only if the stale call ever
            # finishes — a wedged one never comes back)
            stages = self.tracer.open_stages().get(t.ident)
            stage = stages[-1] if stages else "unknown"
            with self._lock:
                self.deadline_abandon_count += 1
            counters.rate(
                self.metric_prefix + ".deadline_abandon_count").increment()
            err = LaneDeadlineExceeded(
                f"{op}: device call exceeded {deadline_s:.1f}s deadline "
                f"(wedged at stage {stage}); worker abandoned")
            self.record_device_failure(op, str(err), stage=stage)
            raise err
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _fallback(self, fallback_fn, op: str, reason: str):
        with self._lock:
            self.fallback_count += 1
            self.last_fallback = {"op": op, "reason": reason,
                                  "ts": time.time()}
        counters.rate(self.metric_prefix + ".fallback_count").increment()
        events.emit("lane.fallback", severity="warn",
                    lane=self.metric_prefix, op=op, reason=reason[:200])
        from .job_trace import JOB_TRACER

        JOB_TRACER.note("lane.fallback", lane=self.metric_prefix, op=op,
                        reason=reason[:200])
        print(f"[lane-guard:{self.metric_prefix}] {op}: falling back to the "
              f"host path ({reason})", flush=True)
        return fallback_fn()

    # --------------------------------------------------------------- state

    def state(self) -> dict:
        with self._lock:
            open_now = self._consec_failures >= self.config.breaker_threshold
            return {
                "breaker_open": open_now,
                "breaker_consecutive_failures": self._consec_failures,
                "breaker_cooldown_remaining_s": round(
                    max(0.0, self._breaker_open_until - time.monotonic()), 3)
                    if open_now else 0.0,
                "fallbacks": self.fallback_count,
                "retries": self.retry_count,
                "deadline_abandons": self.deadline_abandon_count,
                "breaker_trips": self.breaker_trip_count,
                "device_failures": self.device_failure_count,
                "last_failure": self.last_failure,
                "last_fallback": self.last_fallback,
            }

    def reset(self) -> None:
        """Test hook: zero every total and close the breaker."""
        with self._lock:
            self.fallback_count = self.retry_count = 0
            self.deadline_abandon_count = self.breaker_trip_count = 0
            self.device_failure_count = self._consec_failures = 0
            self._breaker_open_until = 0.0
            self.last_failure = self.last_fallback = None
        counters.number(self.metric_prefix + ".breaker_open").set(0)


def _warm_lane_counters() -> None:
    """Pre-register both lanes' counter sets with literal names (the
    guard instances increment through their metric prefix): /metrics
    shows zeros before the first incident, and tools/check_metric_names
    can tie each README row to a registration."""
    counters.rate("compact.lane.fallback_count")
    counters.rate("compact.lane.retry_count")
    counters.rate("compact.lane.deadline_abandon_count")
    counters.rate("compact.lane.breaker_trip_count")
    counters.number("compact.lane.breaker_open")
    counters.rate("read.lane.fallback_count")
    counters.rate("read.lane.retry_count")
    counters.rate("read.lane.deadline_abandon_count")
    counters.rate("read.lane.breaker_trip_count")
    counters.number("read.lane.breaker_open")


_warm_lane_counters()

# process-wide instance: every device-backed merge in this process shares
# one breaker (one device/tunnel per process is the deployment shape)
LANE_GUARD = LaneGuard(LaneGuardConfig.from_env())

# the serving read lane (device point lookups, ops/device_lookup.py via
# engine/db.py get_batch): its OWN breaker/totals so a wedged read probe
# degrades reads to the host walk without routing compactions off the
# device (and a compaction wedge doesn't blind the read path). The default
# 30 s deadline undercuts the compact lane's 120 s floor: reads are
# latency-sensitive and the byte-identical host walk is always ready.
READ_LANE_GUARD = LaneGuard(
    LaneGuardConfig.from_env("PEGASUS_READ_LANE", deadline_s=30.0),
    metric_prefix="read.lane")
