"""Task/threadpool engine: named pools, task-code specs, timers.

The rDSN slice Pegasus consumes (SURVEY.md §2.4 row 2): work is enqueued onto
named pools (THREAD_POOL_DEFAULT/REPLICATION/LOCAL_APP/COMPACT/...), each task
code carries a spec (pool, priority, is_write, allow_batch, idempotent —
DEFINE_STORAGE_WRITE_RPC_CODE, src/include/rrdb/rrdb.code.definition.h:25-40),
and timers repeat on a pool (dsn::tasking::enqueue_timer,
src/server/pegasus_server_impl.cpp:1536-1554).

Heavy compute in this build lives in numpy/JAX (GIL released), so Python
worker threads are an adequate host-side executor; the C++ runtime module
replaces this hot path later without changing the interface.
"""

import heapq
import itertools
import threading
import time
import weakref
from dataclasses import dataclass, field

from . import lockrank


@dataclass(frozen=True)
class TaskCode:
    """A named task type bound to a pool with scheduling attributes."""

    name: str
    pool: str = "THREAD_POOL_DEFAULT"
    priority: int = 1  # 0=LOW, 1=COMMON, 2=HIGH
    is_write: bool = False
    allow_batch: bool = False
    idempotent: bool = False


_task_codes = {}


class _TrackedRegistry:
    """Process-wide ledger of every thread/executor the tracked spawn
    helpers created — the fix-class for the PR 5 rc=134 shutdown abort:
    a daemon thread nobody registered could not be joined at teardown
    because nothing knew it existed. Holds weakrefs only (a finished
    thread must be collectable); `join_all` is the bounded backstop the
    test harness (and any embedding process) can call before interpreter
    finalization. The static pass tools/analyze/thread_lifecycle.py
    enforces that raw spawns route through here."""

    def __init__(self):
        self._lock = threading.Lock()  # leaf lock: nothing nests inside
        self._threads = []    #: guarded_by self._lock
        self._executors = []  #: guarded_by self._lock

    def _prune_locked(self, refs) -> list:  #: requires self._lock
        # deref each weakref ONCE: a referent collected between a guard
        # deref and a value deref would put None into the result
        pairs = [(r, r()) for r in refs]
        refs[:] = [r for r, obj in pairs if obj is not None]
        return [obj for _, obj in pairs if obj is not None]

    def register_thread(self, t) -> None:
        with self._lock:
            self._threads.append(weakref.ref(t))
            self._prune_locked(self._threads)

    def register_executor(self, ex) -> None:
        with self._lock:
            self._executors.append(weakref.ref(ex))
            self._prune_locked(self._executors)

    def live_threads(self) -> list:
        with self._lock:
            return [t for t in self._prune_locked(self._threads)
                    if t.is_alive()]

    def live_executors(self) -> list:
        with self._lock:
            return self._prune_locked(self._executors)

    def join_all(self, timeout_s: float = 5.0) -> list:
        """Shut down tracked executors (no wait) and join tracked
        threads against ONE shared deadline. Returns the threads still
        alive at the deadline (wedged daemons a caller may want to name
        before abandoning them)."""
        for ex in self.live_executors():
            try:
                ex.shutdown(wait=False)
            except Exception:  # noqa: BLE001 - teardown must keep going
                pass
        deadline = time.monotonic() + timeout_s
        leftover = []
        for t in self.live_threads():
            if t is threading.current_thread() or not t.daemon:
                continue
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                leftover.append(t)
        return leftover


TRACKED = _TrackedRegistry()


def spawn_thread(target, *args, name: str = None, daemon: bool = True,
                 start: bool = True, **kwargs):
    """The ONE way to create a thread outside this module
    (tools/analyze/thread_lifecycle.py flags raw ``Thread(...)`` calls):
    same signature spirit as threading.Thread, but every spawn lands in
    TRACKED so teardown can enumerate and join it. start=False returns
    an unstarted (but already registered) thread for create-then-start
    call sites."""
    t = threading.Thread(target=target, args=args, kwargs=kwargs or None,
                         name=name, daemon=daemon)
    TRACKED.register_thread(t)
    if start:
        t.start()
    return t


def tracked_executor(max_workers: int, thread_name_prefix: str = ""):
    """concurrent.futures.ThreadPoolExecutor, registered in TRACKED so
    join_all can shut it down at teardown."""
    from concurrent.futures import ThreadPoolExecutor

    ex = ThreadPoolExecutor(max_workers,
                            thread_name_prefix=thread_name_prefix)
    TRACKED.register_executor(ex)
    return ex


def define_task_code(name, pool="THREAD_POOL_DEFAULT", priority=1, is_write=False,
                     allow_batch=False, idempotent=False) -> TaskCode:
    code = TaskCode(name, pool, priority, is_write, allow_batch, idempotent)
    _task_codes[name] = code
    return code


def task_code(name: str) -> TaskCode:
    return _task_codes[name]


class ThreadPool:
    """A named fixed-size worker pool.

    Two internal queues: `_delayed` ordered by ready time, and `_ready`
    ordered by (priority desc, FIFO). Workers migrate due delayed tasks into
    the ready queue, so priority decides ordering among runnable tasks and a
    delayed task cannot starve behind a stream of immediate ones.
    """

    def __init__(self, name: str, worker_count: int = 1):
        self.name = name
        # one lock RANK for every pool ("taskpool"): pools never nest
        # their locks (workers run tasks outside the lock)
        self._lock = lockrank.named_lock("taskpool")
        # _delayed: (ready_at, seq, priority, fn, args); _ready:
        # (-priority, seq, fn, args)
        self._delayed = []  #: guarded_by self._lock
        self._ready = []    #: guarded_by self._lock
        self._counter = itertools.count()
        self._not_empty = lockrank.named_condition("taskpool", self._lock)
        self._shutdown = False  #: guarded_by self._lock
        self._workers = [
            spawn_thread(self._run, name=f"{name}.{i}", daemon=True,
                         start=False)
            for i in range(worker_count)
        ]
        for w in self._workers:
            w.start()

    def enqueue(self, fn, *args, priority: int = 1, delay_s: float = 0.0):
        with self._lock:
            if self._shutdown:
                raise RuntimeError(f"pool {self.name} is shut down")
            seq = next(self._counter)
            if delay_s <= 0:
                heapq.heappush(self._ready, (-priority, seq, fn, args))
            else:
                heapq.heappush(self._delayed, (time.monotonic() + delay_s, seq, priority, fn, args))
            self._not_empty.notify()

    def _run(self):
        while True:
            with self._lock:
                while True:
                    if self._shutdown:
                        return
                    now = time.monotonic()
                    while self._delayed and self._delayed[0][0] <= now:
                        _, seq, priority, fn, args = heapq.heappop(self._delayed)
                        heapq.heappush(self._ready, (-priority, seq, fn, args))
                    if self._ready:
                        _, _, fn, args = heapq.heappop(self._ready)
                        break
                    if self._delayed:
                        self._not_empty.wait(timeout=self._delayed[0][0] - now)
                    else:
                        self._not_empty.wait()
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - a task must never kill its worker
                import logging, traceback

                logging.getLogger("pegasus_tpu.tasking").error(
                    "task raised in pool %s:\n%s", self.name, traceback.format_exc()
                )

    def stop(self):
        """Stop workers; pending (including delayed) tasks are discarded."""
        with self._lock:
            self._shutdown = True
            self._delayed.clear()
            self._ready.clear()
            self._not_empty.notify_all()
        for w in self._workers:
            w.join(timeout=5)


class Timer:
    """Repeating timer posting onto a pool; cancel() stops future firings."""

    def __init__(self, pool: ThreadPool, interval_s: float, fn, *args, first_delay_s=None):
        self._pool = pool
        self._interval = interval_s
        self._fn = fn
        self._args = args
        self._cancelled = threading.Event()
        self._schedule(self._interval if first_delay_s is None else first_delay_s)

    def _schedule(self, delay):
        if not self._cancelled.is_set():
            try:
                self._pool.enqueue(self._fire, delay_s=delay)
            except RuntimeError:
                self._cancelled.set()  # pool shut down: the timer dies with it

    def _fire(self):
        if self._cancelled.is_set():
            return
        try:
            self._fn(*self._args)
        finally:
            self._schedule(self._interval)

    def cancel(self):
        self._cancelled.set()


DEFAULT_POOLS = {
    # name -> worker count; the reference's pool layout (config.ini:82-158)
    "THREAD_POOL_DEFAULT": 4,
    "THREAD_POOL_REPLICATION": 4,
    "THREAD_POOL_LOCAL_APP": 4,
    "THREAD_POOL_COMPACT": 2,
    "THREAD_POOL_INGESTION": 2,
    "THREAD_POOL_META_STATE": 1,
    "THREAD_POOL_FD": 1,
    "THREAD_POOL_REPLICATION_LONG": 2,
    "THREAD_POOL_BLOCK_SERVICE": 2,
    "THREAD_POOL_SLOG": 1,
    "THREAD_POOL_PLOG": 2,
}


class TaskPools:
    """The process's pool container; one per service node."""

    def __init__(self, pool_sizes: dict = None):
        sizes = dict(DEFAULT_POOLS)
        if pool_sizes:
            sizes.update(pool_sizes)
        self._pools = {name: ThreadPool(name, n) for name, n in sizes.items()}

    def pool(self, name: str) -> ThreadPool:
        return self._pools[name]

    def enqueue(self, code: TaskCode, fn, *args, delay_s: float = 0.0):
        self._pools[code.pool].enqueue(fn, *args, priority=code.priority, delay_s=delay_s)

    def enqueue_timer(self, code: TaskCode, interval_s: float, fn, *args, first_delay_s=None):
        return Timer(self._pools[code.pool], interval_s, fn, *args, first_delay_s=first_delay_s)

    def stop(self):
        for p in self._pools.values():
            p.stop()
