"""Task/threadpool engine: named pools, task-code specs, timers.

The rDSN slice Pegasus consumes (SURVEY.md §2.4 row 2): work is enqueued onto
named pools (THREAD_POOL_DEFAULT/REPLICATION/LOCAL_APP/COMPACT/...), each task
code carries a spec (pool, priority, is_write, allow_batch, idempotent —
DEFINE_STORAGE_WRITE_RPC_CODE, src/include/rrdb/rrdb.code.definition.h:25-40),
and timers repeat on a pool (dsn::tasking::enqueue_timer,
src/server/pegasus_server_impl.cpp:1536-1554).

Heavy compute in this build lives in numpy/JAX (GIL released), so Python
worker threads are an adequate host-side executor; the C++ runtime module
replaces this hot path later without changing the interface.
"""

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TaskCode:
    """A named task type bound to a pool with scheduling attributes."""

    name: str
    pool: str = "THREAD_POOL_DEFAULT"
    priority: int = 1  # 0=LOW, 1=COMMON, 2=HIGH
    is_write: bool = False
    allow_batch: bool = False
    idempotent: bool = False


_task_codes = {}


def define_task_code(name, pool="THREAD_POOL_DEFAULT", priority=1, is_write=False,
                     allow_batch=False, idempotent=False) -> TaskCode:
    code = TaskCode(name, pool, priority, is_write, allow_batch, idempotent)
    _task_codes[name] = code
    return code


def task_code(name: str) -> TaskCode:
    return _task_codes[name]


class ThreadPool:
    """A named fixed-size worker pool.

    Two internal queues: `_delayed` ordered by ready time, and `_ready`
    ordered by (priority desc, FIFO). Workers migrate due delayed tasks into
    the ready queue, so priority decides ordering among runnable tasks and a
    delayed task cannot starve behind a stream of immediate ones.
    """

    def __init__(self, name: str, worker_count: int = 1):
        self.name = name
        self._delayed = []  # (ready_at, seq, priority, fn, args)
        self._ready = []    # (-priority, seq, fn, args)
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._shutdown = False
        self._workers = [
            threading.Thread(target=self._run, name=f"{name}.{i}", daemon=True)
            for i in range(worker_count)
        ]
        for w in self._workers:
            w.start()

    def enqueue(self, fn, *args, priority: int = 1, delay_s: float = 0.0):
        with self._lock:
            if self._shutdown:
                raise RuntimeError(f"pool {self.name} is shut down")
            seq = next(self._counter)
            if delay_s <= 0:
                heapq.heappush(self._ready, (-priority, seq, fn, args))
            else:
                heapq.heappush(self._delayed, (time.monotonic() + delay_s, seq, priority, fn, args))
            self._not_empty.notify()

    def _run(self):
        while True:
            with self._lock:
                while True:
                    if self._shutdown:
                        return
                    now = time.monotonic()
                    while self._delayed and self._delayed[0][0] <= now:
                        _, seq, priority, fn, args = heapq.heappop(self._delayed)
                        heapq.heappush(self._ready, (-priority, seq, fn, args))
                    if self._ready:
                        _, _, fn, args = heapq.heappop(self._ready)
                        break
                    if self._delayed:
                        self._not_empty.wait(timeout=self._delayed[0][0] - now)
                    else:
                        self._not_empty.wait()
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - a task must never kill its worker
                import logging, traceback

                logging.getLogger("pegasus_tpu.tasking").error(
                    "task raised in pool %s:\n%s", self.name, traceback.format_exc()
                )

    def stop(self):
        """Stop workers; pending (including delayed) tasks are discarded."""
        with self._lock:
            self._shutdown = True
            self._delayed.clear()
            self._ready.clear()
            self._not_empty.notify_all()
        for w in self._workers:
            w.join(timeout=5)


class Timer:
    """Repeating timer posting onto a pool; cancel() stops future firings."""

    def __init__(self, pool: ThreadPool, interval_s: float, fn, *args, first_delay_s=None):
        self._pool = pool
        self._interval = interval_s
        self._fn = fn
        self._args = args
        self._cancelled = threading.Event()
        self._schedule(self._interval if first_delay_s is None else first_delay_s)

    def _schedule(self, delay):
        if not self._cancelled.is_set():
            try:
                self._pool.enqueue(self._fire, delay_s=delay)
            except RuntimeError:
                self._cancelled.set()  # pool shut down: the timer dies with it

    def _fire(self):
        if self._cancelled.is_set():
            return
        try:
            self._fn(*self._args)
        finally:
            self._schedule(self._interval)

    def cancel(self):
        self._cancelled.set()


DEFAULT_POOLS = {
    # name -> worker count; the reference's pool layout (config.ini:82-158)
    "THREAD_POOL_DEFAULT": 4,
    "THREAD_POOL_REPLICATION": 4,
    "THREAD_POOL_LOCAL_APP": 4,
    "THREAD_POOL_COMPACT": 2,
    "THREAD_POOL_INGESTION": 2,
    "THREAD_POOL_META_STATE": 1,
    "THREAD_POOL_FD": 1,
    "THREAD_POOL_REPLICATION_LONG": 2,
    "THREAD_POOL_BLOCK_SERVICE": 2,
    "THREAD_POOL_SLOG": 1,
    "THREAD_POOL_PLOG": 2,
}


class TaskPools:
    """The process's pool container; one per service node."""

    def __init__(self, pool_sizes: dict = None):
        sizes = dict(DEFAULT_POOLS)
        if pool_sizes:
            sizes.update(pool_sizes)
        self._pools = {name: ThreadPool(name, n) for name, n in sizes.items()}

    def pool(self, name: str) -> ThreadPool:
        return self._pools[name]

    def enqueue(self, code: TaskCode, fn, *args, delay_s: float = 0.0):
        self._pools[code.pool].enqueue(fn, *args, priority=code.priority, delay_s=delay_s)

    def enqueue_timer(self, code: TaskCode, interval_s: float, fn, *args, first_delay_s=None):
        return Timer(self._pools[code.pool], interval_s, fn, *args, first_delay_s=first_delay_s)

    def stop(self):
        for p in self._pools.values():
            p.stop()
