"""Service-app container: ini-driven process bootstrap (the dsn_run role).

Mirror of the rDSN app container Pegasus boots through
(src/server/main.cpp:94-111 `dsn_run`; pegasus_service_app.h:31-102;
config.ini [apps.meta]/[apps.replica]/[apps.collector]): a config file
declares which apps run in this process and on which ports; `run()`
instantiates each registered factory and starts it. One process can host
meta, replica, collector, or any mix — the onebox pattern.

Config shape (ini):

    [apps.meta]
    type = meta
    run = true
    port = 34601

    [apps.replica]
    type = replica
    run = true
    port = 34801
    data_dir = /tmp/pegasus/replica

    [pegasus.server]
    meta_servers = 127.0.0.1:34601
"""

import os
import threading

from .config import Config

_FACTORIES = {}


def register_app_factory(type_name: str, factory) -> None:
    """factory(name, config, section) -> app object with start()/stop()."""
    _FACTORIES[type_name] = factory


def _maybe_join_multihost() -> bool:
    """ADVICE r5: the multi-host join hook (parallel.mesh.init_multihost)
    existed but nothing invoked it. Service startup joins the
    jax.distributed job whenever the standard env is present; env-free
    processes never pay the jax import."""
    if not (os.environ.get("PEGASUS_COORDINATOR")
            or os.environ.get("JAX_NUM_PROCESSES")):
        return False
    try:
        from ..parallel.mesh import init_multihost

        return init_multihost()
    except Exception as e:  # noqa: BLE001 - a failed join must not stop
        # the control plane; the data plane degrades to single-host
        print(f"[service-app] multi-host join failed: {e!r}", flush=True)
        return False


class ServiceAppContainer:
    def __init__(self, config: Config):
        self.config = config
        self.apps = {}

    def start(self, only: list = None) -> dict:
        _maybe_join_multihost()
        for section in self.config.sections():
            if not section.startswith("apps."):
                continue
            name = section[len("apps."):]
            if only and name not in only:
                continue
            if not self.config.get_bool(section, "run", True):
                continue
            type_name = self.config.get_string(section, "type", name)
            factory = _FACTORIES.get(type_name)
            if factory is None:
                raise ValueError(f"no app factory registered for {type_name!r}")
            app = factory(name, self.config, section)
            app.start()
            self.apps[name] = app
        return self.apps

    def stop(self) -> None:
        for app in reversed(list(self.apps.values())):
            app.stop()
        self.apps.clear()

    def wait_forever(self) -> None:
        threading.Event().wait()


# ------------------------------------------------------ http info routes


def _version_info(kind: str) -> dict:
    import time as _time

    from .remote_command import VERSION, _START_TIME

    return {"version": VERSION, "server_type": kind,
            "uptime_seconds": int(_time.time() - _START_TIME)}


def _compact_trace_route(path: str) -> dict:
    """GET /compact/trace[?last=N]: the compaction stage-span ring buffer
    plus the device watchdog's liveness state — the JSON twin of the
    `compact-trace-dump` remote command. (`/metrics` itself is served by
    CounterReporter for every role; this is the structured-trace surface.)"""
    from urllib.parse import parse_qs, urlparse

    from ..ops.device_watchdog import WATCHDOG
    from .tracing import COMPACT_TRACER

    q = parse_qs(urlparse(path).query)
    try:
        last = int((q.get("last") or ["100"])[0])
    except ValueError:
        last = 100
    return {"watchdog": WATCHDOG.state(), "spans": COMPACT_TRACER.trace(last)}


def _request_trace_route(path: str) -> dict:
    """GET /requests/trace[?last=N][&slow=1][&id=<hex>]: the serving-path
    request tracer (runtime/tracing.py RequestTracer) — sampled completed
    traces plus the slow-request ledger, the HTTP twin of the
    `request-trace-dump`/`slow-requests` remote commands. ?id= looks a
    single trace up by its hex trace_id; ?slow=1 returns the ledger only."""
    from urllib.parse import parse_qs, urlparse

    from .tracing import REQUEST_TRACER

    q = parse_qs(urlparse(path).query)
    try:
        last = int((q.get("last") or ["50"])[0])
    except ValueError:
        last = 50
    trace_id = (q.get("id") or [""])[0]
    if trace_id:
        return {"trace": REQUEST_TRACER.find(trace_id)}
    if (q.get("slow") or ["0"])[0] not in ("0", ""):
        return {"slow_requests": REQUEST_TRACER.slow_requests(last)}
    return {"traces": REQUEST_TRACER.trace(last),
            "slow_requests": REQUEST_TRACER.slow_requests(last)}


def _jobs_route(path: str) -> dict:
    """GET /jobs[?last=N][&id=<j…>][&active=0]: the background-job
    tracer (runtime/job_trace.py JobTracer) — completed job timelines
    plus the still-open ones, the HTTP twin of the `job-trace` remote
    command and the shell's `job_trace`. ?id= looks one timeline up by
    its job id; ?active=0 returns completed jobs only."""
    from urllib.parse import parse_qs, urlparse

    from .job_trace import JOB_TRACER

    q = parse_qs(urlparse(path).query)
    try:
        last = int((q.get("last") or ["50"])[0])
    except ValueError:
        last = 50
    job_id = (q.get("id") or [""])[0]
    if job_id:
        return {"job": JOB_TRACER.find(job_id)}
    active = (q.get("active") or ["1"])[0] not in ("0", "")
    return {"jobs": JOB_TRACER.jobs(last=last, active=active)}


def _events_route(path: str) -> dict:
    """GET /events[?last=N][&prefix=p][&since=ts]: the process-wide
    structured event ring (runtime/events.py) — the HTTP twin of the
    `events-dump` remote command and the shell's `events`."""
    from urllib.parse import parse_qs, urlparse

    from .events import EVENTS

    q = parse_qs(urlparse(path).query)

    def _num(key, cast, default):
        try:
            return cast((q.get(key) or [""])[0])
        except ValueError:
            return default

    return {"events": EVENTS.snapshot(
        last=_num("last", int, None),
        since=_num("since", float, None),
        prefix=(q.get("prefix") or [None])[0])}


def _metrics_history_route(path: str) -> dict:
    """GET /metrics/history[?seconds=N][&prefix=p][&deltas=1]: the metric
    history ring (runtime/metric_history.py) — the sampled tail of the
    selected counter series, queryable by window."""
    from urllib.parse import parse_qs, urlparse

    from .metric_history import HISTORY

    q = parse_qs(urlparse(path).query)
    try:
        seconds = float((q.get("seconds") or [""])[0])
    except ValueError:
        seconds = None
    return HISTORY.window(
        seconds=seconds, prefix=(q.get("prefix") or [None])[0],
        deltas=(q.get("deltas") or ["0"])[0] not in ("0", ""))


def _incidents_route(path: str) -> dict:
    """GET /incidents[?id=<incident>]: the flight recorder's retained
    incident artifacts — the list, or one full artifact by id."""
    from urllib.parse import parse_qs, urlparse

    from ..collector.flight_recorder import RECORDER

    q = parse_qs(urlparse(path).query)
    incident_id = (q.get("id") or [""])[0]
    if incident_id:
        return {"incident": RECORDER.load(incident_id)}
    return {"incidents": RECORDER.list_incidents()}


def _health_cluster_route(meta_addrs):
    """GET /health/cluster[?scrape=0][&last=N]: the cluster doctor's ONE
    structured verdict (healthy|degraded|critical|inconclusive + named
    causes + evidence) — the HTTP twin of the `cluster-doctor` remote
    command and the shell's `cluster_doctor`. ?scrape=0 skips the
    per-node breaker/queue/slow-request scrapes (meta-state fold only);
    ?last=N bounds the slow-request rollup."""
    from urllib.parse import parse_qs, urlparse

    def route(path):
        from ..collector.cluster_doctor import run_cluster_doctor

        q = parse_qs(urlparse(path).query)
        try:
            last = int((q.get("last") or ["10"])[0])
        except ValueError:
            last = 10
        scrape = (q.get("scrape") or ["1"])[0] not in ("0",)
        return run_cluster_doctor(list(meta_addrs), scrape=scrape,
                                  slow_last=last)

    return route


def _slo_route(path: str) -> dict:
    """GET /slo: the per-table SLO burn-rate verdicts this process
    computed last round ({} on processes that never evaluate — the
    collector is the evaluator; the meta serves its own view when a
    collector runs in-process, e.g. a onebox)."""
    from ..collector.info_collector import latest_slo

    return {"slo": latest_slo()}


def _tables_meta_route(meta):
    """GET /tables on the meta: fold the TABLE_STATS beacon fragments
    (ISSUE 18 — every serving process ships its per-table ledger totals
    keyed tables@pid:<pid>; the meta diverts them into _node_tables so
    replica-state consumers never see them) into one cluster-wide
    per-table view + the top-k capacity attribution."""
    def route(path):
        from .table_stats import fold_snapshots, top_k

        frags = []
        with meta._lock:
            for tables in meta._node_tables.values():
                for st in tables.values():
                    frags.append(st.get("tables", {}))
        folded = fold_snapshots(frags)
        return {"tables": folded,
                "top": top_k(folded,
                             int(os.environ.get("PEGASUS_TABLE_TOPK", "5")))}

    return route


def _meta_http_routes(meta) -> dict:
    """The meta's rDSN-http_service analogues: /version, /meta/cluster_info,
    /meta/apps, /meta/app?name=<app>."""
    from urllib.parse import parse_qs, urlparse

    def cluster_info(path):
        with meta._lock:
            alive = meta._alive_nodes_locked()
            return {"meta_server": "self", "app_count": len(meta._apps),
                    "node_count": len(meta._nodes), "alive_nodes": alive}

    def apps(path):
        with meta._lock:
            return [{"app_name": a.app_name, "app_id": a.app_id,
                     "partition_count": a.partition_count,
                     "replica_count": a.replica_count, "status": a.status}
                    for a in meta._apps.values()]

    def app(path):
        q = parse_qs(urlparse(path).query)
        name = (q.get("name") or [""])[0]
        with meta._lock:
            a = meta._apps.get(name)
            if a is None:
                return {"error": f"no app {name!r}"}
            return {"app_name": a.app_name, "app_id": a.app_id,
                    "partition_count": a.partition_count,
                    "envs": a.envs_json,
                    "partitions": [{
                        "pidx": pc.pidx, "ballot": pc.ballot,
                        "primary": pc.primary,
                        "secondaries": list(pc.secondaries)}
                        for pc in meta._parts[a.app_id]]}

    return {"/version": lambda p: _version_info("meta"),
            "/meta/cluster_info": cluster_info,
            "/meta/apps": apps,
            "/meta/app": app,
            "/compact/trace": _compact_trace_route,
            "/requests/trace": _request_trace_route,
            "/jobs": _jobs_route,
            "/events": _events_route,
            "/metrics/history": _metrics_history_route,
            "/incidents": _incidents_route,
            "/tables": _tables_meta_route(meta),
            "/slo": _slo_route}


def _replica_http_routes(stub) -> dict:
    """/version + /replica/info on replica nodes."""

    def info(path):
        with stub._lock:
            reps = list(stub._replicas.values())
        return [{"app_name": r.app_name, "app_id": r.app_id, "pidx": r.pidx,
                 "status": r.status, "ballot": r.ballot,
                 "last_committed": r.last_committed,
                 "last_prepared": r.last_prepared,
                 "last_durable": r.server.engine.last_durable_decree()}
                for r in reps]

    return {"/version": lambda p: _version_info("replica"),
            "/replica/info": info,
            "/compact/trace": _compact_trace_route,
            "/requests/trace": _request_trace_route,
            "/jobs": _jobs_route,
            "/events": _events_route,
            "/metrics/history": _metrics_history_route}


# ---------------------------------------------------------- built-in apps


class MetaApp:
    def __init__(self, name, config: Config, section: str):
        from ..meta.meta_server import MetaServer
        from ..rpc.transport import RpcServer

        state_dir = config.get_string(section, "state_dir",
                                      os.path.join("pegasus-data", "meta"))
        state_path = os.path.join(state_dir, "state.json")
        self.rpc = RpcServer(config.get_string(section, "host", "127.0.0.1"),
                             config.get_int(section, "port", 34601))
        # meta HA: with >1 configured meta, run leader election over the
        # shared state dir (meta/election.py; every meta's state_dir must
        # point at the SAME shared path — the ZK-stand-in). Single meta:
        # no election, always leader.
        metas = config.get_list("pegasus.server", "meta_servers", ())
        self.election = None
        if len(metas) > 1:
            from ..meta.election import MetaElection

            self.election = MetaElection(
                state_path + ".lock", self.address,
                lease_seconds=config.get_float(section,
                                               "election_lease_seconds", 6.0),
                on_acquire=lambda: self.meta.reload_state(),
                # claims must exceed the durable state epoch even when the
                # lease file's lineage was lost (fresh mount, manual rm)
                claim_floor=lambda: self.meta._read_state_epoch())
        self.meta = MetaServer(
            state_path,
            fd_grace_seconds=config.get_float("failure_detector",
                                              "grace_seconds", 22.0),
            election=self.election)
        for code, fn in self.meta.rpc_handlers().items():
            self.rpc.register(code, fn)
        from .toollets import install_toollets

        install_toollets(self.rpc, config.get_list("core", "toollets", ()))
        self._fd_timer = None
        self._fd_interval = config.get_float("failure_detector",
                                             "check_interval_seconds", 5.0)
        # version/info HTTP endpoints (reference rDSN http_service on meta:
        # /version, /meta/cluster_info, /meta/app?name=...)
        http_port = config.get_int(section, "http_port", -1)
        self.reporter = None
        if http_port >= 0:
            from ..collector.reporter import CounterReporter

            # started here, not in start(): BaseServer.shutdown() hangs
            # forever unless serve_forever ran, so a start() that dies
            # before reaching the reporter would make stop() deadlock
            routes = _meta_http_routes(self.meta)
            routes["/health/cluster"] = _health_cluster_route([self.address])
            self.reporter = CounterReporter(
                port=http_port, routes=routes).start()

    @property
    def address(self):
        return f"{self.rpc.address[0]}:{self.rpc.address[1]}"

    def start(self):
        self._stopped = False
        self.rpc.start()
        if self.election is not None:
            self.election.start()
        self._schedule_fd()
        from .metric_history import HISTORY

        HISTORY.start()
        self._history_ref = True
        return self

    def _is_leader(self) -> bool:
        return self.election is None or self.election.is_leader()

    def _schedule_fd(self):
        def tick():
            try:
                if self._is_leader():  # followers watch, never act
                    self.meta.check_leases()
                    # heal quarantined replicas (ISSUE 17): a beacon
                    # reporting QUARANTINED is a lost copy — reconfigure
                    # + re-seed on the same cadence as lease expiry
                    self.meta.repair_quarantined()
            except Exception as e:  # a fenced persist (or any failure)
                # must not kill the FD timer for the process lifetime
                print(f"[meta] fd tick failed: {e!r}", flush=True)
            if self._stopped:
                return
            self._fd_timer = threading.Timer(self._fd_interval, tick)
            self._fd_timer.daemon = True
            self._fd_timer.start()

        self._fd_timer = threading.Timer(self._fd_interval, tick)
        self._fd_timer.daemon = True
        self._fd_timer.start()

        # backup policies + dup-progress env refresh run on their OWN timer:
        # a long synchronous backup inside the FD tick would stall lease
        # checks for its whole duration
        def policy_tick():
            try:
                if self._is_leader():
                    self.meta.run_backup_policies()
                    self.meta.push_dup_envs()
                    self.meta.purge_expired_dropped()
            except Exception as e:  # policy failure must not kill the timer
                print(f"[meta] maintenance tick failed: {e!r}", flush=True)
            if self._stopped:
                return  # stop() raced an in-flight tick: do not re-arm
            self._policy_timer = threading.Timer(
                max(self._fd_interval, 5.0), policy_tick)
            self._policy_timer.daemon = True
            self._policy_timer.start()

        self._policy_timer = threading.Timer(
            max(self._fd_interval, 5.0), policy_tick)
        self._policy_timer.daemon = True
        self._policy_timer.start()

    def stop(self):
        # refcounted sampler: drop OUR ref exactly once (a double stop,
        # or stop-before-start, must not steal a sibling app's ref)
        if getattr(self, "_history_ref", False):
            self._history_ref = False
            from .metric_history import HISTORY

            HISTORY.stop()
        self._stopped = True
        if self._fd_timer:
            self._fd_timer.cancel()
        if getattr(self, "_policy_timer", None):
            self._policy_timer.cancel()
        if self.election is not None:
            self.election.stop()
        if self.reporter:
            self.reporter.stop()
        self.rpc.stop()


class ReplicaApp:
    def __init__(self, name, config: Config, section: str):
        from ..engine import EngineOptions
        from ..replication.replica_stub import ReplicaStub

        metas = config.get_list("pegasus.server", "meta_servers",
                                ["127.0.0.1:34601"])
        backend = config.get_string("pegasus.server", "compaction_backend", "cpu")
        compression = config.get_string("pegasus.server", "sst_compression",
                                        "none")
        # multi-chip manual compaction over every visible device (the
        # engine resolves the mesh lazily; <2 devices = single-chip)
        sharded = config.get_bool("pegasus.server", "sharded_compaction",
                                  False)
        data_dir = config.get_string(section, "data_dir",
                                     os.path.join("pegasus-data", name))

        def options_factory():
            return EngineOptions(backend=backend, compression=compression,
                                 sharded_compaction=sharded)

        # [pegasus.clusters]: name = comma-separated meta list; the
        # duplication target directory (reference config.ini cluster section)
        remote_clusters = {}
        if "pegasus.clusters" in config.sections():
            for key in config.keys("pegasus.clusters"):
                remote_clusters[key] = config.get_list("pegasus.clusters",
                                                       key, [])
        # shared-nothing partition-group executors: PEGASUS_SERVE_GROUPS
        # (or [apps.replica] serve_groups) > 1 forks that many worker
        # processes, each owning a disjoint partition set, behind one
        # public acceptor/router (replication/serve_groups.py)
        groups = int(os.environ.get("PEGASUS_SERVE_GROUPS")
                     or config.get_int(section, "serve_groups", 1))
        if groups > 1:
            from ..replication.serve_groups import GroupedReplicaNode

            self.stub = GroupedReplicaNode(
                data_dir, list(metas),
                host=config.get_string(section, "host", "127.0.0.1"),
                port=config.get_int(section, "port", 0),
                groups=groups, backend=backend, compression=compression,
                sharded_compaction=sharded,
                remote_clusters=remote_clusters,
                cluster_id=config.get_int("pegasus.server", "cluster_id", 1))
        else:
            self.stub = ReplicaStub(
                data_dir, list(metas),
                host=config.get_string(section, "host", "127.0.0.1"),
                port=config.get_int(section, "port", 0),
                options_factory=options_factory,
                remote_clusters=remote_clusters,
                cluster_id=config.get_int("pegasus.server", "cluster_id", 1))
        self._beacon = config.get_float("failure_detector",
                                        "beacon_interval_seconds", 1.0)
        if hasattr(self.stub, "rpc"):
            # toollets wrap the in-process serverlet; a grouped node's
            # serving happens inside the worker processes (each worker's
            # own stub could grow toollets, but the router has no handlers)
            from .toollets import install_toollets

            install_toollets(self.stub.rpc,
                             config.get_list("core", "toollets", ()),
                             command_service=self.stub.commands)
        http_port = config.get_int(section, "http_port", -1)
        self.reporter = None
        if http_port >= 0:
            from ..collector.reporter import CounterReporter

            self.reporter = CounterReporter(
                port=http_port,
                routes=_replica_http_routes(self.stub)).start()

    @property
    def address(self):
        return self.stub.address

    def start(self):
        self.stub.start(self._beacon)
        return self

    def stop(self):
        if self.reporter:
            self.reporter.stop()
        self.stub.stop()


class CollectorApp:
    """The third server role (reference pegasus_service_app.h:31-102
    `pegasus::server::info_collector_app`): cluster stat scraping + hotspot
    analysis + the availability canary, with its own RPC port so the shell
    and tests can query what it publishes."""

    def __init__(self, name, config: Config, section: str):
        import json

        from ..collector.available_detector import AvailableDetector
        from ..collector.info_collector import InfoCollector
        from ..rpc.transport import RpcServer
        from .remote_command import RemoteCommandService

        self.metas = config.get_list("pegasus.server", "meta_servers",
                                     ["127.0.0.1:34601"])
        self._stopping = False
        self.detect_table = config.get_string(section, "available_detect_app",
                                              "test")
        self.collector = InfoCollector(
            list(self.metas),
            interval_seconds=config.get_float(section, "interval_seconds", 10.0))
        # cluster compaction scheduler (ISSUE 10): PEGASUS_SCHED=1 arms
        # the debt-driven control loop; the info collector's confirmed
        # read-hot pins and slow-request rollup feed the decision fold.
        # Off (the default), engines run their local triggers untouched.
        self.scheduler = None
        if os.environ.get("PEGASUS_SCHED", "") == "1":
            from ..collector.compact_scheduler import CompactScheduler

            def _hot_gpids():
                # read_residency publishes copy-on-write: lock-free
                # iteration always sees a stable snapshot
                return {t["gpid"]
                        for t in dict(self.collector.read_residency).values()}

            self.scheduler = CompactScheduler(
                list(self.metas), pool=self.collector.pool,
                hot_fn=_hot_gpids,
                slow_fn=lambda: len(self.collector.cluster_slow_requests))
        self.detector = AvailableDetector(
            list(self.metas), table_name=self.detect_table,
            interval_seconds=config.get_float(section,
                                              "detect_interval_seconds", 1.0))
        self.rpc = RpcServer(config.get_string(section, "host", "127.0.0.1"),
                             config.get_int(section, "port", 0))
        self.commands = RemoteCommandService()
        self.commands.register_defaults(node_kind="collector",
                                        describe=lambda: "collector")

        def info(args):
            return json.dumps({
                "availability": self.detector.report(),
                "hotspots": self.collector.hotspots,
                "hotkeys": self.collector.hotkey_results,
                "app_stats": self.collector.app_stats,
                "compact_stats": self.collector.compact_stats,
                "lag_stats": self.collector.lag_stats,
                "slow_requests": self.collector.cluster_slow_requests,
                "compact_sched": (
                    dict(self.scheduler.status(), enabled=True)
                    if self.scheduler else {"enabled": False}),
            })

        self.commands.register("collector-info", info)

        def compact_sched_status(args):
            """compact-sched-status — the scheduler's last decision round
            (per-partition policy + reasons, delivery map, errors); the
            replica-side command of the same name shows the tokens as
            the engines see them."""
            if self.scheduler is None:
                return json.dumps({"enabled": False})
            return json.dumps(dict(self.scheduler.status(), enabled=True),
                              indent=1)

        self.commands.register("compact-sched-status", compact_sched_status)

        def cluster_doctor(args):
            """cluster-doctor [last] — one structured cluster-health
            verdict (the collector is the doctor's native home: it
            already scrapes every node)."""
            from ..collector.cluster_doctor import run_cluster_doctor

            last = int(args[0]) if args else 10
            return json.dumps(run_cluster_doctor(
                list(self.metas), pool=self.collector.pool,
                slow_last=last), indent=1)

        def trigger_audit(args):
            """trigger-audit [app ...] — run the decree-anchored
            consistency audit across every (or the named) app."""
            from ..collector.cluster_doctor import run_cluster_audit

            return json.dumps(run_cluster_audit(
                list(self.metas), pool=self.collector.pool,
                apps=list(args) or None), indent=1)

        def trigger_incident(args):
            """trigger-incident [reason] — manually capture a flight-
            recorder incident NOW: pull every alive node's event ring +
            metric-history window + slow ledger + recent traces, align
            them on one anchor, run the first-cause heuristic and retain
            the artifact (served as GET /incidents + shell
            flight_recorder)."""
            from ..collector.flight_recorder import RECORDER

            reason = " ".join(args) if args else "manual trigger"
            inc = RECORDER.capture(list(self.metas), reason=reason,
                                   trigger="manual",
                                   pool=self.collector.pool)
            return json.dumps({"incident": inc["id"],
                               "path": inc.get("path", ""),
                               "first_cause": inc.get("first_cause")},
                              indent=1)

        self.commands.register("cluster-doctor", cluster_doctor)
        self.commands.register("trigger-audit", trigger_audit)
        self.commands.register("trigger-incident", trigger_incident)
        self.rpc.register("RPC_CLI_CLI_CALL", self.commands.rpc_handler)
        http_port = config.get_int(section, "http_port", -1)
        self.reporter = None
        if http_port >= 0:
            from ..collector.reporter import CounterReporter

            def tables_route(path):
                # the collector's own cluster fold (collect_table_stats):
                # copy-on-write published, so this read is lock-free
                return {"tables": self.collector.table_stats,
                        "top": self.collector.table_top}

            self.reporter = CounterReporter(
                port=http_port,
                routes={"/compact/trace": _compact_trace_route,
                        "/requests/trace": _request_trace_route,
                        "/jobs": _jobs_route,
                        "/events": _events_route,
                        "/metrics/history": _metrics_history_route,
                        "/incidents": _incidents_route,
                        "/tables": tables_route,
                        "/slo": _slo_route,
                        "/health/cluster":
                            _health_cluster_route(self.metas)}).start()

    @property
    def address(self):
        return f"{self.rpc.address[0]}:{self.rpc.address[1]}"

    def _ensure_probe_table(self) -> bool:
        """Auto-create the canary table (the reference's onebox ships a
        'test' table; a collector must not require manual DDL). -> True
        once a meta acknowledged the create."""
        from ..meta import messages as mm
        from ..meta.meta_server import RPC_CM_CREATE_APP
        from ..rpc import codec
        from ..rpc.transport import RpcConnection

        for m in self.metas:
            host, _, port = m.rpartition(":")
            try:
                conn = RpcConnection((host, int(port)))
                try:
                    conn.call(RPC_CM_CREATE_APP, codec.encode(
                        mm.CreateAppRequest(self.detect_table, 8, 3)),
                        timeout=10.0)
                    return True
                finally:
                    conn.close()
            except OSError:
                continue
        return False

    def _ensure_probe_table_loop(self):
        """The collector routinely boots BEFORE (or restarts independently
        of) the meta; keep trying until a create lands — no deadline, a
        meta that appears an hour later must still get its canary table
        (daemon thread; exits with the process or on stop())."""
        import time as _time

        while not self._stopping:
            try:
                if self._ensure_probe_table():
                    return
            except Exception:
                pass
            _time.sleep(1.0)

    def start(self):
        self._stopping = False
        self.rpc.start()
        from .metric_history import HISTORY
        from .tasking import spawn_thread

        HISTORY.start()
        self._history_ref = True
        spawn_thread(self._ensure_probe_table_loop, daemon=True)
        self.collector.start()
        if self.scheduler is not None:
            self.scheduler.start()
        self.detector.start()
        print(f"[pegasus-tpu] collector rpc on {self.address}", flush=True)
        return self

    def stop(self):
        # refcounted sampler: drop OUR ref exactly once (a double stop,
        # or stop-before-start, must not steal a sibling app's ref)
        if getattr(self, "_history_ref", False):
            self._history_ref = False
            from .metric_history import HISTORY

            HISTORY.stop()
        self._stopping = True
        if self.reporter:
            self.reporter.stop()
        self.detector.stop()
        if self.scheduler is not None:
            self.scheduler.stop()  # before the collector closes their pool
        self.collector.stop()
        self.rpc.stop()


class CompactOffloadApp:
    """The fourth server role (ISSUE 14): one device-owning compaction
    service per TPU host, serving many cpu-only replica nodes. Config:

        [apps.compact_offload]
        run = true
        port = 34901            ; what nodes' placement leases dial
        backend = tpu           ; default: pegasus.server compaction_backend
        job_dir = ...           ; staged-run + job spool (default per-app)

    Point the collector's scheduler at it with
    ``PEGASUS_OFFLOAD_SERVICES=host:34901`` and the fold starts emitting
    (when, where) pairs against its free merge budget."""

    def __init__(self, name, config: Config, section: str):
        from ..replication.compact_offload import CompactOffloadService

        backend = config.get_string(
            section, "backend",
            config.get_string("pegasus.server", "compaction_backend", "cpu"))
        root = config.get_string(section, "job_dir",
                                 os.path.join("pegasus-data", name))
        self.svc = CompactOffloadService(
            root,
            host=config.get_string(section, "host", "127.0.0.1"),
            port=config.get_int(section, "port", 0),
            backend=backend)

    @property
    def address(self):
        return self.svc.address

    def start(self):
        from .metric_history import HISTORY

        self.svc.start()
        HISTORY.start()
        self._history_ref = True
        print(f"[pegasus-tpu] compaction offload service on "
              f"{self.svc.address} (backend {self.svc.backend})", flush=True)
        return self

    def stop(self):
        if getattr(self, "_history_ref", False):
            self._history_ref = False
            from .metric_history import HISTORY

            HISTORY.stop()
        self.svc.stop()


register_app_factory("meta", MetaApp)
register_app_factory("replica", ReplicaApp)
register_app_factory("collector", CollectorApp)
register_app_factory("compact_offload", CompactOffloadApp)
