"""Toollets: pluggable tracer / profiler / fault injector for the RPC layer.

The rDSN toollet surface (SURVEY.md §2.4 'Toollets'; reference
config.ini:44-46 `toollets = tracer, profiler, fault_injector`,
profiler per-task-code counters :531-598): each toollet is an RpcServer
middleware wrapping every registered handler.

  tracer   — ring buffer of (ts, code, seq, dur_us, req/resp sizes) spans,
             dumpable via the `tracer-dump` remote command.
  profiler — per-task-code qps + latency percentile + size counters.
  fault_injector — dsn::fail-style actions per task code:
             cfg("rpc.<CODE>", "10%return()") drops/errors matching RPCs,
             "delay(ms)" injects latency.

Enable from ini: [core] toollets = tracer, profiler  (service_app wires
them onto every app's RpcServer).
"""

import collections
import threading
import time

from . import fail_points
from .perf_counters import counters


class Tracer:
    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=capacity)

    def middleware(self, code, header, body, next_fn):
        t0 = time.perf_counter()
        out = next_fn(header, body)
        dur_us = int((time.perf_counter() - t0) * 1e6)
        with self._lock:
            self._spans.append((time.time(), code, header.seq, dur_us,
                                len(body), len(out) if out else 0))
        return out

    def dump(self, last: int = 100) -> str:
        with self._lock:
            spans = list(self._spans)[-last:]
        return "\n".join(
            f"{ts:.6f} {code} seq={seq} {dur}us req={rq}B resp={rs}B"
            for ts, code, seq, dur, rq, rs in spans) or "no spans"


class Profiler:
    """profiler::*.qps / .latency.server / .size.{request,response}.server"""

    def middleware(self, code, header, body, next_fn):
        t0 = time.perf_counter()
        out = next_fn(header, body)
        counters.rate(f"profiler.{code}.qps").increment()
        counters.percentile(f"profiler.{code}.latency_us").set(
            int((time.perf_counter() - t0) * 1e6))
        counters.percentile(f"profiler.{code}.size.request").set(len(body))
        if out:
            counters.percentile(f"profiler.{code}.size.response").set(len(out))
        return out


class FaultInjector:
    """Per-task-code fault injection through the fail-point registry:
    fail_points.cfg('rpc.RPC_RRDB_RRDB_GET', '10%return()') makes 10% of
    gets fail; 'delay(50)' style argument on the print verb adds latency."""

    def middleware(self, code, header, body, next_fn):
        fp = fail_points.fail_point(f"rpc.{code}")
        if fp is not None:
            verb, arg = fp
            if verb == "return":
                from ..rpc.transport import ERR_BUSY, RpcError

                raise RpcError(ERR_BUSY, f"fault injected: {arg or 'drop'}")
            if verb == "print" and arg.startswith("delay"):
                try:
                    ms = float(arg[arg.index("(") + 1 : arg.rindex(")")] or 0)
                except ValueError:
                    ms = 0
                time.sleep(ms / 1000.0)
        return next_fn(header, body)


TOOLLETS = {"tracer": Tracer, "profiler": Profiler,
            "fault_injector": FaultInjector}


def install_toollets(rpc_server, names, command_service=None):
    """Instantiate the named toollets onto an RpcServer; returns them.
    Registers `tracer-dump` when a RemoteCommandService is provided."""
    out = {}
    for name in names:
        cls = TOOLLETS.get(name.strip())
        if cls is None:
            continue
        t = cls()
        rpc_server.add_middleware(t.middleware)
        out[name.strip()] = t
    tracer = out.get("tracer")
    if tracer is not None and command_service is not None:
        command_service.register(
            "tracer-dump",
            lambda args: tracer.dump(int(args[0]) if args else 100))
    return out
