"""Lock-order deadlock detector: named locks + a process-wide
acquisition graph (ISSUE 9's runtime half of the concurrency lint plane).

Every review round since PR 4 caught a lock/lifecycle race by hand
(prime-vs-release budget races, the PR 5 shutdown abort, the read
coalescer's dead-leader wedge). The static lock-discipline pass
(tools/analyze/lock_discipline.py) proves guarded state is only touched
under its lock; what it CANNOT see is lock *ordering* — thread 1 taking
A then B while thread 2 takes B then A deadlocks even though every
access is perfectly guarded. This module closes that gap the way
production systems do (rDSN's lock checker, abseil's
ABSL_GUARDED_BY+deadlock detector): locks get NAMES, and under
``PEGASUS_LOCKRANK=1`` every acquisition records a held-while-acquiring
edge ``held -> acquiring`` in one process-wide graph. An edge that
closes a cycle (the classic AB/BA inversion, or any longer loop) is a
deadlock WAITING for the right interleaving — it is reported
immediately, naming both acquisition sites and the cycle path, without
needing the unlucky schedule to actually happen. Tier-1 runs with the
detector armed (tests/conftest.py), so every onebox / group-worker /
chaos test doubles as a lock-order regression test.

Usage — modules create locks through the factories instead of raw
``threading`` primitives::

    self._lock = lockrank.named_rlock("engine.lock")
    self._flush_lock = lockrank.named_lock("engine.flush")
    self._prime_cv = lockrank.named_condition("engine.prime_cv",
                                              self._lock)

With ``PEGASUS_LOCKRANK`` unset/0 the factories return the raw
``threading`` primitives — zero overhead, zero behavior change; the
detector is a test/debug mode, not a production tax.

Semantics:
  * names identify lock RANKS, not instances: two partitions' engine
    locks share the name "engine.lock", and same-name edges are skipped
    (cross-instance ordering of peers is not expressible as a rank).
  * ``Condition.wait`` releases the underlying lock — tracking follows,
    so a held-across-wait false edge cannot form.
  * a violation is recorded once per (held, acquiring) edge pair:
    printed to stderr, appended to ``GRAPH.violations``, and appended as
    a JSON line to ``$PEGASUS_LOCKRANK_FILE`` when set (how group-worker
    subprocesses report back to the parent test session).
    ``PEGASUS_LOCKRANK=raise`` additionally raises LockOrderError at the
    acquisition site (unit tests; never the tier-1 default — recording
    keeps the run going so one cycle cannot cascade into noise).

Env knobs: PEGASUS_LOCKRANK (0 | 1 | raise), PEGASUS_LOCKRANK_FILE
(violation sink for multi-process runs).
"""

import json
import os
import sys
import threading

_MODULE_FILE = os.path.abspath(__file__)


def enabled() -> bool:
    """Read per factory call (cheap), so tests/conftest can arm the
    detector before the first pegasus_tpu import without a config dance."""
    return os.environ.get("PEGASUS_LOCKRANK", "0") not in ("", "0")


def _raise_mode() -> bool:
    return os.environ.get("PEGASUS_LOCKRANK", "0") == "raise"


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the lock-order graph."""


class _Graph:
    """Process-wide lock-order graph. Edge a->b = "a was held while b
    was acquired", with the first witnessed (held_site, acquire_site)
    pair kept as evidence."""

    def __init__(self):
        # a RAW lock on purpose: the detector must never track itself
        self._mu = threading.Lock()
        self.edges = {}       #: guarded_by self._mu
        self.violations = []  #: guarded_by self._mu
        self._reported = set()  #: guarded_by self._mu

    def _path(self, src: str, dst: str):  #: requires self._mu
        """DFS path src -> ... -> dst over current edges, or None."""
        stack, seen = [(src, [src])], {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.edges.get(node, {}):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def record(self, held: str, held_site: str, acquiring: str,
               acq_site: str):
        """Record edge held->acquiring; -> violation dict if it closes a
        cycle (first report per edge pair), else None."""
        with self._mu:
            slot = self.edges.setdefault(held, {})
            if acquiring in slot:
                # known edge: any cycle through it was detected when its
                # closing edge was FIRST inserted (every cycle has one),
                # so the steady-state cost per acquire is one dict hit
                return None
            # adding held->acquiring closes a cycle iff acquiring already
            # reaches held
            path = self._path(acquiring, held)
            slot[acquiring] = (held_site, acq_site)
            if path is None:
                return None
            key = (held, acquiring)
            if key in self._reported:
                return None
            self._reported.add(key)
            # evidence for the reverse direction: the first edge of the
            # acquiring->...->held path already in the graph
            fwd_sites = self.edges.get(path[0], {}).get(path[1], ("?", "?"))
            violation = {
                "cycle": path + [acquiring],
                "held": held, "held_site": held_site,
                "acquiring": acquiring, "acquire_site": acq_site,
                "reverse_edge": {"from": path[0], "to": path[1],
                                 "held_site": fwd_sites[0],
                                 "acquire_site": fwd_sites[1]},
                "thread": threading.current_thread().name,
                "pid": os.getpid(),
            }
            self.violations.append(violation)
        return violation

    def snapshot(self) -> dict:
        with self._mu:
            return {"edges": {a: sorted(b) for a, b in self.edges.items()},
                    "violations": list(self.violations)}

    def reset(self) -> None:
        """Test hook: forget every edge and violation."""
        with self._mu:
            self.edges.clear()
            self.violations.clear()
            self._reported.clear()


GRAPH = _Graph()

_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


_PATH_MEMO = {}  # raw co_filename -> short display path ('' = skip frame)


def _display_path(fn: str) -> str:
    """Memoized: '' for detector/threading frames, else the short path.
    Runs on every tracked acquire — no per-call path math."""
    cached = _PATH_MEMO.get(fn)
    if cached is None:
        if os.path.abspath(fn) == _MODULE_FILE or fn.endswith("threading.py"):
            cached = ""
        else:
            cached = os.path.relpath(fn) if fn.startswith("/") else fn
        _PATH_MEMO[fn] = cached
    return cached


def _site() -> str:
    """file:line of the acquisition, skipping detector/threading frames."""
    f = sys._getframe(1)
    while f is not None:
        p = _display_path(f.f_code.co_filename)
        if p:
            return f"{p}:{f.f_lineno}"
        f = f.f_back
    return "?:0"


def _report(violation: dict, to_sink: bool = True) -> None:
    msg = (f"[lockrank] LOCK-ORDER CYCLE "
           f"{' -> '.join(violation['cycle'])}: "
           f"{violation['held']} (held, acquired at "
           f"{violation['held_site']}) while acquiring "
           f"{violation['acquiring']} at {violation['acquire_site']}; "
           f"reverse edge {violation['reverse_edge']['from']} -> "
           f"{violation['reverse_edge']['to']} witnessed at "
           f"{violation['reverse_edge']['acquire_site']}")
    print(msg, file=sys.stderr, flush=True)
    sink = os.environ.get("PEGASUS_LOCKRANK_FILE") if to_sink else None
    if sink:
        try:
            with open(sink, "a") as f:
                f.write(json.dumps(violation) + "\n")
        except OSError:
            pass
    if _raise_mode():
        raise LockOrderError(msg)


class _NamedBase:
    """Shared acquire/release tracking over an inner threading lock."""

    def __init__(self, name: str, inner, graph: _Graph = None):
        self.name = name
        self._inner = inner
        self._graph = graph or GRAPH

    def _on_acquired(self) -> None:
        held = _held()
        site = _site()
        for hname, hsite in held:
            if hname != self.name:
                v = self._graph.record(hname, hsite, self.name, site)
                if v is not None:
                    # private graphs (tests) never write the shared sink
                    _report(v, to_sink=self._graph is GRAPH)
        held.append((self.name, site))

    def _on_released(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                self._on_acquired()
            except BaseException:
                # raise-mode violation: surface it UNLOCKED, or the
                # report itself would leave the lock dangling
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        self._inner.release()
        self._on_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self):
        return f"<{type(self).__name__} {self.name} {self._inner!r}>"


class NamedLock(_NamedBase):
    def __init__(self, name: str, graph: _Graph = None):
        super().__init__(name, threading.Lock(), graph)


class NamedRLock(_NamedBase):
    """Named re-entrant lock. Implements the _release_save /
    _acquire_restore / _is_owned trio threading.Condition probes for, so
    a Condition built over it fully releases the recursion on wait and
    the held-stack tracking follows."""

    def __init__(self, name: str, graph: _Graph = None):
        super().__init__(name, threading.RLock(), graph)

    def _pop_all(self) -> int:
        held = _held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                n += 1
        return n

    def _release_save(self):
        n = self._pop_all()
        return (self._inner._release_save(), n)

    def _acquire_restore(self, state):
        inner_state, n = state
        self._inner._acquire_restore(inner_state)
        held = _held()
        site = _site()
        for _ in range(n):
            held.append((self.name, site))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def locked(self) -> bool:  # RLock has no .locked() pre-3.12
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


def named_lock(name: str, _graph: _Graph = None):
    """threading.Lock, tracked under PEGASUS_LOCKRANK=1."""
    return NamedLock(name, _graph) if enabled() else threading.Lock()


def named_rlock(name: str, _graph: _Graph = None):
    """threading.RLock, tracked under PEGASUS_LOCKRANK=1."""
    return NamedRLock(name, _graph) if enabled() else threading.RLock()


def named_condition(name: str, lock=None, _graph: _Graph = None):
    """threading.Condition over a named lock. Pass an existing
    named_lock/named_rlock to share it (the db's prime_cv rides the
    engine lock); None creates a fresh named RLock (Condition's own
    default, so wait/notify semantics are unchanged)."""
    if lock is None and enabled():
        lock = NamedRLock(name, _graph)
    return threading.Condition(lock)
