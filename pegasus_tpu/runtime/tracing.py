"""Stage-span tracing for the compaction pipeline.

The RPC layer already has a toollet tracer (runtime/toollets.py), but every
bench wedge recorded so far (BENCH_r05: "tpu lane exceeded 360s (device
tunnel wedged mid-init or mid-run)") happened BELOW the RPC layer, inside
the compaction pipeline: device init, host pack, H2D upload, the sort/merge
kernel, or the survivor gather. This module is the in-pipeline probe that
LUDA/RESYSTANCE-style offload perf work needs before any kernel tuning is
trustworthy: nestable stage spans with wall time, record and byte counts,

  - ring-buffered like the RPC tracer: the recent spans dump through the
    `compact-trace-dump` remote command and the `/compact/trace` HTTP
    route (runtime/service_app.py);
  - exported into the process-wide perf-counter registry under
    `compact.stage.<name>.*` (rate counters for span/record/byte
    throughput, a percentile counter for duration), so `/metrics`,
    `perf-counters*`, and the collector all read ONE registry;
  - visible while still OPEN (open_stages / innermost_open): the
    device-health watchdog (ops/device_watchdog.py) reads the live span
    stack to attribute a wedge to the exact stage that never returned.

Stage names used by the pipeline: compact > pack / h2d / device / gather,
plus sst_write at the engine write-out. Spans nest (depth is recorded);
a stage entered recursively (blockwise range decomposition re-enters
`compact`) shows up once per entry, so session sums for such stages count
the nested time once per level — read `calls` alongside `s`.

A TraceSession aggregates every span closed in its thread while active;
bench.py and the manual-compact service record per-stage breakdowns from
it (the `trace` detail in BENCH_*.json).
"""

import collections
import threading
import time
from contextlib import contextmanager

from .perf_counters import counters


class TraceSession:
    """Per-stage aggregate of the spans closed (in the owning thread)
    while the session was active: stage -> {s, calls, records, bytes}."""

    def __init__(self):
        self.stages = {}
        self.started_at = time.time()

    def _add(self, stage: str, dur_s: float, records: int, nbytes: int):
        agg = self.stages.setdefault(
            stage, {"s": 0.0, "calls": 0, "records": 0, "bytes": 0})
        agg["s"] += dur_s
        agg["calls"] += 1
        agg["records"] += records
        agg["bytes"] += nbytes

    def summary(self) -> dict:
        """JSON-ready copy with rounded wall times (stage order = first
        close order, which for a straight-line pipeline is stage order)."""
        return {k: dict(v, s=round(v["s"], 6))
                for k, v in self.stages.items()}


class StageTracer:
    def __init__(self, capacity: int = 4096, prefix: str = "compact"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=capacity)
        self._local = threading.local()
        # thread ident -> [(stage, started_wall_ts), ...] innermost LAST;
        # shared (not thread-local) so the watchdog thread can read which
        # stage another thread is currently stuck in
        self._open = {}

    # ----------------------------------------------------------- span API

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _session_list(self) -> list:
        s = getattr(self._local, "sessions", None)
        if s is None:
            s = self._local.sessions = []
        return s

    @contextmanager
    def span(self, stage: str, records: int = 0, nbytes: int = 0):
        """Time one pipeline stage. Yields a mutable {records, bytes} box
        so counts discovered mid-span (e.g. survivor count) can be added
        before the span closes."""
        stack = self._stack()
        depth = len(stack)
        stack.append(stage)
        tid = threading.get_ident()
        with self._lock:
            self._open.setdefault(tid, []).append((stage, time.time()))
        box = {"records": records, "bytes": nbytes}
        t0 = time.perf_counter()
        try:
            yield box
        finally:
            dur_s = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                open_list = self._open.get(tid)
                if open_list:
                    open_list.pop()
                    if not open_list:
                        self._open.pop(tid, None)
                self._spans.append((time.time(), depth, stage, dur_s,
                                    box["records"], box["bytes"]))
            self._export(stage, dur_s, box["records"], box["bytes"])
            for sess in self._session_list():
                sess._add(stage, dur_s, box["records"], box["bytes"])

    def _export(self, stage, dur_s, records, nbytes):
        base = f"{self.prefix}.stage.{stage}"
        counters.rate(f"{base}.count").increment()
        counters.percentile(f"{base}.duration_us").set(int(dur_s * 1e6))
        if records:
            counters.rate(f"{base}.records").increment(records)
        if nbytes:
            counters.rate(f"{base}.bytes").increment(nbytes)

    @contextmanager
    def session(self):
        """Aggregate the spans this thread closes while the context is
        active (sessions nest; each gets its own aggregate)."""
        sess = TraceSession()
        sessions = self._session_list()
        sessions.append(sess)
        try:
            yield sess
        finally:
            sessions.remove(sess)

    # ------------------------------------------- cross-thread session hand-off

    def propagate_sessions(self) -> list:
        """Snapshot this thread's active session list so a WORKER thread
        (the lane guard runs device calls under a deadline in one) can
        adopt it — spans the worker closes then still aggregate into the
        caller's sessions (manual_compact's per-stage trace must survive
        the guard's thread hop). The caller normally blocks on the worker;
        an ABANDONED (deadline-exceeded) worker may close spans late and
        race the caller's own adds — TraceSession increments are
        GIL-atomic, so a wedge can at worst slightly inflate a summary,
        never corrupt it."""
        return list(self._session_list())

    def adopt_sessions(self, sessions: list) -> None:
        """Install a propagated session snapshot in THIS thread."""
        self._local.sessions = list(sessions)

    # ----------------------------------------------- live-state inspection

    def open_stages(self) -> dict:
        """thread ident -> [stage, ...] (outermost first) for every thread
        with an open span — what the watchdog snapshots on a failed probe."""
        with self._lock:
            return {tid: [s for s, _ in st] for tid, st in self._open.items()}

    def innermost_open(self):
        """(stage, started_wall_ts) of the open span most likely wedged:
        the innermost span of whichever stack has been sitting in its
        innermost stage the LONGEST. None when nothing is open."""
        best = None
        with self._lock:
            for st in self._open.values():
                if not st:
                    continue
                stage, t0 = st[-1]
                if best is None or t0 < best[1]:
                    best = (stage, t0)
        return best

    # ------------------------------------------------------ ring-buffer IO

    def trace(self, last: int = 100) -> list:
        """The most recent closed spans as JSON-ready dicts (close order:
        children close before their parents)."""
        with self._lock:
            spans = list(self._spans)[-last:]
        return [{"ts": ts, "depth": depth, "stage": stage,
                 "duration_us": int(dur_s * 1e6),
                 "records": records, "bytes": nbytes}
                for ts, depth, stage, dur_s, records, nbytes in spans]

    def dump(self, last: int = 100) -> str:
        rows = self.trace(last)
        return "\n".join(
            f"{r['ts']:.6f} {'  ' * r['depth']}{r['stage']} "
            f"{r['duration_us']}us records={r['records']} bytes={r['bytes']}"
            for r in rows) or "no spans"


# process-wide tracer, like the global counter registry: every pipeline
# layer (ops, engine, parallel, bench) threads spans through this instance
COMPACT_TRACER = StageTracer()
