"""Stage-span tracing for the compaction pipeline.

The RPC layer already has a toollet tracer (runtime/toollets.py), but every
bench wedge recorded so far (BENCH_r05: "tpu lane exceeded 360s (device
tunnel wedged mid-init or mid-run)") happened BELOW the RPC layer, inside
the compaction pipeline: device init, host pack, H2D upload, the sort/merge
kernel, or the survivor gather. This module is the in-pipeline probe that
LUDA/RESYSTANCE-style offload perf work needs before any kernel tuning is
trustworthy: nestable stage spans with wall time, record and byte counts,

  - ring-buffered like the RPC tracer: the recent spans dump through the
    `compact-trace-dump` remote command and the `/compact/trace` HTTP
    route (runtime/service_app.py);
  - exported into the process-wide perf-counter registry under
    `compact.stage.<name>.*` (rate counters for span/record/byte
    throughput, a percentile counter for duration), so `/metrics`,
    `perf-counters*`, and the collector all read ONE registry;
  - visible while still OPEN (open_stages / innermost_open): the
    device-health watchdog (ops/device_watchdog.py) reads the live span
    stack to attribute a wedge to the exact stage that never returned.

Stage names used by the pipeline: compact > pack / h2d / device / gather,
plus sst_write at the engine write-out. Spans nest (depth is recorded);
a stage entered recursively (blockwise range decomposition re-enters
`compact`) shows up once per entry, so session sums for such stages count
the nested time once per level — read `calls` alongside `s`.

A TraceSession aggregates every span closed in its thread while active;
bench.py and the manual-compact service record per-stage breakdowns from
it (the `trace` detail in BENCH_*.json).
"""

import collections
import os
import random
import threading
import time
from contextlib import contextmanager

from .perf_counters import counters


class TraceSession:
    """Per-stage aggregate of the spans closed (in the owning thread)
    while the session was active: stage -> {s, calls, records, bytes}."""

    def __init__(self):
        self.stages = {}
        self.started_at = time.time()

    def _add(self, stage: str, dur_s: float, records: int, nbytes: int,
             cpu_s: float = 0.0):
        agg = self.stages.setdefault(
            stage, {"s": 0.0, "cpu_s": 0.0, "calls": 0, "records": 0,
                    "bytes": 0})
        agg["s"] += dur_s
        agg["cpu_s"] += cpu_s
        agg["calls"] += 1
        agg["records"] += records
        agg["bytes"] += nbytes

    def summary(self) -> dict:
        """JSON-ready copy with rounded wall times (stage order = first
        close order, which for a straight-line pipeline is stage order).
        `cpu_s` is the PROCESS cpu-time delta across the span — host
        contention is diagnosable from the artifact: cpu_s >> s means
        other threads worked in parallel under the span; s >> cpu_s with
        a high loadavg means the host starved the stage."""
        return {k: dict(v, s=round(v["s"], 6), cpu_s=round(v["cpu_s"], 6))
                for k, v in self.stages.items()}


class StageTracer:
    def __init__(self, capacity: int = 4096, prefix: str = "compact"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=capacity)
        self._local = threading.local()
        # thread ident -> [(stage, started_wall_ts), ...] innermost LAST;
        # shared (not thread-local) so the watchdog thread can read which
        # stage another thread is currently stuck in
        self._open = {}

    # ----------------------------------------------------------- span API

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _session_list(self) -> list:
        s = getattr(self._local, "sessions", None)
        if s is None:
            s = self._local.sessions = []
        return s

    @contextmanager
    def span(self, stage: str, records: int = 0, nbytes: int = 0):
        """Time one pipeline stage. Yields a mutable {records, bytes} box
        so counts discovered mid-span (e.g. survivor count) can be added
        before the span closes."""
        stack = self._stack()
        depth = len(stack)
        stack.append(stage)
        tid = threading.get_ident()
        with self._lock:
            self._open.setdefault(tid, []).append((stage, time.time()))
        box = {"records": records, "bytes": nbytes}
        t0 = time.perf_counter()
        c0 = time.process_time()
        try:
            yield box
        finally:
            dur_s = time.perf_counter() - t0
            # process (not thread) cpu time: includes concurrent threads'
            # work under the span — exactly what makes host contention
            # attributable from a recorded trace (see TraceSession.summary)
            cpu_s = time.process_time() - c0
            stack.pop()
            with self._lock:
                open_list = self._open.get(tid)
                if open_list:
                    open_list.pop()
                    if not open_list:
                        self._open.pop(tid, None)
                self._spans.append((time.time(), depth, stage, dur_s,
                                    box["records"], box["bytes"], cpu_s))
            self._export(stage, dur_s, box["records"], box["bytes"])
            for sess in self._session_list():
                sess._add(stage, dur_s, box["records"], box["bytes"], cpu_s)

    def event(self, stage: str, dur_s: float, records: int = 0,
              nbytes: int = 0) -> None:
        """Record a synthetic closed span — a duration computed after the
        fact rather than timed in a context (the pipeline's per-range
        overlap intervals). Lands in the ring buffer, the counter
        registry and this thread's active sessions exactly like a span."""
        with self._lock:
            self._spans.append((time.time(), 0, stage, dur_s, records,
                                nbytes, 0.0))
        self._export(stage, dur_s, records, nbytes)
        for sess in self._session_list():
            sess._add(stage, dur_s, records, nbytes)

    def _export(self, stage, dur_s, records, nbytes):
        base = f"{self.prefix}.stage.{stage}"
        counters.rate(f"{base}.count").increment()
        counters.percentile(f"{base}.duration_us").set(int(dur_s * 1e6))
        if records:
            counters.rate(f"{base}.records").increment(records)
        if nbytes:
            counters.rate(f"{base}.bytes").increment(nbytes)

    @contextmanager
    def session(self):
        """Aggregate the spans this thread closes while the context is
        active (sessions nest; each gets its own aggregate)."""
        sess = TraceSession()
        sessions = self._session_list()
        sessions.append(sess)
        try:
            yield sess
        finally:
            sessions.remove(sess)

    # ------------------------------------------- cross-thread session hand-off

    def propagate_sessions(self) -> list:
        """Snapshot this thread's active session list so a WORKER thread
        (the lane guard runs device calls under a deadline in one) can
        adopt it — spans the worker closes then still aggregate into the
        caller's sessions (manual_compact's per-stage trace must survive
        the guard's thread hop). The caller normally blocks on the worker;
        an ABANDONED (deadline-exceeded) worker may close spans late and
        race the caller's own adds — TraceSession increments are
        GIL-atomic, so a wedge can at worst slightly inflate a summary,
        never corrupt it."""
        return list(self._session_list())

    def adopt_sessions(self, sessions: list) -> None:
        """Install a propagated session snapshot in THIS thread."""
        self._local.sessions = list(sessions)

    # ----------------------------------------------- live-state inspection

    def open_stages(self) -> dict:
        """thread ident -> [stage, ...] (outermost first) for every thread
        with an open span — what the watchdog snapshots on a failed probe."""
        with self._lock:
            return {tid: [s for s, _ in st] for tid, st in self._open.items()}

    def innermost_open(self):
        """(stage, started_wall_ts) of the open span most likely wedged:
        the innermost span of whichever stack has been sitting in its
        innermost stage the LONGEST. None when nothing is open."""
        best = None
        with self._lock:
            for st in self._open.values():
                if not st:
                    continue
                stage, t0 = st[-1]
                if best is None or t0 < best[1]:
                    best = (stage, t0)
        return best

    # ------------------------------------------------------ ring-buffer IO

    def trace(self, last: int = 100) -> list:
        """The most recent closed spans as JSON-ready dicts (close order:
        children close before their parents)."""
        with self._lock:
            spans = list(self._spans)[-last:]
        return [{"ts": ts, "depth": depth, "stage": stage,
                 "duration_us": int(dur_s * 1e6),
                 "cpu_us": int(cpu_s * 1e6),
                 "records": records, "bytes": nbytes}
                for ts, depth, stage, dur_s, records, nbytes, cpu_s in spans]

    def dump(self, last: int = 100) -> str:
        rows = self.trace(last)
        return "\n".join(
            f"{r['ts']:.6f} {'  ' * r['depth']}{r['stage']} "
            f"{r['duration_us']}us records={r['records']} bytes={r['bytes']}"
            for r in rows) or "no spans"


# process-wide tracer, like the global counter registry: every pipeline
# layer (ops, engine, parallel, bench) threads spans through this instance
COMPACT_TRACER = StageTracer()


# ======================================================== request tracing
#
# Where the StageTracer above times the compaction pipeline (a background
# job), the RequestTracer times the SERVING path: one trace per client
# request, its id carried in the RPC header (rpc/transport.py RpcHeader
# trace_id/trace_sampled) from client/client.py through the replica
# serverlet, the PacificA prepare/commit round, the private-log append and
# the engine apply. Spans are recorded at close time (children before
# parents, like StageTracer) into one per-trace record.
#
# Retention is two-tier:
#   - a sampled ring buffer of completed traces (every `sample_every`-th
#     trace; default every trace — this is a Python build, span cost is a
#     dict append), served by GET /requests/trace and the
#     `request-trace-dump` remote command;
#   - a slow-request ledger: ANY trace whose end-to-end duration reaches
#     `slow_threshold_us` keeps its full stage timeline regardless of
#     sampling — served by GET /requests/trace?slow=1 and the
#     `slow-requests` remote command. A slow put is attributable to the
#     client hop, the RPC layer, the quorum round or the engine without
#     reproducing it.
#
# Cross-process semantics: each process records the spans IT closes. The
# originating client owns the trace (root_local) and finalizes it; a
# server process that received the context over the wire finalizes its own
# partial view when its last concurrently-open handler for that trace
# returns. In a onebox (everything in one process, one global
# REQUEST_TRACER) the two sides share one record, so a single client put
# yields a single trace holding client, rpc, replication, plog and engine
# spans — the acceptance shape tests/test_request_tracing.py pins.


class TraceContext:
    """What travels in the RPC header: trace identity + sampling flag.
    `remote` marks a context that arrived over the wire (this process does
    not own the trace root)."""

    __slots__ = ("trace_id", "sampled", "remote")

    def __init__(self, trace_id: int, sampled: bool = True,
                 remote: bool = False):
        self.trace_id = trace_id
        self.sampled = sampled
        self.remote = remote


class RequestTracer:
    MAX_ACTIVE = 4096       # leaked/abandoned trace guard
    MAX_SPANS = 512         # per-trace span cap (runaway scan sessions)

    def __init__(self, capacity: int = 512, slow_capacity: int = 256):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ring = collections.deque(maxlen=capacity)
        self._slow = collections.deque(maxlen=slow_capacity)
        self._active = {}   # trace_id -> open trace record
        self.slow_threshold_us = int(
            os.environ.get("PEGASUS_SLOW_REQUEST_US", "50000"))
        self.sample_every = max(1, int(
            os.environ.get("PEGASUS_TRACE_SAMPLE_EVERY", "1")))
        self._seq = 0

    # ------------------------------------------------------------ context

    def current(self):
        """The TraceContext active in this thread, or None."""
        return getattr(self._local, "ctx", None)

    def _entry(self, trace_id: int, op: str, root_local: bool) -> dict:
        with self._lock:
            e = self._active.get(trace_id)
            if e is None:
                while len(self._active) >= self.MAX_ACTIVE:
                    self._active.pop(next(iter(self._active)))
                e = {"trace_id": trace_id, "op": op, "started": time.time(),
                     "spans": [], "root_local": root_local, "refs": 0}
                self._active[trace_id] = e
            return e

    @contextmanager
    def root(self, op: str):
        """Begin a trace in this thread (the CLIENT side of a request).
        Records a `client.<op>` span and finalizes the trace at exit.
        Nested client ops inside an active trace (e.g. copy_data's reads
        feeding writes) record plain spans instead of new traces."""
        prev = self.current()
        if prev is not None:
            with self.span(f"client.{op}"):
                yield prev
            return
        with self._lock:
            self._seq += 1
            sampled = (self._seq % self.sample_every) == 0
        ctx = TraceContext(random.getrandbits(63) | 1, sampled)
        e = self._entry(ctx.trace_id, op, root_local=True)
        self._local.ctx = ctx
        t0 = time.perf_counter()
        try:
            with self.span(f"client.{op}"):
                yield ctx
        finally:
            self._local.ctx = None
            self._finalize(e, int((time.perf_counter() - t0) * 1e6),
                           ctx.sampled)

    @contextmanager
    def serve(self, ctx: TraceContext, op: str):
        """Install a wire-propagated context for a SERVER-side handler and
        record the `rpc.server.<op>` span. When this process does not own
        the trace root, the trace's local view finalizes once its last
        open handler returns."""
        prev = self.current()
        e = self._entry(ctx.trace_id, op, root_local=False)
        with self._lock:
            e["refs"] += 1
        self._local.ctx = ctx
        t0 = time.perf_counter()
        try:
            with self.span(f"rpc.server.{op}"):
                yield ctx
        finally:
            self._local.ctx = prev
            with self._lock:
                e["refs"] -= 1
                done = e["refs"] == 0 and not e["root_local"]
            if done:
                self._finalize(e, int((time.perf_counter() - t0) * 1e6),
                               ctx.sampled)

    @contextmanager
    def adopt(self, ctx):
        """Install an existing context in THIS thread for a worker-pool
        hop (the parallel prepare fan-out runs _send_prepare_window on pool
        threads) — spans the worker closes join the owner's trace. No
        finalize: the owning thread's root/serve does that, and it blocks
        on the workers before closing, so the trace stays active. ctx
        may be None (untraced caller) — then this is a no-op."""
        if ctx is None:
            yield None
            return
        prev = getattr(self._local, "ctx", None)
        self._local.ctx = ctx
        try:
            yield ctx
        finally:
            self._local.ctx = prev

    @contextmanager
    def span(self, name: str, **attrs):
        """Record one stage of the active trace (no-op without a context).
        Yields the mutable attr dict so counts discovered mid-span can be
        added before it closes."""
        ctx = getattr(self._local, "ctx", None)
        if ctx is None:
            yield attrs
            return
        with self._lock:
            e = self._active.get(ctx.trace_id)
        if e is None:
            yield attrs
            return
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield attrs
        finally:
            self._local.depth = depth
            rec = {"name": name, "ts": ts, "depth": depth,
                   "duration_us": int((time.perf_counter() - t0) * 1e6)}
            rec.update(attrs)
            with self._lock:
                if len(e["spans"]) < self.MAX_SPANS:
                    e["spans"].append(rec)

    # ---------------------------------------------------------- retention

    def _finalize(self, e: dict, dur_us: int, sampled: bool) -> None:
        with self._lock:
            self._active.pop(e["trace_id"], None)
        trace = {"trace_id": format(e["trace_id"], "016x"), "op": e["op"],
                 "ts": e["started"], "duration_us": dur_us,
                 "spans": e["spans"]}
        slow = dur_us >= self.slow_threshold_us
        with self._lock:
            if slow:
                self._slow.append(trace)
            if sampled:
                self._ring.append(trace)
        counters.rate("request.trace.completed_count").increment()
        counters.percentile("request.trace.duration_us").set(dur_us)
        if slow:
            counters.rate("request.trace.slow_count").increment()

    def trace(self, last: int = 50) -> list:
        """The most recent sampled completed traces, JSON-ready."""
        with self._lock:
            return list(self._ring)[-last:]

    def slow_requests(self, last: int = 50) -> list:
        """The slow-request ledger: full stage timelines of every request
        that crossed slow_threshold_us."""
        with self._lock:
            return list(self._slow)[-last:]

    def find(self, trace_id: str):
        """Look one completed trace up by hex id (ledger first: slow
        traces are the ones being hunted)."""
        with self._lock:
            for t in list(self._slow) + list(self._ring):
                if t["trace_id"] == trace_id:
                    return t
        return None


# process-wide request tracer: client, transport, replication and engine
# all record into this instance (one process = one local trace view)
REQUEST_TRACER = RequestTracer()
