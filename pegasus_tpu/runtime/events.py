"""Structured event plane: the cluster flight recorder's ring (ISSUE 12).

Every observability surface built so far answers "what is happening
NOW" — counters are levels, traces sample the present, the doctor folds
the current beacon state. Nothing records that a breaker TRIPPED two
minutes ago and closed again, that a scheduler token expired, that a
meta election flapped — the transient state *transitions* every real
incident is reconstructed from. Those transitions used to live as
scattered `print`s and ad-hoc counters; this module is the one bus they
all emit into:

    from ..runtime import events
    events.emit("lane.breaker_trip", severity="error", lane="read.lane")

Design constraints (this sits on hot paths — the lane guard, the write
admission throttle):

  * allocation-light: one tuple append into a preallocated ring under a
    leaf lock; attrs are kept as the caller's kwargs dict (no copy, no
    JSON until a dump is requested);
  * bounded: `PEGASUS_EVENTS_CAP` entries (default 4096); every
    overwrite of an occupied slot counts into ``events.drop_count``
    (there is no per-reader ack — once the ring has wrapped, drop rate
    tracks emit rate; compare the two to size the retained window);
  * queryable by window: every entry carries a wall-clock ts and a
    monotone per-process seq, so the flight recorder can align rings
    from many processes on one anchor.

Event NAMES are part of the repo's lint surface: every emit call site
must use a literal name documented in README.md's "### Event table"
(tools/analyze events pass, both directions — exactly the discipline
the metric-name and remote-command tables already get).

Surfaces: ``GET /events`` on any role's http_port, the ``events-dump``
remote command (per-PID JSON, so the partition-group router's structural
fan-out merge keeps every worker process's ring), and the shell's
``events``.
"""

import os
import threading
import time

from . import lockrank
from .perf_counters import counters

SEVERITIES = ("info", "warn", "error")


class EventBus:
    """Bounded process-wide ring of (seq, ts, name, severity, attrs)."""

    def __init__(self, capacity: int = None):
        self.capacity = capacity if capacity is not None else int(
            os.environ.get("PEGASUS_EVENTS_CAP", "4096"))
        self.capacity = max(1, self.capacity)
        self._lock = lockrank.named_lock("events.ring")
        # preallocated ring + write cursor: append cost is one slot store
        self._ring = [None] * self.capacity  #: guarded_by self._lock
        self._next = 0   # total events ever emitted  #: guarded_by self._lock
        # counter objects resolved once (PR 6 registry-lock rule: emit()
        # can run per-write under the admission throttle)
        self._c_emit = counters.rate("events.emit_count")
        self._c_drop = counters.rate("events.drop_count")

    def emit(self, name: str, severity: str = "info", **attrs) -> None:
        """Record one state transition. `attrs` must be JSON-serializable
        scalars/short strings (they are dumped verbatim by the surfaces);
        the kwargs dict is stored as-is — no copies on the hot path."""
        ts = time.time()
        with self._lock:
            slot = self._next % self.capacity
            dropped = self._ring[slot] is not None
            self._ring[slot] = (self._next, ts, name, severity,
                                attrs or None)
            self._next += 1
        self._c_emit.increment()
        if dropped:
            self._c_drop.increment()

    # ------------------------------------------------------------- queries

    def snapshot(self, last: int = None, since: float = None,
                 prefix: str = None) -> list:
        """JSON-ready event dicts, oldest first. `last` bounds the count
        (applied AFTER the filters), `since` keeps events with ts >= it,
        `prefix` filters on the event name."""
        with self._lock:
            n = self._next
            if n <= self.capacity:
                entries = [e for e in self._ring[:n]]
            else:
                cut = n % self.capacity
                entries = self._ring[cut:] + self._ring[:cut]
        out = []
        for e in entries:
            if e is None:
                continue
            seq, ts, name, severity, attrs = e
            if since is not None and ts < since:
                continue
            if prefix is not None and not name.startswith(prefix):
                continue
            ev = {"seq": seq, "ts": ts, "name": name, "sev": severity}
            if attrs:
                ev["attrs"] = dict(attrs)
            out.append(ev)
        if last is not None and last >= 0:
            out = out[-last:] if last else []
        return out

    def emitted_total(self) -> int:
        """Total events ever emitted (monotone; the ring holds the tail)."""
        with self._lock:
            return self._next

    def reset(self) -> None:
        """Test hook: empty the ring."""
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0


# process-wide bus, like the counter registry and the tracers: every
# subsystem's transitions land in ONE per-process timeline
EVENTS = EventBus()


def emit(name: str, severity: str = "info", **attrs) -> None:
    """Module-level shorthand for EVENTS.emit — the canonical call-site
    shape the events lint pass scans for (module-qualified, with the
    name as a plain string literal)."""
    EVENTS.emit(name, severity=severity, **attrs)


def dump(last: int = None, since: float = None, prefix: str = None) -> list:
    return EVENTS.snapshot(last=last, since=since, prefix=prefix)
