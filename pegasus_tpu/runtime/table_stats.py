"""Per-table (tenant) resource ledger (ISSUE 18).

Every observability plane before this one is node- or partition-scoped;
the unit users see is the TABLE. This module is the accounting source:
one `TableLedger` per (process, table) charges serving-path work
(ops/latency per op class, bytes in/out, errors, DebtThrottle delay-ms)
into `table.<name>.*` counters, and folds device-plane attribution
(compaction device seconds + offload bytes from the job tracer's causal
jobs, device-read probe counts, HBM resident bytes) onto the same key.

Ledgers live in the process-wide `TABLE_STATS` registry. Replica hosts
register each opened replica's gpid under its table name, so process-
level signals that only know an (app_id, pidx) — the job tracer's
compact jobs, transport-level dispatch rejects — can still be charged
to the right tenant. `snapshot()` exports one JSON-able dict per table
(totals, not windowed rates: windowed values don't survive a remote
fold), and `fold_snapshots()` is the one shared merge used by the
collector, the shell and the bench: totals sum, percentiles MAX.

Counters are resolved ONCE per ledger (PR 6 rule: the registry lock is
per-call, and hot-path lookups convoy concurrent readers).
"""

import threading

from .perf_counters import counters

# snapshot keys that are percentile dicts (MAX-merged on fold); every
# other numeric key sums
_PCTL_KEYS = ("read_latency_us", "write_latency_us", "scan_latency_us")
_SUM_KEYS = ("read_qps", "write_qps", "scan_qps", "bytes_in", "bytes_out",
             "errors", "throttle_delay_ms", "device_seconds",
             "offload_bytes", "device_read_count", "hbm_resident_bytes")


class TableLedger:
    """One table's per-process accounting; all charge_* methods are
    lock-free (each hits its own pre-resolved counter)."""

    def __init__(self, name: str):
        self.name = name
        pfx = f"table.{name}."
        self._c_read_qps = counters.rate(pfx + "read_qps")
        self._c_write_qps = counters.rate(pfx + "write_qps")
        self._c_scan_qps = counters.rate(pfx + "scan_qps")
        self._c_bytes_in = counters.rate(pfx + "bytes_in")
        self._c_bytes_out = counters.rate(pfx + "bytes_out")
        self._c_errors = counters.rate(pfx + "error_count")
        # incremented BY delay-ms so .total() is the monotone ms sum the
        # ==global regression test compares against
        self._c_throttle_ms = counters.rate(pfx + "throttle_delay_ms")
        self._c_read_lat = counters.percentile(pfx + "read_latency_us")
        self._c_write_lat = counters.percentile(pfx + "write_latency_us")
        self._c_scan_lat = counters.percentile(pfx + "scan_latency_us")
        # device-plane attribution: window-scoped gauges refreshed by the
        # beacon path (attribute_jobs / set_hbm_resident), plus a monotone
        # probe count charged at the engine's device-lookup site
        self._c_device_s = counters.number(pfx + "device_seconds")
        self._c_offload_b = counters.number(pfx + "offload_bytes")
        self._c_device_reads = counters.number(pfx + "device_read_count")
        self._c_hbm = counters.number(pfx + "hbm_resident_bytes")

    # ------------------------------------------------------- serving path

    def charge_read(self, elapsed_us: int, nbytes_out: int = 0) -> None:
        self._c_read_qps.increment()
        self._c_read_lat.set(elapsed_us)
        if nbytes_out:
            self._c_bytes_out.increment(nbytes_out)

    def charge_write(self, elapsed_us: int, nbytes_in: int = 0,
                     n_ops: int = 1) -> None:
        self._c_write_qps.increment(n_ops)
        self._c_write_lat.set(elapsed_us)
        if nbytes_in:
            self._c_bytes_in.increment(nbytes_in)

    def charge_scan(self, elapsed_us: int, nbytes_out: int = 0) -> None:
        self._c_scan_qps.increment()
        self._c_scan_lat.set(elapsed_us)
        if nbytes_out:
            self._c_bytes_out.increment(nbytes_out)

    def charge_bytes_in(self, nbytes: int) -> None:
        self._c_bytes_in.increment(nbytes)

    def charge_error(self, n: int = 1) -> None:
        self._c_errors.increment(n)

    def charge_throttle_delay(self, delay_ms: float) -> None:
        self._c_throttle_ms.increment(delay_ms)

    # ------------------------------------------------------- device plane

    def charge_device_read(self, n_probes: int = 1) -> None:
        self._c_device_reads.increment(n_probes)

    def set_hbm_resident(self, nbytes: int) -> None:
        self._c_hbm.set(nbytes)

    def set_device_attribution(self, device_seconds: float,
                               offload_bytes: int) -> None:
        self._c_device_s.set(device_seconds)
        self._c_offload_b.set(offload_bytes)

    # ----------------------------------------------------------- export

    def snapshot(self) -> dict:
        return {
            "read_qps": self._c_read_qps.total(),
            "write_qps": self._c_write_qps.total(),
            "scan_qps": self._c_scan_qps.total(),
            "bytes_in": self._c_bytes_in.total(),
            "bytes_out": self._c_bytes_out.total(),
            "errors": self._c_errors.total(),
            "throttle_delay_ms": self._c_throttle_ms.total(),
            "device_seconds": self._c_device_s.value(),
            "offload_bytes": self._c_offload_b.value(),
            "device_read_count": self._c_device_reads.value(),
            "hbm_resident_bytes": self._c_hbm.value(),
            "read_latency_us": self._c_read_lat.percentiles(),
            "write_latency_us": self._c_write_lat.percentiles(),
            "scan_latency_us": self._c_scan_lat.percentiles(),
        }

    def throttle_delay_ms_total(self) -> float:
        return self._c_throttle_ms.total()

    def unregister(self) -> None:
        pfx = f"table.{self.name}."
        for suffix in _SUM_KEYS + _PCTL_KEYS:
            name = {"errors": "error_count"}.get(suffix, suffix)
            counters.remove(pfx + name)


class TableStats:
    """Process-wide registry: table name -> TableLedger, plus the
    gpid -> table mapping that lets partition- and job-scoped signals
    land on a tenant key."""

    def __init__(self):
        self._lock = threading.Lock()  # lockrank: leaf (no calls out)
        self._ledgers = {}      #: guarded_by self._lock
        self._by_app = {}       # app_id -> table  #: guarded_by self._lock
        self._by_gpid = {}      # "app.pidx" -> table  #: guarded_by self._lock

    def ledger(self, name: str) -> TableLedger:
        with self._lock:
            led = self._ledgers.get(name)
            if led is None:
                led = self._ledgers[name] = TableLedger(name)
            return led

    def register_gpid(self, app_id: int, pidx: int, table: str) -> TableLedger:
        led = self.ledger(table)
        with self._lock:
            self._by_app[app_id] = table
            self._by_gpid[f"{app_id}.{pidx}"] = table
        return led

    def table_for_app(self, app_id: int) -> str:
        with self._lock:
            return self._by_app.get(app_id, "")

    def table_for_gpid(self, gpid: str) -> str:
        with self._lock:
            return self._by_gpid.get(gpid, "")

    def charge_app_error(self, app_id: int) -> None:
        """Charge a transport-level reject (e.g. an armed serve.dispatch
        fail point) to the table serving app_id; no-op when unmapped —
        meta/collector traffic carries app_id 0."""
        with self._lock:
            name = self._by_app.get(app_id)
            led = self._ledgers.get(name) if name else None
        if led is not None:
            led.charge_error()

    # ------------------------------------------------- device attribution

    def attribute_jobs(self, jobs) -> None:
        """Fold completed causal jobs (ISSUE 16 tracer dicts) into
        per-table device seconds + offload bytes. Compact jobs carry a
        pidx attr and hop records whose `offload.ship`/`offload.fetch`
        nbytes are the offload wire cost; the gpid -> table map resolves
        the tenant. Window-scoped gauge semantics: each call REPLACES
        the attribution (callers pass the tracer's retained window)."""
        device_s = {}
        offload_b = {}
        for job in jobs:
            if job.get("kind") != "compact" or "status" not in job:
                continue
            attrs = job.get("attrs", {})
            gpid = attrs.get("gpid", "")
            if not gpid:
                pidx = attrs.get("pidx")
                if pidx is None:
                    continue
                with self._lock:
                    hits = [t for g, t in self._by_gpid.items()
                            if g.endswith(f".{pidx}")]
                # ambiguous pidx (several tables share it): skip rather
                # than mis-charge
                if len(set(hits)) != 1:
                    continue
                table = hits[0]
            else:
                table = self.table_for_gpid(gpid)
            if not table:
                continue
            device_s[table] = (device_s.get(table, 0.0)
                               + job.get("duration_us", 0) / 1e6)
            for hop in job.get("hops", []):
                if hop.get("name", "").startswith("offload."):
                    offload_b[table] = (offload_b.get(table, 0)
                                        + int(hop.get("nbytes", 0)))
        with self._lock:
            leds = list(self._ledgers.values())
        for led in leds:
            led.set_device_attribution(device_s.get(led.name, 0.0),
                                       offload_b.get(led.name, 0))

    # ----------------------------------------------------------- export

    def snapshot(self) -> dict:
        with self._lock:
            leds = list(self._ledgers.values())
        return {led.name: led.snapshot() for led in leds}

    def tables(self) -> list:
        with self._lock:
            return sorted(self._ledgers)

    def total_throttle_delay_ms(self) -> float:
        with self._lock:
            leds = list(self._ledgers.values())
        return sum(led.throttle_delay_ms_total() for led in leds)

    def reset(self) -> None:
        """Test hook: drop every ledger AND its registry counters."""
        with self._lock:
            leds = list(self._ledgers.values())
            self._ledgers.clear()
            self._by_app.clear()
            self._by_gpid.clear()
        for led in leds:
            led.unregister()


def fold_snapshots(fragments) -> dict:
    """Merge per-process snapshot() dicts (e.g. pid-keyed remote-command
    fragments) into one per-table view: totals sum across processes,
    latency percentile dicts take the per-quantile MAX (worst host)."""
    out = {}
    for frag in fragments:
        if not isinstance(frag, dict):
            continue
        for table, m in frag.items():
            if not isinstance(m, dict):
                continue
            agg = out.setdefault(table, {})
            for k, v in m.items():
                if k in _PCTL_KEYS and isinstance(v, dict):
                    cur = agg.setdefault(k, {})
                    for q, qv in v.items():
                        cur[q] = max(cur.get(q, 0), qv)
                elif isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
    return out


def top_k(folded: dict, k: int = 5) -> dict:
    """Capacity attribution: rank tables by each resource axis."""
    axes = {
        "ops": lambda m: (m.get("read_qps", 0) + m.get("write_qps", 0)
                          + m.get("scan_qps", 0)),
        "bytes": lambda m: m.get("bytes_in", 0) + m.get("bytes_out", 0),
        "device_seconds": lambda m: m.get("device_seconds", 0),
        "hbm_bytes": lambda m: m.get("hbm_resident_bytes", 0),
    }
    out = {}
    for axis, keyfn in axes.items():
        ranked = sorted(((keyfn(m), t) for t, m in folded.items()),
                        reverse=True)
        out[axis] = [{"table": t, "value": v} for v, t in ranked[:k] if v > 0]
    return out


TABLE_STATS = TableStats()
