"""Metric history: the time axis over the perf-counter registry (ISSUE 12).

`perf_counters.counters.snapshot()` answers "what is the value NOW";
every scrape-driven consumer (collector, doctor, /metrics) therefore
loses any excursion that resolves between two scrapes — an L0 stall that
cleared, a breaker that tripped and closed, a 30-second dispatch-queue
spike. This module samples a configurable slice of the registry on a
fixed cadence into a fixed-size ring, so the last
``capacity * interval`` seconds of every selected series are queryable
by window after the fact — the raw material the flight-recorder
incident correlator (collector/flight_recorder.py) aligns against the
event ring.

Sampling semantics per counter kind (the registry's read semantics make
the stored numbers deltas/rates already):

  * rate counters publish a rolling per-second rate — stored as-is, each
    sample IS the interval's rate;
  * number/gauge counters store the level; the window query can derive
    per-sample deltas from consecutive ring entries (``deltas=True``);
  * percentile counters flatten to their p99 as ``<name>.p99`` (storing
    five quantiles per series would quintuple the ring for tail data the
    p99 already carries).

Knobs: ``PEGASUS_HISTORY_INTERVAL_S`` (default 5), ``PEGASUS_HISTORY_CAP``
samples retained (default 720 — an hour at the default cadence),
``PEGASUS_HISTORY_PREFIXES`` (comma-separated counter-name prefixes; the
default set covers the lane guards, engine debt/throttle, serving,
replication lag and the event bus itself).

Surfaces: ``GET /metrics/history`` on any role's http_port and the
``metrics-history`` remote command (per-PID JSON, so a partition-group
router's structural merge keeps every worker process's ring). One
process-wide instance (HISTORY) is refcount-started by the service apps;
``history.sample_count`` rates the cadence.
"""

import os
import threading
import time

from . import lockrank
from .perf_counters import counters
from .tasking import spawn_thread

_DEFAULT_PREFIXES = (
    "compact.lane.", "read.lane.", "offload.", "engine.", "rpc.server.",
    "plog.", "serve.group.", "replica.", "dup.lag.", "events.",
    "request.trace.", "manual_compact.", "doctor.", "incident.",
    "collector.", "sched.", "audit.",
    # the compaction stage spans' duration p99s: the series the
    # scheduler's feedback tuner folds (ISSUE 14 satellite)
    "compact.stage.",
    # the learn plane's ship/verify series (ISSUE 13 — was invisible in
    # flight-recorder history windows) and the job tracer's gauges
    "learn.", "job.",
    # tenant plane (ISSUE 18): per-table ledgers + SLO burn gauges, so
    # incident windows carry the offending table's series unprompted
    "table.", "slo.",
    # device-served range reads (ISSUE 19): batch/row totals plus the
    # device-vs-host split — a fallback storm shows up as host_count
    # climbing in the history window
    "read.range.",
    # native read data plane (ISSUE 20): wave/batch/writev rates — a
    # fallback-to-Python regression (stale .so, knob flipped, fail
    # point left armed) shows as these flatlining while rpc.server.qps
    # holds. "serve." also widens the old "serve.group." sample to the
    # serving plane's other series
    "native.", "serve.",
)


class MetricHistory:
    def __init__(self, interval_s: float = None, capacity: int = None,
                 prefixes=None):
        self.interval_s = float(
            os.environ.get("PEGASUS_HISTORY_INTERVAL_S", "5")
            if interval_s is None else interval_s)
        self.capacity = max(2, int(
            os.environ.get("PEGASUS_HISTORY_CAP", "720")
            if capacity is None else capacity))
        if prefixes is None:
            env = os.environ.get("PEGASUS_HISTORY_PREFIXES", "")
            prefixes = tuple(p.strip() for p in env.split(",")
                             if p.strip()) or _DEFAULT_PREFIXES
        self.prefixes = tuple(prefixes)
        self._lock = lockrank.named_lock("history.ring")
        # ring of (ts, {name: float}) samples, oldest overwritten
        self._ring = [None] * self.capacity  #: guarded_by self._lock
        self._next = 0                       #: guarded_by self._lock
        # refcounted start/stop: meta+replica+collector in one onebox
        # process share one sampler, and the last app stopping stops it
        self._refs = 0                       #: guarded_by self._lock
        self._stop_evt = None                #: guarded_by self._lock
        self._c_sample = counters.rate("history.sample_count")

    # ------------------------------------------------------------ sampling

    def sample_once(self, now: float = None) -> dict:
        """Take one sample (also the test seam: `now` injects the time
        axis). -> the stored {name: value} dict."""
        snap = counters.snapshot()
        vals = {}
        for name, v in snap.items():
            if not name.startswith(self.prefixes):
                continue
            if isinstance(v, dict):  # percentile counter: keep the p99
                vals[name + ".p99"] = float(v.get("p99", 0))
            else:
                vals[name] = float(v)
        ts = time.time() if now is None else now
        with self._lock:
            self._ring[self._next % self.capacity] = (ts, vals)
            self._next += 1
        self._c_sample.increment()
        return vals

    def _loop(self, stop_evt: threading.Event) -> None:
        while not stop_evt.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:  # noqa: BLE001 - a bad counter value
                # must never kill the history cadence for the process life
                print(f"[metric-history] sample failed: {e!r}", flush=True)

    def start(self) -> "MetricHistory":
        """Refcounted: the first start spawns the sampler thread, later
        starts just bump the count."""
        with self._lock:
            self._refs += 1
            if self._stop_evt is not None:
                return self
            self._stop_evt = threading.Event()
            evt = self._stop_evt
        spawn_thread(self._loop, evt, daemon=True, name="metric-history")
        return self

    def stop(self) -> None:
        """Drop one reference; the last one stops the sampler thread
        (it exits at its next wait tick — bounded by interval_s)."""
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs or self._stop_evt is None:
                return
            evt, self._stop_evt = self._stop_evt, None
        evt.set()

    # ------------------------------------------------------------- queries

    def _samples_locked(self) -> list:  #: requires self._lock
        n = self._next
        if n <= self.capacity:
            return [s for s in self._ring[:n]]
        cut = n % self.capacity
        return self._ring[cut:] + self._ring[:cut]

    def window(self, seconds: float = None, prefix: str = None,
               names=None, deltas: bool = False, now: float = None) -> dict:
        """The ring's tail as JSON-ready samples, oldest first.
        `seconds` keeps samples with ts >= now - seconds; `prefix`/
        `names` filter series; `deltas=True` adds per-sample deltas vs
        the PREVIOUS retained sample (the level-counter rate view)."""
        cutoff = None
        if seconds is not None:
            cutoff = (time.time() if now is None else now) - seconds
        with self._lock:
            samples = self._samples_locked()
        names = set(names) if names else None
        out, prev = [], None
        for s in samples:
            if s is None:
                continue
            ts, vals = s
            keep = {k: v for k, v in vals.items()
                    if (prefix is None or k.startswith(prefix))
                    and (names is None or k in names)}
            if cutoff is not None and ts < cutoff:
                prev = keep  # the last pre-window sample anchors deltas
                continue
            entry = {"ts": ts, "values": keep}
            if deltas:
                entry["deltas"] = {
                    k: round(v - prev[k], 6) if prev and k in prev else 0.0
                    for k, v in keep.items()}
            out.append(entry)
            prev = keep
        return {"interval_s": self.interval_s, "capacity": self.capacity,
                "samples": out}

    def series(self, name: str, seconds: float = None) -> list:
        """[(ts, value)] for one counter over the window — convenience."""
        w = self.window(seconds=seconds, names=[name])
        return [(s["ts"], s["values"][name]) for s in w["samples"]
                if name in s["values"]]

    def reset(self) -> None:
        """Test hook: empty the ring (sampler refs untouched)."""
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0


# process-wide sampler (one per OS process: each partition-group worker
# runs its own, exactly like the counter registry it samples)
HISTORY = MetricHistory()
