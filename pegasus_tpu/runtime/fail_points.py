"""Deterministic failure injection, modeled on dsn::fail points.

The reference arms points like ``dsn::fail::cfg("db_write_batch_put",
"10%return()")`` in tests against hooks compiled into the write path
(src/server/rocksdb_wrapper.cpp:49,90,143,164;
src/server/test/pegasus_server_write_test.cpp:45-49). Actions support the
same mini-language subset the tests use, plus the two chaos verbs the
compaction lane guard needs (a wedged device call is a SLEEP, a transient
device error is a RAISE):

    "return()"     -> hook returns the given (or default) injected value
    "return(v)"    -> hook returns v (string)
    "10%return()"  -> 10% probability
    "3*return()"   -> only first 3 hits
    "off()"        -> disabled
    "print()"      -> log and continue
    "sleep(ms)"    -> block the calling thread ms milliseconds, continue
    "raise(msg)"   -> raise FailPointError(msg) from the hook
"""

import random
import re
import threading
import time

_ACTION_RE = re.compile(
    r"^\s*(?:(?P<pct>\d+(?:\.\d+)?)%)?\s*(?:(?P<cnt>\d+)\*)?\s*(?P<verb>return|off|print|sleep|raise)\((?P<arg>[^)]*)\)\s*$"
)


class FailPointError(RuntimeError):
    """Raised by a fail point armed with the 'raise(msg)' verb."""


class _FailPointRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._points = {}
        self._enabled = False
        self._active = 0   # non-'off' points; with _enabled it forms the
        # UNLOCKED fast-path check in evaluate() — once every point is
        # healed with off(), hot-path hooks (serve.dispatch runs per RPC)
        # go back to a plain attribute read instead of taking the lock
        self._rng = random.Random(0)

    def setup(self):
        with self._lock:
            self._enabled = True
            self._points.clear()
            self._active = 0

    def teardown(self):
        with self._lock:
            self._enabled = False
            self._points.clear()
            self._active = 0

    def arm(self, name: str, action: str):
        """cfg() that also ENABLES the registry without clearing points
        already armed — the ``set-fail-point`` remote-command path (ISSUE
        11): a chaos harness arms points one at a time in a live server
        process, where setup()'s clear would heal every other armed
        fault as a side effect."""
        with self._lock:
            self._enabled = True
        self.cfg(name, action)

    def cfg(self, name: str, action: str):
        m = _ACTION_RE.match(action)
        if not m:
            raise ValueError(f"bad fail point action: {action!r}")
        with self._lock:
            self._points[name] = {
                "pct": float(m.group("pct")) if m.group("pct") else None,
                "remaining": int(m.group("cnt")) if m.group("cnt") else None,
                "verb": m.group("verb"),
                "arg": m.group("arg"),
            }
            self._active = sum(1 for p in self._points.values()
                               if p["verb"] != "off")
        # flight-recorder timeline (ISSUE 12): an armed fault is the
        # canonical first-cause candidate the incident correlator hunts
        # for, so arm/heal transitions land in the event ring of the
        # process the fault actually lives in
        from . import events

        if m.group("verb") == "off":
            events.emit("failpoint.disarm", point=name)
        else:
            events.emit("failpoint.arm", severity="warn", point=name,
                        action=action)

    def evaluate(self, name: str):
        """None = not triggered; otherwise the (verb, arg) tuple. Pure:
        side-effectful verbs (sleep/raise) act in fail_point(), OUTSIDE the
        registry lock — a sleeping hook must not block cfg()/teardown()."""
        if not self._enabled or not self._active:
            return None
        with self._lock:
            p = self._points.get(name)
            if p is None or p["verb"] == "off":
                return None
            if p["pct"] is not None and self._rng.uniform(0, 100) >= p["pct"]:
                return None
            if p["remaining"] is not None:
                if p["remaining"] <= 0:
                    return None
                p["remaining"] -= 1
            return (p["verb"], p["arg"])


_REGISTRY = _FailPointRegistry()
setup = _REGISTRY.setup
teardown = _REGISTRY.teardown
cfg = _REGISTRY.cfg
arm = _REGISTRY.arm


def fail_point(name: str):
    """FAIL_POINT_INJECT_F analogue.

    Returns None when not armed/triggered. The chaos verbs act here:
    'sleep(ms)' blocks the calling thread then continues (simulated device
    wedge — the lane guard's deadline must abandon it), 'raise(msg)'
    raises FailPointError (simulated transient device error). Otherwise
    the ("return"|"print", arg) tuple is returned and call sites decide
    what an injected return means (typically an error status
    short-circuiting the operation).
    """
    fp = _REGISTRY.evaluate(name)
    if fp is None:
        return None
    verb, arg = fp
    if verb == "sleep":
        time.sleep(float(arg or 0) / 1000.0)
        return None
    if verb == "raise":
        raise FailPointError(arg or f"injected failure at {name}")
    return fp


def inject(name: str) -> None:
    """Stage-boundary hook for the compaction pipeline (compact.pack,
    compact.h2d, compact.device, compact.gather, engine.sst_write):
    sleep()/raise() act inside fail_point(); a 'return' arming is treated
    as an injected error too (stage hooks have no value to return), and
    'print' logs and continues."""
    fp = fail_point(name)
    if fp is None:
        return
    verb, arg = fp
    if verb == "print":
        print(f"[fail_point] {name}: print({arg})", flush=True)
        return
    raise FailPointError(arg or f"injected failure at {name}")
