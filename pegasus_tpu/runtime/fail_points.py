"""Deterministic failure injection, modeled on dsn::fail points.

The reference arms points like ``dsn::fail::cfg("db_write_batch_put",
"10%return()")`` in tests against hooks compiled into the write path
(src/server/rocksdb_wrapper.cpp:49,90,143,164;
src/server/test/pegasus_server_write_test.cpp:45-49). Actions support the
same mini-language subset the tests use:

    "return()"     -> hook returns the given (or default) injected value
    "return(v)"    -> hook returns v (string)
    "10%return()"  -> 10% probability
    "3*return()"   -> only first 3 hits
    "off()"        -> disabled
    "print()"      -> log and continue
"""

import random
import re
import threading

_ACTION_RE = re.compile(
    r"^\s*(?:(?P<pct>\d+(?:\.\d+)?)%)?\s*(?:(?P<cnt>\d+)\*)?\s*(?P<verb>return|off|print)\((?P<arg>[^)]*)\)\s*$"
)


class _FailPointRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._points = {}
        self._enabled = False
        self._rng = random.Random(0)

    def setup(self):
        with self._lock:
            self._enabled = True
            self._points.clear()

    def teardown(self):
        with self._lock:
            self._enabled = False
            self._points.clear()

    def cfg(self, name: str, action: str):
        m = _ACTION_RE.match(action)
        if not m:
            raise ValueError(f"bad fail point action: {action!r}")
        with self._lock:
            self._points[name] = {
                "pct": float(m.group("pct")) if m.group("pct") else None,
                "remaining": int(m.group("cnt")) if m.group("cnt") else None,
                "verb": m.group("verb"),
                "arg": m.group("arg"),
            }

    def evaluate(self, name: str):
        """None = not triggered; otherwise ("return", arg) or ("print", arg)."""
        if not self._enabled:
            return None
        with self._lock:
            p = self._points.get(name)
            if p is None or p["verb"] == "off":
                return None
            if p["pct"] is not None and self._rng.uniform(0, 100) >= p["pct"]:
                return None
            if p["remaining"] is not None:
                if p["remaining"] <= 0:
                    return None
                p["remaining"] -= 1
            return (p["verb"], p["arg"])


_REGISTRY = _FailPointRegistry()
setup = _REGISTRY.setup
teardown = _REGISTRY.teardown
cfg = _REGISTRY.cfg


def fail_point(name: str):
    """FAIL_POINT_INJECT_F analogue.

    Returns None when not armed/triggered, else the ("return"|"print", arg)
    tuple; call sites decide what an injected return means (typically an
    error status short-circuiting the operation).
    """
    return _REGISTRY.evaluate(name)
