"""Remote commands: name -> handler registry invocable over RPC.

The rDSN `register_command` surface (SURVEY.md §2.4 'Remote commands';
reference src/server/main.cpp:74-90 registers server-info/server-stat, the
shell invokes them via `remote_command`, src/shell/commands/misc.cpp). The
perf-counter scrape commands mirror command_helper.h:891-1146.
"""

import json
import time
from dataclasses import dataclass, field
from typing import List

from ..rpc import codec
from .perf_counters import counters

VERSION = "pegasus-tpu 2.0"
_START_TIME = time.time()


@dataclass
class RemoteCommandRequest:
    command: str = ""
    arguments: List[str] = field(default_factory=list)


@dataclass
class RemoteCommandResponse:
    output: str = ""


class RemoteCommandService:
    def __init__(self):
        self._commands = {}

    def register(self, name: str, fn) -> None:
        """fn(args: list[str]) -> str."""
        self._commands[name] = fn

    def register_defaults(self, node_kind: str, describe=None) -> None:
        self.register("help", lambda a: "\n".join(sorted(self._commands)))
        self.register("server-info", lambda a: (
            f"{VERSION}, {node_kind}, started {int(time.time() - _START_TIME)}s ago"))
        self.register("server-stat", self._cmd_server_stat)
        self.register("perf-counters", lambda a: self._dump_counters(None))
        self.register("perf-counters-by-prefix",
                      lambda a: self._dump_counters(
                          lambda n: any(n.startswith(p) for p in a)))
        self.register("perf-counters-by-substr",
                      lambda a: self._dump_counters(
                          lambda n: any(p in n for p in a)))
        self.register("set-fail-point", self._cmd_set_fail_point)
        self.register("events-dump", self._cmd_events_dump)
        self.register("metrics-history", self._cmd_metrics_history)
        self.register("compact-trace-dump", self._cmd_compact_trace_dump)
        self.register("device-health", self._cmd_device_health)
        self.register("request-trace-dump", self._cmd_request_trace_dump)
        self.register("slow-requests", self._cmd_slow_requests)
        self.register("job-trace", self._cmd_job_trace)
        self.register("table-stats", self._cmd_table_stats)
        self.register("slo-status", self._cmd_slo_status)
        if describe is not None:
            self.register("describe", lambda a: json.dumps(describe(), indent=1))

    @staticmethod
    def _cmd_set_fail_point(args) -> str:
        """set-fail-point <name> <action> — arm (or heal, with 'off()') a
        fail point in THIS server process at runtime, using the same
        action mini-language tests use (`sleep(ms)`, `raise(msg)`,
        `return(v)`, `N%`/`K*` modifiers). The chaos scenario engine's
        fault-injection surface (ISSUE 11): before this command, fail
        points could only be armed in-process before startup, so a
        spawned group worker or remote node was out of reach. Arming
        never clears other armed points (fail_points.arm). The reply is
        a JSON dict keyed by this process's pid, so a partition-group
        router's structural fan-out merge keeps every worker's ack and
        the caller can count how many processes armed."""
        import os

        from . import fail_points

        if len(args) < 2:
            return "usage: set-fail-point <name> <action>"
        name, action = args[0], " ".join(args[1:])
        try:
            fail_points.arm(name, action)
        except ValueError as e:
            return str(e)   # "bad fail point action: ..."
        return json.dumps({f"pid:{os.getpid()}": f"{name}={action}"})

    @staticmethod
    def _cmd_events_dump(args) -> str:
        """events-dump [last] [prefix] — this process's structured event
        ring (runtime/events.py), the flight recorder's per-node source.
        The reply is a JSON dict keyed by this process's pid, so a
        partition-group router's structural fan-out merge keeps EVERY
        worker process's ring side by side (disjoint keys survive the
        merge — the same shape set-fail-point uses for its acks)."""
        import os

        from .events import EVENTS

        last = int(args[0]) if args else None
        prefix = args[1] if len(args) > 1 else None
        return json.dumps({f"pid:{os.getpid()}":
                           EVENTS.snapshot(last=last, prefix=prefix)})

    @staticmethod
    def _cmd_metrics_history(args) -> str:
        """metrics-history [seconds] [prefix] — this process's metric
        history window (runtime/metric_history.py): the sampled tail of
        the selected counter series. Pid-keyed like events-dump so a
        grouped node's router merge keeps each worker's ring."""
        import os

        from .metric_history import HISTORY

        seconds = float(args[0]) if args else None
        prefix = args[1] if len(args) > 1 else None
        return json.dumps({f"pid:{os.getpid()}":
                           HISTORY.window(seconds=seconds, prefix=prefix)})

    @staticmethod
    def _cmd_compact_trace_dump(args) -> str:
        """compact-trace-dump [last] — recent compaction stage spans from
        the process-wide ring buffer (runtime/tracing.py)."""
        from .tracing import COMPACT_TRACER

        return COMPACT_TRACER.dump(int(args[0]) if args else 100)

    @staticmethod
    def _cmd_device_health(args) -> str:
        """device-health — the device watchdog's liveness/wedge state."""
        from ..ops.device_watchdog import WATCHDOG

        return json.dumps(WATCHDOG.state(), indent=1)

    @staticmethod
    def _cmd_request_trace_dump(args) -> str:
        """request-trace-dump [last] — recent sampled request traces from
        the serving-path tracer (runtime/tracing.py RequestTracer)."""
        from .tracing import REQUEST_TRACER

        return json.dumps(
            REQUEST_TRACER.trace(int(args[0]) if args else 50), indent=1)

    @staticmethod
    def _cmd_slow_requests(args) -> str:
        """slow-requests [last] — the slow-request ledger: full stage
        timelines of every request over the slow threshold."""
        from .tracing import REQUEST_TRACER

        return json.dumps(
            REQUEST_TRACER.slow_requests(int(args[0]) if args else 50),
            indent=1)

    @staticmethod
    def _cmd_job_trace(args) -> str:
        """job-trace [last | <job-id>] — this process's background-job
        timelines (runtime/job_trace.py): completed jobs plus the still-
        open ones, or ONE timeline when a j…-id is given. Pid-keyed like
        events-dump, so a partition-group router's structural fan-out
        merge keeps every worker process's view side by side."""
        import os

        from .job_trace import JOB_TRACER

        if args and args[0].startswith("j"):
            found = JOB_TRACER.find(args[0])
            return json.dumps({f"pid:{os.getpid()}":
                               [found] if found else []})
        last = int(args[0]) if args else 50
        return json.dumps({f"pid:{os.getpid()}": JOB_TRACER.jobs(last=last)})

    @staticmethod
    def _cmd_table_stats(args) -> str:
        """table-stats — this process's per-table tenant ledger totals
        (runtime/table_stats.py). Pid-keyed like events-dump, so a
        partition-group router's structural fan-out merge keeps every
        worker process's fragment; callers fold them with
        table_stats.fold_snapshots (totals sum, percentiles MAX)."""
        import os

        from .table_stats import TABLE_STATS

        return json.dumps({f"pid:{os.getpid()}": TABLE_STATS.snapshot()})

    @staticmethod
    def _cmd_slo_status(args) -> str:
        """slo-status — the most recent per-table SLO burn-rate verdicts
        this process has computed ({} on nodes that never evaluate SLOs
        — the collector is the evaluator). Pid-keyed for the router
        merge like every other structural command."""
        import os

        from ..collector.info_collector import latest_slo

        return json.dumps({f"pid:{os.getpid()}": latest_slo()})

    def _cmd_server_stat(self, args) -> str:
        """One-line digest of selected counters (brief_stat.cpp role)."""
        snap = counters.snapshot()
        keys = sorted(k for k in snap if k.endswith("_qps"))[:8]
        parts = [f"{k.rsplit('.', 1)[-1]}={snap[k]:.0f}" for k in keys]
        return ", ".join(parts) if parts else "no stats yet"

    def _dump_counters(self, pred) -> str:
        snap = counters.snapshot()
        out = {k: v for k, v in sorted(snap.items()) if pred is None or pred(k)}
        return json.dumps(out, indent=1)

    def invoke(self, command: str, arguments: list) -> str:
        fn = self._commands.get(command)
        if fn is None:
            return f"unknown command: {command!r} (try 'help')"
        try:
            return fn(list(arguments))
        except Exception as e:  # surface the error text, keep serving
            return f"command failed: {e!r}"

    def rpc_handler(self, header, body) -> bytes:
        req = codec.decode(RemoteCommandRequest, body)
        return codec.encode(RemoteCommandResponse(
            self.invoke(req.command, req.arguments)))
