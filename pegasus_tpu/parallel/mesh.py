"""Device mesh helpers.

The only parallel axis a KV store's compaction needs is the hash-shard axis
('shard'): partitions are already data-parallel by construction (disjoint
hash ranges per replica, reference src/base/pegasus_key_schema.h:178), so
within one partition's compaction we shard records by key-hash across chips
and exchange with a single all_to_all over ICI (SURVEY.md §5.7c/§5.8).

Multi-HOST (the reference's NCCL/MPI-backend analogue, §5.8): the data
plane needs no new code — `init_multihost()` joins this process into a
jax.distributed job, after which `jax.devices()` spans every host's chips,
`make_mesh()` builds a global mesh, and the same all_to_all lowers to ICI
within a pod slice / DCN across slices. XLA owns the transport exactly
where the reference hand-rolls collectives over NCCL. The control plane
(RPC, replication, meta) is multi-host by construction — plain TCP.
"""

import os

import jax
import numpy as np
from jax.sharding import Mesh


_joined = False  # idempotence: jax.distributed.initialize rejects a re-init


def init_multihost(coordinator: str = None, num_processes: int = None,
                   process_id: int = None) -> bool:
    """Join a multi-host jax.distributed job (idempotent; False = single
    host). Args default from the standard env (PEGASUS_COORDINATOR /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID); a TPU-pod runtime that sets its
    own cluster env needs no arguments at all. Invoked automatically by
    service startup (runtime.service_app) when that env is present."""
    global _joined
    coordinator = coordinator or os.environ.get("PEGASUS_COORDINATOR")
    if num_processes is None:
        env_np = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env_np) if env_np else None
    if process_id is None:
        env_pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env_pid) if env_pid else None
    if coordinator is None and num_processes is None:
        return False  # single-host: nothing to join
    if _joined:
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes, process_id=process_id)
    _joined = True
    return True


def make_mesh(n_devices: int = None, axis: str = "shard") -> Mesh:
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))
