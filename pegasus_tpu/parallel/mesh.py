"""Device mesh helpers.

The only parallel axis a KV store's compaction needs is the hash-shard axis
('shard'): partitions are already data-parallel by construction (disjoint
hash ranges per replica, reference src/base/pegasus_key_schema.h:178), so
within one partition's compaction we shard records by key-hash across chips
and exchange with a single all_to_all over ICI (SURVEY.md §5.7c/§5.8).
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: int = None, axis: str = "shard") -> Mesh:
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))
