"""Multi-chip compaction: hash-sharded sort/merge with an all_to_all exchange.

The TPU-native answer to "compaction of a multi-GB partition is bigger than
one chip" (SURVEY.md §5.7c): records are hash-classed by key (`hash32 %
n_shards` — every version of a key, and every sort_key of a hash_key, lands
in the same class), each chip takes one class, and a single all_to_all over
the mesh's ICI routes records from whichever input run they arrived in to
their owning chip. Each chip then runs the same merge_body as the
single-chip kernel on its class. SPMD via shard_map; no NCCL/MPI analogue —
the exchange is an XLA collective.

Output is a list of per-shard KVBlocks: independent sorted runs over
disjoint hash classes (the sharded-SST layout). Their union equals the
single-chip compaction output exactly.

Routing uses fixed per-(src,dst) capacity `cap` (static shapes for XLA);
rows past capacity are counted, and the host retries with full capacity on
overflow — hash uniformity makes that rare at sane capacity factors.
"""

import functools
from dataclasses import replace

import numpy as np

from ..engine.block import KVBlock
from ..ops.compact import (CompactOptions, CompactResult, _apply_default_ttl,
                           _pow2ceil, _stats, apply_post_filters, merge_body,
                           sort_block)
from ..ops.packing import compute_suffix_ranks, pack_key_prefixes
from ..runtime.fail_points import inject as _inject
from ..runtime.lane_guard import LANE_GUARD
from ..runtime.tracing import COMPACT_TRACER as _TRACE


def _next_bucket(n: int) -> int:
    return _pow2ceil(n, 1024)


def _shard_map():
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    return shard_map


@functools.lru_cache(maxsize=32)
def _sharded_kernel(mesh_key, w: int, n_loc: int, cap: int, axis: str):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh = _MESHES[mesh_key]
    nsh = mesh.shape[axis]
    nrecv = nsh * cap

    def per_device(cols, rank, klen, prio, expire, deleted, hash32, valid, gid,
                   now, pidx, pmask, bottommost, do_filter):
        # local slice: cols [w, n_loc], rest [n_loc]
        dest = (hash32 % jnp.uint32(nsh)).astype(jnp.int32)
        order = jnp.argsort(dest)
        dest_s = dest[order]
        counts = jnp.bincount(dest, length=nsh).astype(jnp.int32)
        starts = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
        )
        within = jnp.arange(n_loc, dtype=jnp.int32) - starts[dest_s]
        ok = (within < cap) & valid[order]
        slot = jnp.where(ok, dest_s * cap + within, nrecv)  # nrecv = OOB drop
        overflow = jnp.sum((within >= cap) & valid[order]).astype(jnp.int32)

        def route(x, fill):
            buf = jnp.full((nrecv,), fill, dtype=x.dtype)
            buf = buf.at[slot].set(x[order], mode="drop")
            return lax.all_to_all(
                buf.reshape(nsh, cap), axis, split_axis=0, concat_axis=0
            ).reshape(nrecv)

        r_cols = [route(cols[i], jnp.uint32(0)) for i in range(w)]
        r_rank = route(rank, jnp.uint32(0))
        r_klen = route(klen, jnp.uint32(0))
        r_prio = route(prio, jnp.uint32(0))
        r_expire = route(expire, jnp.uint32(0))
        r_deleted = route(deleted, jnp.bool_(False))
        r_hash = route(hash32, jnp.uint32(0))
        r_valid = route(valid, jnp.bool_(False))
        r_gid = route(gid, jnp.int32(-1))

        perm, keep = merge_body(
            r_cols, r_rank, r_klen, r_prio, r_expire, r_deleted, r_hash, r_valid,
            now, pidx, pmask, bottommost, do_filter,
            # the routing scrambled row order: tie-break intra-run
            # duplicate keys by ORIGINAL concat position, matching the
            # host backend's stable first-wins (invalid rows carry gid -1
            # but every sort key is already forced to the max there)
            pos=r_gid.astype(jnp.uint32),
        )
        return r_gid[perm], keep, overflow[None]

    smap = _shard_map()(
        per_device,
        mesh=mesh,
        in_specs=(
            P(None, axis), P(axis), P(axis), P(axis), P(axis), P(axis),
            P(axis), P(axis), P(axis), P(), P(), P(), P(), P(),
        ),
        out_specs=(P(axis), P(axis), P(axis)),
    )
    return jax.jit(smap)


# shard_map needs the concrete Mesh at trace time; lru_cache keys must be
# hashable, so meshes are interned here by id-key
_MESHES = {}


def _intern_mesh(mesh):
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    _MESHES[key] = mesh
    return key


def sharded_compact(blocks, mesh, opts: CompactOptions, axis: str = "shard",
                    capacity_factor: float = 2.0):
    """Compact K runs (newest first) across the mesh. Returns
    (list[KVBlock] per shard, stats dict)."""
    import jax.numpy as jnp

    runs = [b for b in blocks if b.n]
    nsh = mesh.shape[axis]
    if not runs:
        return [KVBlock.empty() for _ in range(nsh)], {"input_records": 0,
                                                       "output_records": 0, "dropped": 0}
    block = runs[0] if len(runs) == 1 else KVBlock.concat(runs)
    prio = np.repeat(np.arange(len(runs), dtype=np.uint32), [b.n for b in runs])
    n = block.n
    w = opts.prefix_u32
    n_loc = _next_bucket(-(-n // nsh))
    n_pad = n_loc * nsh

    with _TRACE.span("pack", records=n):
        prefixes = pack_key_prefixes(block.key_arena, block.key_off,
                                     block.key_len, w)
        rank = compute_suffix_ranks(block, w, prefixes)

        def pad(a, fill=0):
            out = np.full(n_pad, fill, dtype=a.dtype)
            out[:n] = a
            return out

        cols = np.zeros((w, n_pad), np.uint32)
        cols[:, :n] = prefixes.T
        args = (
            pad(rank), pad(block.key_len.astype(np.uint32)), pad(prio),
            pad(block.expire_ts), pad(block.deleted), pad(block.hash32),
            pad(np.ones(n, dtype=bool), False),
            pad(np.arange(n, dtype=np.int32), -1),
        )
    now = opts.resolved_now()
    scalars = (jnp.uint32(now), jnp.uint32(opts.pidx), jnp.uint32(opts.partition_mask),
               jnp.asarray(bool(opts.bottommost)), jnp.asarray(bool(opts.filter)))

    mesh_key = _intern_mesh(mesh)
    # pow2 capacity so nrecv = nsh*cap is pow2 -> the merge takes the bitonic
    # path (nsh is a pow2 device count)
    def pow2ceil(x):
        p = 1
        while p < x:
            p <<= 1
        return p

    cap = min(n_loc, max(8, pow2ceil(int(n_loc / nsh * capacity_factor))))
    # the kernel span covers upload + all_to_all + merge + download (the
    # np.asarray calls sync); a capacity-overflow retry re-enters the span
    while True:
        with _TRACE.span("device", records=n):
            _inject("compact.device")
            fn = _sharded_kernel(mesh_key, w, n_loc, cap, axis)
            gid_sorted, keep, overflow = fn(cols, *args, *scalars)
            gid_sorted = np.asarray(gid_sorted)
            keep = np.asarray(keep)
        if int(np.asarray(overflow).sum()) == 0:
            break
        if cap >= n_loc:  # can't happen: full capacity admits every row
            raise RuntimeError("sharded_compact overflow at full capacity")
        cap = n_loc  # retry with loss-proof capacity

    nrecv = nsh * cap
    shards = []
    out_total = 0
    with _TRACE.span("gather") as sp:
        for s in range(nsh):
            seg_ids = gid_sorted[s * nrecv : (s + 1) * nrecv]
            seg_keep = keep[s * nrecv : (s + 1) * nrecv]
            ids = seg_ids[seg_keep]
            shard = block.gather(ids)
            if opts.filter and opts.default_ttl > 0:
                _apply_default_ttl(shard, now + opts.default_ttl)
            out_total += shard.n
            shards.append(shard)
        sp["records"] = out_total
    return shards, {"input_records": n, "output_records": out_total,
                    "dropped": n - out_total, "n_shards": nsh, "capacity": cap}


def sharded_compact_block(blocks, mesh, opts: CompactOptions,
                          axis: str = "shard") -> CompactResult:
    """Engine seam (VERDICT-r3 item 7): run the multi-chip hash-sharded
    compaction and reassemble ONE key-sorted block byte-equal to
    `compact_blocks(blocks, opts)` — what LsmEngine.manual_compact installs
    when its mesh has >1 device (the reference's analogue spreads
    partition-ranged compaction work across nodes; here the spread is
    hash classes across chips and the final order is restored on install).

    Equality argument: hash-classing sends every version of a key to one
    shard, each shard's merge_body output is key-sorted and deduped, so
    shard outputs hold DISJOINT key sets whose union is exactly the
    single-chip survivor set. A stable key sort of their concatenation is
    therefore the single-chip output order. Post filters (user compaction
    rules, default-TTL rewrite) run after reassembly in compact_blocks'
    exact order — the kernel runs with them masked off."""
    # resolve `now` ONCE: the kernel's TTL drops and the post filters must
    # agree on the clock or the output can differ from the single-chip
    # result for records expiring between two resolved_now() calls
    opts = replace(opts, now=opts.resolved_now())

    def _device_lane() -> CompactResult:
        kernel_opts = replace(opts, default_ttl=0, user_ops=())
        shards, stats = sharded_compact(blocks, mesh, kernel_opts, axis=axis)
        live = [s for s in shards if s.n]
        if not live:
            return CompactResult(KVBlock.empty(),
                                 _stats(stats["input_records"], 0))
        merged = live[0] if len(live) == 1 else KVBlock.concat(live)
        out = sort_block(merged, CompactOptions(prefix_u32=opts.prefix_u32,
                                                backend=opts.backend))
        out = apply_post_filters(out, opts, opts.now)
        return CompactResult(out, _stats(stats["input_records"], out.n))

    def _cpu_lane() -> CompactResult:
        from ..ops.compact import compact_blocks

        return compact_blocks(blocks, replace(opts, backend="cpu"))

    # the lane guard makes the multi-chip path safe to prefer: a wedged
    # collective / dead chip degrades to the single-node cpu merge, whose
    # output this function is byte-equal to by construction
    return LANE_GUARD.run(_device_lane, _cpu_lane, op="sharded_compact")


def compact_blocks_meshed(blocks, opts: CompactOptions,
                          mesh=None) -> CompactResult:
    """Merge entry for the compaction-offload service (ISSUE 14): one
    call that multiplexes tenants across whatever the host owns — the
    all_to_all hash-sharded kernel when the mesh spans >1 device, the
    guarded single-chip merge for a device backend, the plain host merge
    otherwise. Every path is byte-equal to ``compact_blocks(blocks,
    opts)`` on cpu (the sharded path by sharded_compact_block's
    reassembly argument, the single-chip path by the standing
    device-vs-host contract), so a cpu-only tenant's local fallback and
    the service's merged output can never diverge."""
    from ..ops.compact import compact_blocks

    if mesh is not None and mesh.devices.size > 1:
        return sharded_compact_block(blocks, mesh, opts)
    if opts.backend != "cpu":
        return LANE_GUARD.run(
            lambda: compact_blocks(blocks, opts),
            lambda: compact_blocks(blocks, replace(opts, backend="cpu")),
            op="offload_merge")
    return compact_blocks(blocks, opts)
