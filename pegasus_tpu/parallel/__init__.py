from .mesh import init_multihost, make_mesh
from .sharded_compact import sharded_compact, sharded_compact_block

__all__ = ["init_multihost", "make_mesh", "sharded_compact", "sharded_compact_block"]
