from .mesh import init_multihost, make_mesh
from .sharded_compact import (compact_blocks_meshed, sharded_compact,
                              sharded_compact_block)

__all__ = ["init_multihost", "make_mesh", "sharded_compact",
           "sharded_compact_block", "compact_blocks_meshed"]
