from .mesh import make_mesh
from .sharded_compact import sharded_compact

__all__ = ["make_mesh", "sharded_compact"]
