from .mesh import make_mesh
from .sharded_compact import sharded_compact, sharded_compact_block

__all__ = ["make_mesh", "sharded_compact", "sharded_compact_block"]
