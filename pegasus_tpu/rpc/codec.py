"""Compact binary codec for the rpc.messages dataclasses.

The reference serializes rrdb structs with thrift binary protocol
(src/idl/rrdb.thrift -> src/base/rrdb_types.cpp). This build keeps the same
struct/field shapes (rpc.messages mirrors the .thrift declarations) but
derives the wire format from the dataclass type annotations instead of
generated code:

    int        -> zigzag varint
    bool       -> 1 byte
    bytes      -> varint length + raw
    str        -> varint length + utf-8
    Optional[X]-> presence byte + X
    List[X]    -> varint count + X...
    dataclass  -> varint field count + fields in declaration order
    IntEnum    -> as int

The leading field count lets a decoder accept messages from an older
encoder (missing trailing fields fall back to dataclass defaults), which is
the append-only evolution rule the thrift ids gave the reference.
"""

import dataclasses
import functools
import threading
import typing


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: bytearray, n: int) -> None:
    if n < 0x80:  # the overwhelmingly common case: counts, lengths,
        out.append(n)  # small zigzagged ints — one append, no loop
        return
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf, off: int):
    b = buf[off]
    if not b & 0x80:
        return b, off + 1
    shift = 0
    val = 0
    end = off + 10  # the longest varint the encoder emits for [-2^63, 2^64)
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        if off >= end:
            # corrupt frame: without the bound this would keep absorbing
            # continuation bytes into an ever-growing int where the C
            # decoder (native/fastcodec.c rd_varint) raises — both paths
            # must reject the same malformed input
            raise CodecError("varint overflow (longer than 10 bytes)")
        shift += 7


class CodecError(Exception):
    pass


# The annotation interpretation (typing.get_origin / get_args /
# issubclass walks) is done ONCE per type here, yielding closure pairs
# (enc(out, v), dec(buf, off) -> (v, off)); the serving path runs only the
# closures. Re-interpreting annotations per value measured ~40% of YCSB
# server CPU (typing.get_origin alone: 3M calls per 10k-op run).
@functools.lru_cache(maxsize=None)
def _codec_for(t):
    origin = typing.get_origin(t)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) != 1:
            raise CodecError(f"unsupported union {t!r}")
        # inner codec resolved on first non-None use (same lazy rule as
        # lists: an always-None Optional of an unsupported type must work)
        lazy = []

        def inner_codec():
            if not lazy:
                lazy.append(_codec_for(args[0]))
            return lazy[0]

        def enc(out, v):
            if v is None:
                out.append(0)
            else:
                out.append(1)
                inner_codec()[0](out, v)

        def dec(buf, off):
            flag = buf[off]
            off += 1
            if not flag:
                return None, off
            return inner_codec()[1](buf, off)

        return enc, dec
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        # item codec resolved on first non-empty use: an always-empty list
        # of an unsupported item type must keep working (it writes/reads
        # only the zero count — e.g. LogMutation.requests: List[tuple])
        lazy = []

        def item_codec():
            if not lazy:
                lazy.append(_codec_for(item_t))
            return lazy[0]

        def enc(out, v):
            write_varint(out, len(v))
            if not v:
                return
            enc_i = item_codec()[0]
            for item in v:
                enc_i(out, item)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            if not n:
                return [], off
            dec_i = item_codec()[1]
            out = []
            for _ in range(n):
                item, off = dec_i(buf, off)
                out.append(item)
            return out, off

        return enc, dec
    if t is bytes:

        def enc(out, v):
            write_varint(out, len(v))
            out.extend(v)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return bytes(buf[off : off + n]), off + n

        return enc, dec
    if t is str:

        def enc(out, v):
            raw = v.encode("utf-8")
            write_varint(out, len(raw))
            out.extend(raw)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return bytes(buf[off : off + n]).decode("utf-8"), off + n

        return enc, dec
    if t is bool:

        def enc(out, v):
            out.append(1 if v else 0)

        def dec(buf, off):
            return bool(buf[off]), off + 1

        return enc, dec
    if t is int:
        # the hottest codec leaf (decrees, ballots, ids, error codes…):
        # zigzag + varint inlined for the 1-byte case, no helper calls
        def enc(out, v):
            v = int(v)
            v = (v << 1) ^ (v >> 63) if v < 0 else v << 1
            if v < 0x80:
                out.append(v)
            else:
                write_varint(out, v)

        def dec(buf, off):
            b = buf[off]
            if not b & 0x80:
                return (b >> 1) ^ -(b & 1), off + 1
            n, off = read_varint(buf, off)
            return (n >> 1) ^ -(n & 1), off

        return enc, dec
    if isinstance(t, type) and issubclass(t, int):  # IntEnum

        def enc(out, v):
            write_varint(out, _zigzag(int(v)))

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return t(_unzigzag(n)), off

        return enc, dec
    if dataclasses.is_dataclass(t):
        # bind the plan once on first use (lazy, not eager, so recursive
        # dataclasses don't loop during plan construction). The bound plan
        # may be the C fast path (bytes-returning encode / offset-aware
        # decode_from) or the Python _StructPlan.
        plan = []

        def enc(out, v):
            if not plan:
                p = _plan_of(t)
                plan.append((p, isinstance(p, _StructPlan)))
            p, is_py = plan[0]
            if is_py:
                p.encode(out, v)
            else:
                out += p.encode(v)

        def dec(buf, off):
            if not plan:
                p = _plan_of(t)
                plan.append((p, isinstance(p, _StructPlan)))
            p, is_py = plan[0]
            if is_py:
                return p.decode(buf, off)
            return p.decode_from(buf, off)

        return enc, dec
    raise CodecError(f"unsupported type {t!r}")


class _StructPlan:
    __slots__ = ("cls", "names", "encs", "decs", "n", "pairs")

    def __init__(self, cls):
        self.cls = cls
        hints = typing.get_type_hints(cls)
        fields = dataclasses.fields(cls)
        self.names = [f.name for f in fields]
        self.encs = [_codec_for(hints[f.name])[0] for f in fields]
        self.decs = [_codec_for(hints[f.name])[1] for f in fields]
        self.n = len(fields)
        self.pairs = list(zip(self.names, self.encs))

    def encode(self, out, obj):
        # write_varint, not a raw byte: its <0x80 fast path is one append
        # anyway, and a 128+-field dataclass (which the C plan rejects,
        # landing exactly here) still frames correctly
        write_varint(out, self.n)
        for name, enc in self.pairs:
            enc(out, getattr(obj, name))

    def decode(self, buf, off):
        n, off = read_varint(buf, off)
        if n > self.n:
            raise CodecError(f"{self.cls.__name__}: encoder sent {n} "
                             f"fields, decoder knows {self.n}")
        kwargs = {}
        for i in range(n):
            kwargs[self.names[i]], off = self.decs[i](buf, off)
        return self.cls(**kwargs), off


# ----------------------------------------------------------- C fast path
# native/fastcodec.c interprets the same wire format from a node tree
# compiled once per dataclass; ~half the serving CPU was inside the
# Python closures above. Specs mirror _codec_for case by case; any shape
# the C side can't express falls the WHOLE class back to _StructPlan
# (differential fuzzing in tests/test_fastcodec.py pins byte equality).

_fast_plans = {}  # cls -> fastcodec.Plan (two-phase: create, then init)
_plan_lock = threading.RLock()  # serializes ALL plan construction:
# lru_cache does not serialize concurrent misses, and a racing thread
# must never see a created-but-uninitialized fc.Plan


def _lazy_unsupported(t) -> bool:
    """Would the PYTHON codec defer this type lazily (raise only on first
    real use)? That is the oracle for the C 'X' node: anything the Python
    path genuinely supports must NOT narrow to empty-only, or C-path and
    Python-fallback peers split wire compatibility."""
    try:
        _codec_for(t)
        return False
    except CodecError:
        return True


def _spec_for(t, fc, created):
    """Build the C node spec for one annotation, inside the transaction
    `created` (the classes whose plans this top-level build created)."""
    if dataclasses.is_dataclass(t):
        return ("D", _fast_plan(t, fc, created))
    origin = typing.get_origin(t)
    if origin is typing.Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) != 1:
            raise CodecError(f"unsupported union {t!r}")
        try:
            return ("O", _spec_for(args[0], fc, created))
        except CodecError:
            if _lazy_unsupported(args[0]):
                return ("O", ("X",))  # always-None Optionals still work
            raise  # C-specific failure: fall the WHOLE class back
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        try:
            return ("L", _spec_for(item_t, fc, created))
        except CodecError:
            if _lazy_unsupported(item_t):
                return ("L", ("X",))  # empty lists still round-trip
            raise  # C-specific failure: fall the WHOLE class back
    if t is bytes:
        return ("y",)
    if t is str:
        return ("s",)
    if t is bool:
        return ("b",)
    if t is int:
        return ("i",)
    if isinstance(t, type) and issubclass(t, int):  # IntEnum
        return ("e", t)
    raise CodecError(f"unsupported type {t!r}")


def _fast_plan(cls, fc, created=None):
    plan = _fast_plans.get(cls)
    if plan is not None:
        return plan
    # transactional build: a failure anywhere in a recursive plan graph
    # must discard EVERY plan created during this top-level call — an
    # initialized sibling that captured the failing in-flight plan in a
    # 'D' node would otherwise encode it as an empty struct forever
    top = created is None
    if top:
        created = []
    # two-phase so recursive dataclasses resolve to the in-flight plan
    plan = fc.Plan()
    _fast_plans[cls] = plan
    created.append(cls)
    try:
        hints = typing.get_type_hints(cls)
        fields = dataclasses.fields(cls)
        names = tuple(f.name for f in fields)
        specs = tuple(_spec_for(hints[f.name], fc, created)
                      for f in fields)
        plan.init_plan(cls, names, specs)
    except Exception:
        if top:
            for c in created:
                _fast_plans.pop(c, None)
        raise
    return plan


_plan_cache = {}  # cls -> finished plan; published only AFTER init


def _plan_of(cls):
    plan = _plan_cache.get(cls)  # lock-free hot path (GIL-atomic dict)
    if plan is not None:
        return plan
    with _plan_lock:
        plan = _plan_cache.get(cls)
        if plan is not None:
            return plan
        from .. import native

        fc = native.fastcodec()
        if fc is not None:
            fc.register_error(CodecError)
            try:
                plan = _fast_plan(cls, fc)
            except Exception:  # noqa: BLE001 - unsupported shape: Python
                plan = _StructPlan(cls)
        else:
            plan = _StructPlan(cls)
        _plan_cache[cls] = plan
        return plan


def encode(obj) -> bytes:
    """Serialize a rpc.messages dataclass instance."""
    plan = _plan_of(type(obj))
    if type(plan) is _StructPlan:
        out = bytearray()
        plan.encode(out, obj)
        return bytes(out)
    return plan.encode(obj)  # C fast path: one call, returns bytes


def decode(cls, data) -> object:
    """Deserialize `data` into an instance of dataclass `cls`."""
    plan = _plan_of(cls)
    if type(plan) is _StructPlan:
        obj, off = plan.decode(data, 0)
        if off != len(data):
            raise CodecError(
                f"{cls.__name__}: {len(data) - off} trailing bytes")
        return obj
    return plan.decode(data)  # C fast path: trailing check included
