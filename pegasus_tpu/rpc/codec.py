"""Compact binary codec for the rpc.messages dataclasses.

The reference serializes rrdb structs with thrift binary protocol
(src/idl/rrdb.thrift -> src/base/rrdb_types.cpp). This build keeps the same
struct/field shapes (rpc.messages mirrors the .thrift declarations) but
derives the wire format from the dataclass type annotations instead of
generated code:

    int        -> zigzag varint
    bool       -> 1 byte
    bytes      -> varint length + raw
    str        -> varint length + utf-8
    Optional[X]-> presence byte + X
    List[X]    -> varint count + X...
    dataclass  -> varint field count + fields in declaration order
    IntEnum    -> as int

The leading field count lets a decoder accept messages from an older
encoder (missing trailing fields fall back to dataclass defaults), which is
the append-only evolution rule the thrift ids gave the reference.
"""

import dataclasses
import functools
import typing


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: bytearray, n: int) -> None:
    if n < 0x80:  # the overwhelmingly common case: counts, lengths,
        out.append(n)  # small zigzagged ints — one append, no loop
        return
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf, off: int):
    b = buf[off]
    if not b & 0x80:
        return b, off + 1
    shift = 0
    val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


class CodecError(Exception):
    pass


# The annotation interpretation (typing.get_origin / get_args /
# issubclass walks) is done ONCE per type here, yielding closure pairs
# (enc(out, v), dec(buf, off) -> (v, off)); the serving path runs only the
# closures. Re-interpreting annotations per value measured ~40% of YCSB
# server CPU (typing.get_origin alone: 3M calls per 10k-op run).
@functools.lru_cache(maxsize=None)
def _codec_for(t):
    origin = typing.get_origin(t)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if len(args) != 1:
            raise CodecError(f"unsupported union {t!r}")
        # inner codec resolved on first non-None use (same lazy rule as
        # lists: an always-None Optional of an unsupported type must work)
        lazy = []

        def inner_codec():
            if not lazy:
                lazy.append(_codec_for(args[0]))
            return lazy[0]

        def enc(out, v):
            if v is None:
                out.append(0)
            else:
                out.append(1)
                inner_codec()[0](out, v)

        def dec(buf, off):
            flag = buf[off]
            off += 1
            if not flag:
                return None, off
            return inner_codec()[1](buf, off)

        return enc, dec
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        # item codec resolved on first non-empty use: an always-empty list
        # of an unsupported item type must keep working (it writes/reads
        # only the zero count — e.g. LogMutation.requests: List[tuple])
        lazy = []

        def item_codec():
            if not lazy:
                lazy.append(_codec_for(item_t))
            return lazy[0]

        def enc(out, v):
            write_varint(out, len(v))
            if not v:
                return
            enc_i = item_codec()[0]
            for item in v:
                enc_i(out, item)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            if not n:
                return [], off
            dec_i = item_codec()[1]
            out = []
            for _ in range(n):
                item, off = dec_i(buf, off)
                out.append(item)
            return out, off

        return enc, dec
    if t is bytes:

        def enc(out, v):
            write_varint(out, len(v))
            out.extend(v)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return bytes(buf[off : off + n]), off + n

        return enc, dec
    if t is str:

        def enc(out, v):
            raw = v.encode("utf-8")
            write_varint(out, len(raw))
            out.extend(raw)

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return bytes(buf[off : off + n]).decode("utf-8"), off + n

        return enc, dec
    if t is bool:

        def enc(out, v):
            out.append(1 if v else 0)

        def dec(buf, off):
            return bool(buf[off]), off + 1

        return enc, dec
    if t is int:
        # the hottest codec leaf (decrees, ballots, ids, error codes…):
        # zigzag + varint inlined for the 1-byte case, no helper calls
        def enc(out, v):
            v = int(v)
            v = (v << 1) ^ (v >> 63) if v < 0 else v << 1
            if v < 0x80:
                out.append(v)
            else:
                write_varint(out, v)

        def dec(buf, off):
            b = buf[off]
            if not b & 0x80:
                return (b >> 1) ^ -(b & 1), off + 1
            n, off = read_varint(buf, off)
            return (n >> 1) ^ -(n & 1), off

        return enc, dec
    if isinstance(t, type) and issubclass(t, int):  # IntEnum

        def enc(out, v):
            write_varint(out, _zigzag(int(v)))

        def dec(buf, off):
            n, off = read_varint(buf, off)
            return t(_unzigzag(n)), off

        return enc, dec
    if dataclasses.is_dataclass(t):
        # bind the plan once on first use (lazy, not eager, so recursive
        # dataclasses don't loop during plan construction)
        plan = []

        def enc(out, v):
            if not plan:
                plan.append(_plan_of(t))
            plan[0].encode(out, v)

        def dec(buf, off):
            if not plan:
                plan.append(_plan_of(t))
            return plan[0].decode(buf, off)

        return enc, dec
    raise CodecError(f"unsupported type {t!r}")


class _StructPlan:
    __slots__ = ("cls", "names", "encs", "decs", "n", "pairs")

    def __init__(self, cls):
        self.cls = cls
        hints = typing.get_type_hints(cls)
        fields = dataclasses.fields(cls)
        self.names = [f.name for f in fields]
        self.encs = [_codec_for(hints[f.name])[0] for f in fields]
        self.decs = [_codec_for(hints[f.name])[1] for f in fields]
        self.n = len(fields)
        assert self.n < 0x80  # encode() writes the count as one raw byte
        self.pairs = list(zip(self.names, self.encs))

    def encode(self, out, obj):
        out.append(self.n)  # field counts are tiny; 1-byte varint always
        for name, enc in self.pairs:
            enc(out, getattr(obj, name))

    def decode(self, buf, off):
        n, off = read_varint(buf, off)
        if n > self.n:
            raise CodecError(f"{self.cls.__name__}: encoder sent {n} "
                             f"fields, decoder knows {self.n}")
        kwargs = {}
        for i in range(n):
            kwargs[self.names[i]], off = self.decs[i](buf, off)
        return self.cls(**kwargs), off


@functools.lru_cache(maxsize=None)
def _plan_of(cls) -> _StructPlan:
    return _StructPlan(cls)


def encode(obj) -> bytes:
    """Serialize a rpc.messages dataclass instance."""
    out = bytearray()
    _plan_of(type(obj)).encode(out, obj)
    return bytes(out)


def decode(cls, data) -> object:
    """Deserialize `data` into an instance of dataclass `cls`."""
    obj, off = _plan_of(cls).decode(data, 0)
    if off != len(data):
        raise CodecError(f"{cls.__name__}: {len(data) - off} trailing bytes")
    return obj
