"""Compact binary codec for the rpc.messages dataclasses.

The reference serializes rrdb structs with thrift binary protocol
(src/idl/rrdb.thrift -> src/base/rrdb_types.cpp). This build keeps the same
struct/field shapes (rpc.messages mirrors the .thrift declarations) but
derives the wire format from the dataclass type annotations instead of
generated code:

    int        -> zigzag varint
    bool       -> 1 byte
    bytes      -> varint length + raw
    str        -> varint length + utf-8
    Optional[X]-> presence byte + X
    List[X]    -> varint count + X...
    dataclass  -> varint field count + fields in declaration order
    IntEnum    -> as int

The leading field count lets a decoder accept messages from an older
encoder (missing trailing fields fall back to dataclass defaults), which is
the append-only evolution rule the thrift ids gave the reference.
"""

import dataclasses
import functools
import typing


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf, off: int):
    shift = 0
    val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


class CodecError(Exception):
    pass


@functools.lru_cache(maxsize=None)
def _fields_of(cls):
    hints = typing.get_type_hints(cls)
    return [(f.name, hints[f.name], f) for f in dataclasses.fields(cls)]


def _encode_value(out: bytearray, t, v) -> None:
    origin = typing.get_origin(t)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if v is None:
            out.append(0)
        else:
            out.append(1)
            _encode_value(out, args[0], v)
    elif origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        write_varint(out, len(v))
        for item in v:
            _encode_value(out, item_t, item)
    elif t is bytes:
        write_varint(out, len(v))
        out.extend(v)
    elif t is str:
        raw = v.encode("utf-8")
        write_varint(out, len(raw))
        out.extend(raw)
    elif t is bool:
        out.append(1 if v else 0)
    elif t is int or (isinstance(t, type) and issubclass(t, int)):
        write_varint(out, _zigzag(int(v)))
    elif dataclasses.is_dataclass(t):
        _encode_struct(out, t, v)
    else:
        raise CodecError(f"unsupported type {t!r}")


def _decode_value(buf, off: int, t):
    origin = typing.get_origin(t)
    if origin is typing.Union:
        args = [a for a in typing.get_args(t) if a is not type(None)]
        flag = buf[off]
        off += 1
        if not flag:
            return None, off
        return _decode_value(buf, off, args[0])
    if origin in (list, typing.List):
        (item_t,) = typing.get_args(t)
        n, off = read_varint(buf, off)
        out = []
        for _ in range(n):
            item, off = _decode_value(buf, off, item_t)
            out.append(item)
        return out, off
    if t is bytes:
        n, off = read_varint(buf, off)
        return bytes(buf[off : off + n]), off + n
    if t is str:
        n, off = read_varint(buf, off)
        return bytes(buf[off : off + n]).decode("utf-8"), off + n
    if t is bool:
        return bool(buf[off]), off + 1
    if t is int or (isinstance(t, type) and issubclass(t, int)):
        n, off = read_varint(buf, off)
        v = _unzigzag(n)
        return (t(v) if t is not int else v), off
    if dataclasses.is_dataclass(t):
        return _decode_struct(buf, off, t)
    raise CodecError(f"unsupported type {t!r}")


def _encode_struct(out: bytearray, cls, obj) -> None:
    fields = _fields_of(cls)
    write_varint(out, len(fields))
    for name, t, _ in fields:
        _encode_value(out, t, getattr(obj, name))


def _decode_struct(buf, off: int, cls):
    n, off = read_varint(buf, off)
    fields = _fields_of(cls)
    if n > len(fields):
        raise CodecError(
            f"{cls.__name__}: encoder sent {n} fields, decoder knows {len(fields)}")
    kwargs = {}
    for i in range(n):
        name, t, _ = fields[i]
        kwargs[name], off = _decode_value(buf, off, t)
    obj = cls(**kwargs)
    return obj, off


def encode(obj) -> bytes:
    """Serialize a rpc.messages dataclass instance."""
    out = bytearray()
    _encode_struct(out, type(obj), obj)
    return bytes(out)


def decode(cls, data) -> object:
    """Deserialize `data` into an instance of dataclass `cls`."""
    obj, off = _decode_struct(data, 0, cls)
    if off != len(data):
        raise CodecError(f"{cls.__name__}: {len(data) - off} trailing bytes")
    return obj
