from . import messages
from .messages import Status, FilterType, CasCheckType, MutateOperation

__all__ = ["messages", "Status", "FilterType", "CasCheckType", "MutateOperation"]
