"""rrdb task-code names (src/include/rrdb/rrdb.code.definition.h:25-40).

One canonical home so the server dispatcher, serverlet, client, and
duplicator all agree; write codes carry batching semantics (BATCHABLE) the
dispatcher uses like the reference's ALLOW_BATCH task-spec flag.
"""

RPC_PUT = "RPC_RRDB_RRDB_PUT"
RPC_MULTI_PUT = "RPC_RRDB_RRDB_MULTI_PUT"
RPC_REMOVE = "RPC_RRDB_RRDB_REMOVE"
RPC_MULTI_REMOVE = "RPC_RRDB_RRDB_MULTI_REMOVE"
RPC_INCR = "RPC_RRDB_RRDB_INCR"
RPC_CHECK_AND_SET = "RPC_RRDB_RRDB_CHECK_AND_SET"
RPC_CHECK_AND_MUTATE = "RPC_RRDB_RRDB_CHECK_AND_MUTATE"
RPC_DUPLICATE = "RPC_RRDB_RRDB_DUPLICATE"
RPC_BULK_LOAD_INGEST = "RPC_RRDB_RRDB_BULK_LOAD"
# admin no-op mutation: rides the PacificA prepare path so every replica
# computes a consistency digest at the SAME applied decree (ISSUE 8)
RPC_TRIGGER_AUDIT = "RPC_RRDB_RRDB_TRIGGER_AUDIT"

RPC_GET = "RPC_RRDB_RRDB_GET"
RPC_MULTI_GET = "RPC_RRDB_RRDB_MULTI_GET"
RPC_SORTKEY_COUNT = "RPC_RRDB_RRDB_SORTKEY_COUNT"
RPC_TTL = "RPC_RRDB_RRDB_TTL"
RPC_GET_SCANNER = "RPC_RRDB_RRDB_GET_SCANNER"
RPC_SCAN = "RPC_RRDB_RRDB_SCAN"
RPC_CLEAR_SCANNER = "RPC_RRDB_RRDB_CLEAR_SCANNER"

BATCHABLE = {RPC_PUT, RPC_REMOVE}
