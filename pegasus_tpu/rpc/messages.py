"""Wire message types — the rrdb service surface (src/idl/rrdb.thrift:23-318).

Python dataclass mirrors of every request/response struct; the binary codec
(rpc.codec) serializes them for the TCP transport. Error codes in responses
follow the storage-status numbering the reference exposes to clients
(rocksdb::Status codes embedded in thrift `error` fields).
"""

import enum
from dataclasses import dataclass, field
from typing import List, Optional


class Status(enum.IntEnum):
    """Storage status codes carried in response.error."""

    OK = 0
    NOT_FOUND = 1
    CORRUPTION = 2
    NOT_SUPPORTED = 3
    INVALID_ARGUMENT = 4
    IO_ERROR = 5
    INCOMPLETE = 7
    TRY_AGAIN = 13


class FilterType(enum.IntEnum):  # rrdb.thrift:23-29
    NO_FILTER = 0
    MATCH_ANYWHERE = 1
    MATCH_PREFIX = 2
    MATCH_POSTFIX = 3


class CasCheckType(enum.IntEnum):  # rrdb.thrift:31-59
    NO_CHECK = 0
    VALUE_NOT_EXIST = 1
    VALUE_NOT_EXIST_OR_EMPTY = 2
    VALUE_EXIST = 3
    VALUE_NOT_EMPTY = 4
    VALUE_MATCH_ANYWHERE = 5
    VALUE_MATCH_PREFIX = 6
    VALUE_MATCH_POSTFIX = 7
    VALUE_BYTES_LESS = 8
    VALUE_BYTES_LESS_OR_EQUAL = 9
    VALUE_BYTES_EQUAL = 10
    VALUE_BYTES_GREATER_OR_EQUAL = 11
    VALUE_BYTES_GREATER = 12
    VALUE_INT_LESS = 13
    VALUE_INT_LESS_OR_EQUAL = 14
    VALUE_INT_EQUAL = 15
    VALUE_INT_GREATER_OR_EQUAL = 16
    VALUE_INT_GREATER = 17


class MutateOperation(enum.IntEnum):  # rrdb.thrift:61-65
    PUT = 0
    DELETE = 1


@dataclass
class KeyRequest:
    """Single-key request body (the reference passes a raw blob for
    get/remove/ttl; sortkey_count passes the hash_key blob)."""

    key: bytes = b""


@dataclass
class UpdateRequest:  # update_request
    key: bytes
    value: bytes
    expire_ts_seconds: int = 0


@dataclass
class UpdateResponse:  # update_response
    error: int = 0
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class ReadResponse:  # read_response
    error: int = 0
    value: bytes = b""
    app_id: int = 0
    partition_index: int = 0
    server: str = ""


@dataclass
class TTLResponse:  # ttl_response
    error: int = 0
    ttl_seconds: int = 0
    app_id: int = 0
    partition_index: int = 0
    server: str = ""


@dataclass
class CountResponse:  # count_response
    error: int = 0
    count: int = 0
    app_id: int = 0
    partition_index: int = 0
    server: str = ""


@dataclass
class KeyValue:  # key_value
    key: bytes
    value: bytes = b""
    expire_ts_seconds: Optional[int] = None


@dataclass
class MultiPutRequest:  # multi_put_request
    hash_key: bytes
    kvs: List[KeyValue] = field(default_factory=list)
    expire_ts_seconds: int = 0


@dataclass
class MultiRemoveRequest:  # multi_remove_request
    hash_key: bytes
    sort_keys: List[bytes] = field(default_factory=list)
    max_count: int = 0  # deprecated upstream


@dataclass
class MultiRemoveResponse:  # multi_remove_response
    error: int = 0
    count: int = 0
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class MultiGetRequest:  # multi_get_request
    hash_key: bytes
    sort_keys: List[bytes] = field(default_factory=list)
    max_kv_count: int = 0
    max_kv_size: int = 0
    no_value: bool = False
    start_sortkey: bytes = b""
    stop_sortkey: bytes = b""
    start_inclusive: bool = True
    stop_inclusive: bool = False
    sort_key_filter_type: int = FilterType.NO_FILTER
    sort_key_filter_pattern: bytes = b""
    reverse: bool = False


@dataclass
class MultiGetResponse:  # multi_get_response
    error: int = 0
    kvs: List[KeyValue] = field(default_factory=list)
    app_id: int = 0
    partition_index: int = 0
    server: str = ""


@dataclass
class IncrRequest:  # incr_request
    key: bytes
    increment: int = 0
    expire_ts_seconds: int = 0  # 0 keep ttl; >0 reset; <0 clear


@dataclass
class IncrResponse:  # incr_response
    error: int = 0
    new_value: int = 0
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class CheckAndSetRequest:  # check_and_set_request
    hash_key: bytes
    check_sort_key: bytes = b""
    check_type: int = CasCheckType.NO_CHECK
    check_operand: bytes = b""
    set_diff_sort_key: bool = False
    set_sort_key: bytes = b""
    set_value: bytes = b""
    set_expire_ts_seconds: int = 0
    return_check_value: bool = False


@dataclass
class CheckAndSetResponse:  # check_and_set_response
    error: int = 0
    check_value_returned: bool = False
    check_value_exist: bool = False
    check_value: bytes = b""
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class Mutate:  # mutate
    operation: int
    sort_key: bytes
    value: bytes = b""
    set_expire_ts_seconds: int = 0


@dataclass
class CheckAndMutateRequest:  # check_and_mutate_request
    hash_key: bytes
    check_sort_key: bytes = b""
    check_type: int = CasCheckType.NO_CHECK
    check_operand: bytes = b""
    mutate_list: List[Mutate] = field(default_factory=list)
    return_check_value: bool = False


@dataclass
class CheckAndMutateResponse:  # check_and_mutate_response
    error: int = 0
    check_value_returned: bool = False
    check_value_exist: bool = False
    check_value: bytes = b""
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class GetScannerRequest:  # get_scanner_request
    start_key: bytes = b""
    stop_key: bytes = b""
    start_inclusive: bool = True
    stop_inclusive: bool = False
    batch_size: int = 1000
    no_value: bool = False
    hash_key_filter_type: int = FilterType.NO_FILTER
    hash_key_filter_pattern: bytes = b""
    sort_key_filter_type: int = FilterType.NO_FILTER
    sort_key_filter_pattern: bytes = b""
    validate_partition_hash: bool = True
    return_expire_ts: bool = False


@dataclass
class ScanRequest:  # scan_request
    context_id: int


@dataclass
class ScanResponse:  # scan_response
    error: int = 0
    kvs: List[KeyValue] = field(default_factory=list)
    context_id: int = 0
    app_id: int = 0
    partition_index: int = 0
    server: str = ""


@dataclass
class BulkLoadIngestRequest:
    """Replicated ingestion command (the ingestion_request role): every
    replica of the partition reads the shared provider set and ingests it
    at the same decree, so bulk-loaded data survives failover."""

    provider_root: str = ""
    app_name: str = ""
    partition_count: int = 0


@dataclass
class BulkLoadIngestResponse:
    error: int = 0
    ingested_records: int = 0
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0
    server: str = ""


@dataclass
class DuplicateRequest:  # duplicate_request
    timestamp: int = 0
    task_code: str = ""
    raw_message: bytes = b""
    cluster_id: int = 0
    verify_timetag: bool = False


@dataclass
class DuplicateResponse:  # duplicate_response
    error: int = 0
    error_hint: str = ""


@dataclass
class TriggerAuditRequest:
    """Admin no-op mutation: every replica computes an order-independent
    digest of its engine state at the decree this mutation applies at.
    `now` is the expiry clock the PRIMARY chose — all replicas filter
    TTL-expired records against the same instant, so clock skew cannot
    fake a mismatch. `pmask` (partition_count - 1) is the ownership
    mask the PRIMARY chose: every replica excludes records the
    partition no longer owns (split stale halves) against the SAME
    mask — the env-spread partition_version is asynchronous per
    replica, so anchoring the mask in the mutation is what keeps a
    digest during a split from faking a mismatch (append-only codec
    evolution: old senders leave it 0 = engine-local mask)."""

    audit_id: int = 0
    now: int = 0
    pmask: int = 0


@dataclass
class TriggerAuditResponse:
    error: int = 0
    app_id: int = 0
    partition_index: int = 0
    decree: int = 0            # the decree the digest is anchored at
    digest: str = ""           # 32-hex-char order-independent state digest
    records: int = 0           # live records folded into the digest
    server: str = ""


@dataclass
class LearnBlockEntry:
    """One checkpoint block in a learn manifest: filename + size +
    content digest (the delta-handshake identity, ISSUE 13)."""

    name: str = ""
    size: int = 0
    digest: str = ""


@dataclass
class LearnPrepareRequest:
    """Manifest-diff handshake, learner -> primary: `have` is the
    learner's live block set; the primary pins an immutable checkpoint
    and answers with the full manifest plus which blocks are missing.
    delta=False (the kill switch) ships everything regardless of
    `have`."""

    app_id: int = 0
    pidx: int = 0
    delta: bool = True
    have: List[LearnBlockEntry] = field(default_factory=list)
    # trailing, ISSUE 16: the learner's job-trace id — the serving
    # primary attributes its checkpoint pin to the learn's timeline
    job: str = ""


@dataclass
class LearnPrepareResponse:
    error: int = 0
    error_text: str = ""
    learn_id: int = 0          # pin handle for fetch/tail/finish
    ckpt_decree: int = 0       # the pinned checkpoint's manifest decree
    ballot: int = 0
    last_committed: int = 0
    blocks: List[LearnBlockEntry] = field(default_factory=list)
    missing: List[str] = field(default_factory=list)
    # decree-anchored digest of the pinned checkpoint (PR 8 fold) plus
    # the TTL clock + ownership mask it was computed against, so the
    # learner can prove the shipped state byte-consistent on arrival
    digest: str = ""
    digest_now: int = 0
    digest_pmask: int = 0


@dataclass
class LearnFetchRequest:
    """One bounded chunk of one pinned block (primary serves it
    lock-free; the learner pipelines these through call_many waves)."""

    app_id: int = 0
    pidx: int = 0
    learn_id: int = 0
    name: str = ""
    offset: int = 0
    length: int = 0


@dataclass
class LearnFetchResponse:
    error: int = 0
    error_text: str = ""
    data: bytes = b""
    crc: int = 0               # crc32 of `data` (per-chunk integrity)
    total: int = 0             # whole-block size


@dataclass
class LearnTailRequest:
    app_id: int = 0
    pidx: int = 0
    learn_id: int = 0


@dataclass
class LearnTailResponse:
    error: int = 0
    error_text: str = ""
    tail: List[bytes] = field(default_factory=list)  # encoded LogMutations
    last_committed: int = 0
    ballot: int = 0


@dataclass
class LearnFinishRequest:
    """Release the learn pin (checkpoint + log GC resume)."""

    app_id: int = 0
    pidx: int = 0
    learn_id: int = 0


# ------------------------------------------------- compaction offload (ISSUE 14)
# One device-owning compaction service per TPU host serves many CPU-only
# replica nodes: a tenant ships packed runs (content-addressed, chunked,
# CRC-checked — the learn plane's streaming shape), the service merges
# them on its device and the tenant fetches the merged output back.
# Block identity reuses LearnBlockEntry (name + size + content digest);
# chunk fetches reuse LearnFetchResponse (data + crc + total).


@dataclass
class OffloadBeginRequest:
    """Open one merge job: the manifest of packed runs (newest first —
    run order IS merge priority) plus the merge options as JSON (the
    wire-safe CompactOptions subset; user rules and default-TTL rewrite
    stay tenant-side, the sharded_compact_block post-filter pattern)."""

    tenant: str = ""
    gpid: str = ""
    runs: List[LearnBlockEntry] = field(default_factory=list)
    opts_json: str = ""
    # trailing, ISSUE 16: the tenant's job-trace id; the service records
    # its ship/merge hops against it and returns them on merge
    job: str = ""


@dataclass
class OffloadBeginResponse:
    error: int = 0
    error_text: str = ""
    job_id: int = 0
    # run names already fully staged (content-address hit from an earlier
    # interrupted ship or a sibling tenant) — the resume/dedup set
    staged: List[str] = field(default_factory=list)


@dataclass
class OffloadShipRequest:
    """One bounded chunk of one packed run, written at its offset (chunks
    of a block may land out of order across the RPC pool)."""

    job_id: int = 0
    name: str = ""
    offset: int = 0
    data: bytes = b""
    crc: int = 0               # crc32 of `data`


@dataclass
class OffloadShipResponse:
    error: int = 0
    error_text: str = ""
    landed: bool = False       # block complete + whole-file digest verified


@dataclass
class OffloadMergeRequest:
    job_id: int = 0


@dataclass
class OffloadMergeResponse:
    error: int = 0
    error_text: str = ""
    outputs: List[LearnBlockEntry] = field(default_factory=list)
    stats_json: str = ""
    # trailing, ISSUE 16: the service-side hop records for the job (JSON
    # list) — the tenant stitches them into its own timeline, so one
    # timeline spans both hosts
    spans_json: str = ""


@dataclass
class OffloadFetchRequest:
    """One bounded chunk of a merged output block (response:
    LearnFetchResponse — data + per-chunk crc + whole-block size)."""

    job_id: int = 0
    name: str = ""
    offset: int = 0
    length: int = 0


@dataclass
class OffloadFinishRequest:
    """Release the job (staged runs stay content-addressed for reuse;
    the job dir and its outputs drop)."""

    job_id: int = 0


def match_filter(filter_type: int, pattern: bytes, data: bytes) -> bool:
    """The anywhere/prefix/postfix matcher shared by scans and multi_get."""
    if filter_type == FilterType.NO_FILTER or not pattern:
        return True
    if len(data) < len(pattern):
        return False
    if filter_type == FilterType.MATCH_ANYWHERE:
        return pattern in data
    if filter_type == FilterType.MATCH_PREFIX:
        return data.startswith(pattern)
    if filter_type == FilterType.MATCH_POSTFIX:
        return data.endswith(pattern)
    raise ValueError(f"bad filter type {filter_type}")
