"""TCP RPC transport: framed request/response with task-code dispatch.

The rDSN network layer this build re-provides (SURVEY.md §2.4 'RPC /
network'): a serverlet registers handlers by task-code name
(reference: storage_serverlet::register_rpc_handlers,
src/server/pegasus_read_service.h:36-84) and a connection-pooling client
issues pipelined request/response calls with per-call timeouts
(reference: rrdb_client over partition_resolver::call_op,
src/include/rrdb/rrdb.client.h:41-120).

Frame: u32 LE payload length | payload. Payload = codec-encoded RpcHeader
followed by the body bytes. Requests and responses share the frame; the
`is_response` flag disambiguates (one socket carries both directions).
Every connection is full-duplex: a reader thread matches responses to
pending sequence numbers, so many calls can be in flight at once.
"""

import socket
import socketserver
import struct
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass

from . import codec
from ..runtime.fail_points import FailPointError, fail_point
from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread
from ..runtime.tracing import REQUEST_TRACER, TraceContext


# rDSN-style error codes carried at the RPC layer (engine-level status stays
# in each response body's `error` field, like the reference splits dsn::error
# from rocksdb status)
ERR_OK = 0
ERR_HANDLER_NOT_FOUND = 1
ERR_TIMEOUT = 2
ERR_INVALID_STATE = 3       # e.g. not primary / partition not served here
ERR_OBJECT_NOT_FOUND = 4    # no such app / partition
ERR_BUSY = 5
ERR_INVALID_DATA = 6
ERR_NETWORK_FAILURE = 7
ERR_FORWARD_TO_PRIMARY = 8  # follower meta: retry against the leader


@dataclass
class RpcHeader:
    seq: int = 0
    code: str = ""
    app_id: int = 0
    partition_index: int = 0
    partition_hash: int = 0
    error: int = 0          # response-only: rpc-level error
    error_text: str = ""
    is_response: bool = False
    # request tracing (runtime/tracing.py RequestTracer): the caller's
    # trace context rides every request frame; 0 = untraced. Appended
    # last per the codec's append-only evolution rule, so frames from an
    # older encoder still decode (the fields default).
    trace_id: int = 0
    trace_sampled: bool = False
    # True on every frame of a connection that carries ONE partition's
    # traffic only (ConnectionPool shard keys). A partition-group router
    # may hand such a connection off to the owning group executor wholesale
    # (replication/serve_groups.py); unsharded connections stay on the
    # per-frame relay path. Appended last (evolution rule).
    sharded: bool = False


class RpcError(Exception):
    def __init__(self, err: int, text: str = ""):
        super().__init__(f"rpc error {err}: {text}")
        self.err = err
        self.text = text


def _send_frame(sock, header: RpcHeader, body: bytes, lock=None) -> None:
    h = codec.encode(header)
    hl = len(h)
    # one buffer, one copy of the body (the old payload+frame concats
    # copied large values twice per send)
    frame = bytearray(8 + hl + len(body))
    struct.pack_into("<II", frame, 0, 4 + hl + len(body), hl)
    frame[8 : 8 + hl] = h
    frame[8 + hl :] = body
    if lock:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


# the native read data plane's attribution counters (ISSUE 20): waves
# drained by the C reader, frames that arrived pre-binned into hot-code
# batches, and vectored sends. With PEGASUS_NATIVE=0 all four flatline —
# the bench A/B and the metric-history fallback regression both read
# these.
_C_WAVE = counters.rate("native.wave_count")
_C_BATCH_FRAMES = counters.rate("native.batch_frames")
_C_WRITEV = counters.rate("native.writev_count")
_C_WRITEV_BYTES = counters.rate("native.writev_bytes")


def _native_writer():
    """-> the fastcodec module when the native vectored writer should be
    used, else None (knob off, extension absent/stale, or the
    ``serve.native`` fail point forcing the pure-Python twin)."""
    from .. import native

    if not native.native_on():
        return None
    fc = native.fastcodec()
    if fc is None or not hasattr(fc, "sendmsg_frames"):
        return None
    try:
        if fail_point("serve.native") is not None:
            return None
    except FailPointError:
        return None
    return fc


def _send_encoded_frames(sock, enc, lock=None) -> None:
    """Vectored frame write: `enc` is [(header_bytes, body), ...] and the
    whole wave leaves in one call. Native path: fastcodec.sendmsg_frames
    gathers length prefixes + headers + bodies into iovecs and sendmsg()s
    with the GIL released (zero body copies). Fallback: one coalesced
    bytearray + sendall. Both write the exact same bytes in the exact
    same order — the byte-identity test pins that."""
    fc = _native_writer()
    ctx = lock if lock is not None else nullcontext()
    with ctx:
        if fc is not None:
            fd = sock.fileno()
            if fd >= 0:
                sent = fc.sendmsg_frames(fd, enc)
                _C_WRITEV.increment()
                _C_WRITEV_BYTES.increment(sent)
                return
        buf = bytearray()
        for h, b in enc:
            buf += struct.pack("<II", 4 + len(h) + len(b), len(h))
            buf += h
            buf += b
        sock.sendall(buf)


def _send_frames(sock, pairs, lock=None) -> None:
    """_send_encoded_frames over [(RpcHeader, body), ...]."""
    _send_encoded_frames(sock, [(codec.encode(h), b) for h, b in pairs],
                         lock=lock)


class _FrameReader:
    """Buffered framing for a socket with a SINGLE reader thread: one
    kernel recv typically yields several pipelined frames (length word +
    header + body used to cost 2+ recv syscalls per frame)."""

    __slots__ = ("sock", "buf", "pos", "hot")

    def __init__(self, sock, initial: bytes = b"", hot=()):
        self.sock = sock
        self.buf = bytearray(initial)
        self.pos = 0
        self.hot = frozenset(hot)

    def _fill(self, need: int) -> None:
        buf = self.buf
        if self.pos and (len(buf) == self.pos or self.pos > (1 << 16)):
            del buf[: self.pos]  # compact consumed bytes
            self.pos = 0
        while len(buf) - self.pos < need:
            chunk = self.sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk

    def frame(self):
        self._fill(4)
        pos = self.pos
        (plen,) = struct.unpack_from("<I", self.buf, pos)
        self._fill(4 + plen)
        pos = self.pos  # _fill may have compacted
        (hlen,) = struct.unpack_from("<I", self.buf, pos + 4)
        if plen < 4 or hlen > plen - 4:
            # same validation, same error class as the C reader — the
            # adversarial-frame differential test pins the parity
            raise codec.CodecError("corrupt frame lengths")
        mv = memoryview(self.buf)
        try:
            header = codec.decode(RpcHeader, mv[pos + 8 : pos + 8 + hlen])
            body = bytes(mv[pos + 8 + hlen : pos + 4 + plen])  # ONE copy
        finally:
            mv.release()  # buf must be resizable before the next _fill
        self.pos = pos + 4 + plen
        return header, body

    def _buffered_frame(self) -> bool:
        """A complete frame sits in the buffer (no recv needed)?"""
        avail = len(self.buf) - self.pos
        if avail < 4:
            return False
        (plen,) = struct.unpack_from("<I", self.buf, self.pos)
        return avail >= 4 + plen

    def wave(self):
        """-> every complete frame currently available (blocking for the
        first): the pure-Python twin of fastcodec.FrameReader.read_wave."""
        out = [self.frame()]
        while self._buffered_frame():
            out.append(self.frame())
        return out

    def wave_batched(self):
        """wave() binned by hot task code — the pure-Python twin of
        fastcodec's read_wave_binned, same coalescing semantics: frames
        whose code is in `hot` join ONE (code, frames) entry opened at
        their first frame's arrival position; every other frame gets a
        singleton entry in arrival order."""
        out, bins = [], {}
        for header, body in self.wave():
            code = header.code
            lst = bins.get(code)
            if lst is not None:
                lst.append((header, body))
                continue
            lst = [(header, body)]
            if code in self.hot:
                bins[code] = lst
            out.append((code, lst))
        return out


class _NativeFrameReader:
    """fastcodec.FrameReader wrapper: drains a pipelined frame wave in ONE
    C call (recv with the GIL released + header decode + body slicing),
    instead of re-entering Python per frame."""

    __slots__ = ("sock", "fr")

    def __init__(self, fc, sock, initial: bytes = b"", hot=()):
        self.sock = sock
        self.fr = fc.FrameReader(codec._plan_of(RpcHeader), tuple(hot))
        if initial:
            self.fr.feed(initial)

    def _fd(self):
        # resolve the fd per wave, never cache it: after sock.close() (a
        # timed-out connection being invalidated under this reader) the
        # number can be REUSED by a brand-new socket, and a cached fd
        # would recv another connection's bytes. fileno() on a closed
        # socket returns -1 -> EBADF -> clean reader exit.
        fd = self.sock.fileno()
        if fd < 0:
            raise ConnectionError("socket closed")
        return fd

    def wave(self):
        wave = self.fr.read_wave(self._fd())
        _C_WAVE.increment()
        return wave

    def wave_batched(self):
        """Binned dispatch wave: header parse + hot-code binning both
        happen in C; Python sees [(code, [(header, body), ...]), ...]."""
        wave = self.fr.read_wave_binned(self._fd())
        _C_WAVE.increment()
        for _, frames in wave:
            if len(frames) > 1:
                _C_BATCH_FRAMES.increment(len(frames))
        return wave


def make_frame_reader(sock, initial: bytes = b"", hot=()):
    """Best available frame reader for a blocking socket: the C wave
    drainer when PEGASUS_NATIVE is on, fastcodec is importable (with the
    binned-wave entry point — an older .so without it must not be half
    used) AND the RpcHeader plan compiled to a C plan (a Python-plan
    header would hand the C reader an incompatible object), else the
    buffered Python reader. `hot` is the task codes to coalesce into
    per-code batches in wave_batched()."""
    from .. import native

    if native.native_on():
        fc = native.fastcodec()
        if fc is not None and hasattr(fc, "FrameReader") \
                and hasattr(fc.FrameReader, "read_wave_binned") \
                and isinstance(codec._plan_of(RpcHeader), fc.Plan):
            return _NativeFrameReader(fc, sock, initial, hot)
    return _FrameReader(sock, initial, hot)


class RpcServer:
    """Threaded TCP serverlet. Handlers: code -> fn(header, body) -> body.

    A handler may raise RpcError to return an rpc-level error. Handlers run
    on the connection's thread (the engine has its own locking)."""

    # requests run on a shared worker pool (a thread spawn per request cost
    # ~60us x thousands/s on the serving path). Requests beyond the pool
    # QUEUE (bounded dispatch — the old design spawned an unbounded raw
    # thread per overflow request), except PRIORITY_CODES: replication and
    # lifecycle RPCs keep the escape-hatch thread, because a pool whose 16
    # workers all sit in client_write waiting for secondary prepare acks
    # must still serve the prepares those acks depend on (the classic
    # distributed pool deadlock).
    POOL_WORKERS = 16
    PRIORITY_CODES = frozenset({
        "RPC_PREPARE", "RPC_LEARN", "RPC_FD_FAILURE_DETECTOR_PING",
        "RPC_LEARN_PREPARE", "RPC_LEARN_FETCH", "RPC_LEARN_TAIL",
        "RPC_LEARN_FINISH",
        "RPC_CONFIG_PROPOSAL_OPEN_REPLICA",
        "RPC_CONFIG_PROPOSAL_CLOSE_REPLICA",
    })

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._handlers = {}
        # hot read codes with a BATCH handler: fn(headers, bodies) ->
        # per-frame results (bytes | RpcError | Exception). The frame
        # reader coalesces these codes in C (ISSUE 20) and dispatch
        # enters Python once per batch instead of once per frame.
        self._batch_handlers = {}
        self._middlewares = []   # fn(code, header, body, next) -> body
        from ..runtime.tasking import tracked_executor

        self._pool = tracked_executor(self.POOL_WORKERS,
                                      thread_name_prefix="rpc-serve")
        self._busy = 0
        self._busy_lock = threading.Lock()
        # live accepted connections: stop() shuts them down so a stopped
        # server looks like a KILLED one to its peers (in-flight calls
        # fail fast instead of dangling until the client timeout — the
        # chaos service-kill actor depends on this)
        self._conn_lock = threading.Lock()
        self._conns = set()  #: guarded_by self._conn_lock
        self._depth_gauge = counters.number("rpc.server.dispatch_queue_depth")
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer.serve_connection(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Server((host, port), _Handler)
        self.address = self._srv.server_address  # (host, actual_port)
        self._thread = spawn_thread(self._srv.serve_forever, daemon=True,
                                    start=False)

    def serve_connection(self, sock, initial: bytes = b"") -> None:
        """Serve one connection to exhaustion: drain pipelined frame waves
        (fastcodec.FrameReader when available — frame read + header decode
        stay in C for the whole wave) and dispatch each request."""
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            return
        wlock = threading.Lock()
        dispatch = self._dispatch
        with self._conn_lock:
            self._conns.add(sock)
        try:
            # always bin hot codes — the C wave amortization holds even
            # when middlewares (tracer/profiler/fault toollets) are
            # installed, because _dispatch_batch routes those batches
            # back through the per-frame path, middleware chain intact
            hot = tuple(self._batch_handlers)
            reader = make_frame_reader(sock, initial, hot)
            while True:
                for code, frames in reader.wave_batched():
                    if len(frames) == 1:
                        header, body = frames[0]
                        dispatch(sock, wlock, header, body)
                    else:
                        self._dispatch_batch(sock, wlock, code, frames)
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conn_lock:
                self._conns.discard(sock)

    def serve_adopted(self, sock, initial: bytes = b"") -> None:
        """Adopt a connection accepted elsewhere (the partition-group
        router hands client sockets over with their already-read bytes);
        serving runs on a fresh daemon thread, closing the socket at EOF."""
        def run():
            try:
                self.serve_connection(sock, initial)
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        spawn_thread(run, daemon=True, name="rpc-adopted")

    def register(self, code: str, handler) -> None:
        self._handlers[code] = handler

    def register_batch(self, code: str, handler) -> None:
        """Register a batch handler: fn(headers, bodies) -> one result
        per frame, each bytes (success), RpcError, or any Exception
        (encoded exactly like the per-frame path encodes them). The code
        MUST also have a per-frame handler — singleton frames, traced
        frames, middleware'd connections and the serve.native fallback
        all still route per frame."""
        self._batch_handlers[code] = handler

    def register_serverlet(self, obj) -> None:
        """Register every (code, fn) pair from obj.rpc_handlers(), plus
        obj.rpc_batch_handlers() when the serverlet provides them."""
        for code, fn in obj.rpc_handlers().items():
            self.register(code, fn)
        for code, fn in getattr(obj, "rpc_batch_handlers",
                                dict)().items():
            self.register_batch(code, fn)

    def add_middleware(self, mw) -> None:
        """mw(code, header, body, next_fn) -> response body. The rDSN
        toollet seam: tracer/profiler/fault-injector wrap every handler."""
        self._middlewares.append(mw)

    def start(self) -> "RpcServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        # shutdown (never close — the handler thread owns the fd and a
        # cross-thread close could race a reused descriptor) every live
        # connection: peers see EOF now, exactly like a process kill,
        # instead of requests silently dangling until their timeouts
        with self._conn_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._pool.shutdown(wait=False)

    def _dispatch(self, sock, wlock, header: RpcHeader, body: bytes) -> None:
        # serve.dispatch: the chaos seam for a wedged group executor —
        # sleep(ms) stalls this connection's whole dispatch loop (frames
        # queue in the kernel buffer, the client's timeout is the bound),
        # raise(msg) rejects the request with ERR_BUSY instead of serving
        try:
            fail_point("serve.dispatch")
        except FailPointError as e:
            resp = RpcHeader(seq=header.seq, code=header.code,
                             is_response=True, error=ERR_BUSY,
                             error_text=str(e))
            counters.rate("rpc.server.error_count").increment()
            if header.app_id:
                # tenant attribution (ISSUE 18): a rejected dispatch is
                # an error the TABLE saw, even though no replica handler
                # ran; no-op when the app_id is unmapped in this process
                from ..runtime.table_stats import TABLE_STATS

                TABLE_STATS.charge_app_error(header.app_id)
            try:
                _send_frame(sock, resp, b"", lock=wlock)
            except (ConnectionError, OSError):
                pass
            return
        if header.code in self.PRIORITY_CODES:
            with self._busy_lock:
                overflow = self._busy >= self.POOL_WORKERS
            if overflow:
                # liveness escape: replication/lifecycle must never queue
                # behind a pool full of work that is WAITING on them
                spawn_thread(self._serve_one, sock, wlock, header, body,
                             daemon=True)
                return
        with self._busy_lock:
            self._busy += 1
            depth = self._busy - self.POOL_WORKERS
        if depth > 0:
            self._depth_gauge.set(depth)
        try:
            self._pool.submit(self._serve_pooled, sock, wlock, header, body)
        except RuntimeError:   # server stopping: pool already shut down
            with self._busy_lock:
                self._busy -= 1

    def _serve_pooled(self, sock, wlock, header, body) -> None:
        try:
            self._serve_one(sock, wlock, header, body)
        finally:
            with self._busy_lock:
                self._busy -= 1
                depth = self._busy - self.POOL_WORKERS
            self._depth_gauge.set(max(0, depth))

    def _serve_one(self, sock, wlock, header: RpcHeader, body: bytes) -> None:
        resp = RpcHeader(seq=header.seq, code=header.code, is_response=True)
        out = b""
        t0 = time.perf_counter()
        # adopt the caller's trace context for the handler's whole stack
        # (replication, plog, engine spans all land in the same trace)
        scope = (REQUEST_TRACER.serve(
            TraceContext(header.trace_id, header.trace_sampled, remote=True),
            header.code) if header.trace_id else nullcontext())
        with scope:
            try:
                fn = self._handlers.get(header.code)
                if fn is None:
                    resp.error = ERR_HANDLER_NOT_FOUND
                    resp.error_text = header.code
                else:
                    call = fn
                    for mw in reversed(self._middlewares):
                        call = (lambda h, b, _mw=mw, _next=call:
                                _mw(h.code, h, b, _next))
                    out = call(header, body)
            except RpcError as e:
                resp.error, resp.error_text = e.err, e.text
            except Exception as e:  # handler bug -> error, not a dead connection
                resp.error, resp.error_text = ERR_INVALID_DATA, repr(e)
        counters.rate("rpc.server.qps").increment()
        counters.percentile("rpc.server.latency_us").set(
            int((time.perf_counter() - t0) * 1e6))
        if resp.error:
            counters.rate("rpc.server.error_count").increment()
        try:
            _send_frame(sock, resp, out, lock=wlock)
        except (ConnectionError, OSError):
            pass

    def _dispatch_batch(self, sock, wlock, code: str, frames) -> None:
        """Dispatch a hot-code batch the reader coalesced: ONE pool task,
        ONE handler call, ONE vectored reply write for the whole batch.
        Falls back to per-frame dispatch when the serve.native fail point
        triggers mid-wave, when any frame carries a trace context (spans
        must attach per request), or when middlewares are installed
        (tracer/profiler/fault toollets wrap per-frame handlers; the C
        wave binning still amortizes the read side) — the per-frame twin
        produces byte-identical responses, so the fallback is invisible
        on the wire."""
        batch_ok = True
        try:
            if fail_point("serve.native") is not None:
                batch_ok = False
        except FailPointError:
            batch_ok = False
        if (not batch_ok or self._middlewares
                or code not in self._batch_handlers
                or any(h.trace_id for h, _ in frames)):
            for header, body in frames:
                self._dispatch(sock, wlock, header, body)
            return
        # serve.dispatch fires once per batch — the batch IS one dispatch
        try:
            fail_point("serve.dispatch")
        except FailPointError as e:
            err = counters.rate("rpc.server.error_count")
            pairs = []
            for header, _ in frames:
                pairs.append((RpcHeader(
                    seq=header.seq, code=header.code, is_response=True,
                    error=ERR_BUSY, error_text=str(e)), b""))
                err.increment()
                if header.app_id:
                    from ..runtime.table_stats import TABLE_STATS

                    TABLE_STATS.charge_app_error(header.app_id)
            try:
                _send_frames(sock, pairs, lock=wlock)
            except (ConnectionError, OSError):
                pass
            return
        with self._busy_lock:
            self._busy += 1
            depth = self._busy - self.POOL_WORKERS
        if depth > 0:
            self._depth_gauge.set(depth)
        try:
            self._pool.submit(self._serve_batch_pooled, sock, wlock, code,
                              frames)
        except RuntimeError:   # server stopping: pool already shut down
            with self._busy_lock:
                self._busy -= 1

    def _serve_batch_pooled(self, sock, wlock, code, frames) -> None:
        try:
            self._serve_batch(sock, wlock, code, frames)
        finally:
            with self._busy_lock:
                self._busy -= 1
                depth = self._busy - self.POOL_WORKERS
            self._depth_gauge.set(max(0, depth))

    def _serve_batch(self, sock, wlock, code: str, frames) -> None:
        t0 = time.perf_counter()
        headers = [h for h, _ in frames]
        bodies = [b for _, b in frames]
        try:
            results = self._batch_handlers[code](headers, bodies)
        except Exception as e:  # handler bug -> errors, not a dead conn
            results = [e] * len(frames)
        pairs, n_err = [], 0
        for header, res in zip(headers, results):
            resp = RpcHeader(seq=header.seq, code=header.code,
                             is_response=True)
            out = b""
            if isinstance(res, RpcError):
                resp.error, resp.error_text = res.err, res.text
            elif isinstance(res, BaseException):
                resp.error, resp.error_text = ERR_INVALID_DATA, repr(res)
            else:
                out = res
            if resp.error:
                n_err += 1
            pairs.append((resp, out))
        # same counter cardinality as the per-frame path: one qps tick
        # and one latency sample PER FRAME (the batch shares its elapsed)
        elapsed = int((time.perf_counter() - t0) * 1e6)
        counters.rate("rpc.server.qps").increment(len(frames))
        lat = counters.percentile("rpc.server.latency_us")
        for _ in frames:
            lat.set(elapsed)
        if n_err:
            counters.rate("rpc.server.error_count").increment(n_err)
        try:
            _send_frames(sock, pairs, lock=wlock)
        except (ConnectionError, OSError):
            pass


class RpcConnection:
    """One full-duplex client connection with pipelined calls.

    shard: any hashable marking this connection as carrying exactly ONE
    partition's traffic (the ConnectionPool's shard key). Sharded
    connections set RpcHeader.sharded on every frame, which lets a
    partition-group serving node hand the whole connection to the owning
    group executor instead of relaying frame by frame."""

    def __init__(self, addr, connect_timeout: float = 5.0, shard=None):
        self.addr = tuple(addr)
        self.shard = shard
        self._sock = socket.create_connection(self.addr, timeout=connect_timeout)
        self._sock.settimeout(None)
        # rpc frames are small request/response pairs: Nagle + delayed ACK
        # turns concurrent small calls into ~40ms stalls
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending = {}   # seq -> (event, slot)
        self._seq = 0
        self._dead = None
        self._ev_pool = []   # recycled Events (success path only)
        self._reader = spawn_thread(self._read_loop, daemon=True)

    def _read_loop(self):
        try:
            reader = make_frame_reader(self._sock)
            while True:
                frames = reader.wave()
                # one lock round per WAVE: pipelined responses (call_many
                # peers, group-commit bursts) stop paying a lock handoff
                # per frame
                with self._plock:
                    ents = [(self._pending.pop(h.seq, None), h, b)
                            for h, b in frames]
                for ent, header, body in ents:
                    if ent:
                        ev, slot = ent
                        slot.append((header, body))
                        ev.set()
        except (ConnectionError, OSError) as e:
            self._dead = e
            with self._plock:
                pending = list(self._pending.values())
                self._pending.clear()
            for ev, slot in pending:
                slot.append(None)
                ev.set()

    def call(self, code: str, body: bytes, app_id: int = 0,
             partition_index: int = 0, partition_hash: int = 0,
             timeout: float = 10.0):
        """-> (RpcHeader, body bytes); raises RpcError on rpc-level failure."""
        if self._dead:
            raise RpcError(ERR_NETWORK_FAILURE, str(self._dead))
        with self._plock:
            self._seq += 1
            seq = self._seq
            # recycle Events from completed calls: one allocation
            # (Event + its Condition + lock) per RPC adds up at
            # thousands of calls/s
            ev = self._ev_pool.pop() if self._ev_pool else threading.Event()
            slot = []
            self._pending[seq] = (ev, slot)
        ctx = REQUEST_TRACER.current()
        header = RpcHeader(seq=seq, code=code, app_id=app_id,
                           partition_index=partition_index,
                           partition_hash=partition_hash,
                           trace_id=ctx.trace_id if ctx else 0,
                           trace_sampled=bool(ctx and ctx.sampled),
                           sharded=self.shard is not None)
        with REQUEST_TRACER.span(f"rpc.{code}", bytes=len(body)):
            try:
                _send_frame(self._sock, header, body, lock=self._wlock)
            except (ConnectionError, OSError) as e:
                with self._plock:
                    self._pending.pop(seq, None)
                raise RpcError(ERR_NETWORK_FAILURE, str(e))
            if not ev.wait(timeout):
                # do NOT recycle: the reader may still set this event later
                with self._plock:
                    self._pending.pop(seq, None)
                raise RpcError(ERR_TIMEOUT, f"{code} after {timeout}s")
        if not slot or slot[0] is None:
            raise RpcError(ERR_NETWORK_FAILURE, str(self._dead))
        rh, rbody = slot[0]
        # set + consumed: nobody else references this event again
        ev.clear()
        with self._plock:
            if len(self._ev_pool) < 64:
                self._ev_pool.append(ev)
        if rh.error != ERR_OK:
            raise RpcError(rh.error, rh.error_text)
        return rh, rbody

    def call_many(self, calls, timeout: float = 10.0):
        """Pipelined batch call: every request frame is buffered and
        leaves in ONE coalesced socket send (writev-style — the per-frame
        sendall of k small frames cost k syscalls and k wlock
        acquisitions), then the responses are collected in issue order.

        Each call is (code, body) or (code, body, app_id, pidx, phash) —
        the 5-tuple shape routes each frame like call() does, so the
        client's multi-partition fan-out (batch_get / scanner prefetch /
        duplicator shipping) pipelines through here too.

        -> [(RpcHeader, body)]; raises RpcError on the first failure. The
        replication catch-up path streams its backlog windows through
        here."""
        pend = self.call_many_send(calls)
        return self.call_many_collect(pend, calls, timeout)

    def call_many_send(self, calls):
        """Send half of call_many: one coalesced write, -> pending token.
        Lets a caller overlap waves across SEVERAL connections (fan-out
        sends first, then collects), so k partitions' worth of server work
        runs concurrently instead of lockstep."""
        if not calls:
            return []
        if self._dead:
            raise RpcError(ERR_NETWORK_FAILURE, str(self._dead))
        ctx = REQUEST_TRACER.current()
        sharded = self.shard is not None
        pend, enc, total = [], [], 0
        with self._plock:
            for call in calls:
                code, body = call[0], call[1]
                app_id, pidx, phash = (call[2], call[3], call[4]) \
                    if len(call) > 2 else (0, 0, 0)
                self._seq += 1
                seq = self._seq
                ev = self._ev_pool.pop() if self._ev_pool else threading.Event()
                slot = []
                self._pending[seq] = (ev, slot)
                pend.append((seq, ev, slot))
                header = RpcHeader(
                    seq=seq, code=code, app_id=app_id,
                    partition_index=pidx, partition_hash=phash,
                    trace_id=ctx.trace_id if ctx else 0,
                    trace_sampled=bool(ctx and ctx.sampled),
                    sharded=sharded)
                h = codec.encode(header)
                enc.append((h, body))
                total += 8 + len(h) + len(body)
        with REQUEST_TRACER.span("rpc.call_many", bytes=total,
                                 records=len(calls)):
            try:
                # vectored when native: the frame bodies go straight into
                # iovecs with the GIL released, instead of being copied
                # into one coalesced bytearray first
                _send_encoded_frames(self._sock, enc, lock=self._wlock)
            except (ConnectionError, OSError) as e:
                with self._plock:
                    for seq, _, _ in pend:
                        self._pending.pop(seq, None)
                raise RpcError(ERR_NETWORK_FAILURE, str(e))
        return pend

    def call_many_collect(self, pend, calls, timeout: float = 10.0):
        """Collect half of call_many: responses in issue order."""
        deadline = time.monotonic() + timeout
        out = []
        for i, (seq, ev, slot) in enumerate(pend):
            if not ev.wait(max(0.0, deadline - time.monotonic())):
                with self._plock:  # abandon everything still in flight
                    for s2, _, _ in pend[i:]:
                        self._pending.pop(s2, None)
                raise RpcError(ERR_TIMEOUT,
                               f"{calls[i][0]} after {timeout}s")
            if not slot or slot[0] is None:
                raise RpcError(ERR_NETWORK_FAILURE, str(self._dead))
            rh, rbody = slot[0]
            ev.clear()
            with self._plock:
                if len(self._ev_pool) < 64:
                    self._ev_pool.append(ev)
            if rh.error != ERR_OK:
                raise RpcError(rh.error, rh.error_text)
            out.append((rh, rbody))
        return out

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class ConnectionPool:
    """(addr, shard) -> RpcConnection cache with reconnect-on-failure.

    shard=None (default) is the classic one-connection-per-node behavior.
    A non-None shard keys a DEDICATED connection for one partition's
    traffic: the client's partition fan-out stops serializing behind a
    single socket, and a partition-group serving node can hand the whole
    connection to the owning group executor (RpcHeader.sharded)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns = {}

    def get(self, addr, shard=None) -> RpcConnection:
        addr = tuple(addr)
        key = (addr, shard)
        with self._lock:
            conn = self._conns.get(key)
        if conn is not None and not conn._dead:
            return conn
        # connect OUTSIDE the pool lock: a black-holed peer blocks
        # create_connection for its full timeout, and holding the pool-wide
        # lock through that would serialize every other caller (including
        # the replication write path) behind one dead host
        fresh = RpcConnection(addr, shard=shard)
        with self._lock:
            cur = self._conns.get(key)
            if cur is not None and not cur._dead and cur is not conn:
                fresh.close()  # lost the race to another connector
                return cur
            self._conns[key] = fresh
        return fresh

    def invalidate(self, addr) -> None:
        """Drop EVERY shard's connection to addr (a dead node is dead for
        all of its partitions)."""
        addr = tuple(addr)
        with self._lock:
            dead = [k for k in self._conns if k[0] == addr]
            conns = [self._conns.pop(k) for k in dead]
        for conn in conns:
            conn.close()

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
