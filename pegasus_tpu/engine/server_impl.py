"""The storage-engine server: read handlers, write dispatch, app-envs.

The pegasus_server_impl + pegasus_server_write pair
(src/server/pegasus_server_impl.{h,cpp}, pegasus_server_write.cpp) over our
LSM engine: every rrdb read RPC handled here (get :265, multi_get :343,
sortkey_count :764, ttl :843, get_scanner :904, scan :1151), committed
mutations dispatched per decree (on_batched_write_requests,
pegasus_server_write.cpp:39-110: consecutive put/remove batched into one
engine write; multi_put/incr/CAS/... routed to single handlers), dynamic
behavior driven by app-envs (update_app_envs :2406).
"""

import os
import struct
import threading
import time

from ..base import consts, key_schema
from ..base.utils import epoch_now
from ..base.value_schema import SCHEMAS
from ..runtime import lockrank
from ..runtime.perf_counters import counters
from ..runtime.tracing import REQUEST_TRACER
from ..rpc import messages as msg
from ..rpc.messages import FilterType, Status, match_filter
from .db import EngineOptions, LsmEngine
from .range_read_limiter import RangeReadLimiter
from .scan_context import ScanContext, ScanContextCache
from .write_service import WriteService

# write op codes live in rpc.task_codes; re-exported for existing callers
from ..rpc.task_codes import (BATCHABLE, RPC_BULK_LOAD_INGEST,  # noqa: F401
                              RPC_CHECK_AND_MUTATE, RPC_CHECK_AND_SET,
                              RPC_DUPLICATE, RPC_INCR, RPC_MULTI_PUT,
                              RPC_MULTI_REMOVE, RPC_PUT, RPC_REMOVE,
                              RPC_TRIGGER_AUDIT)

# short op names for the per-partition qps + latency counter pairs
# (app.<id>.<pidx>.<op>_qps / <op>_latency_us — write-path latency parity
# with the read handlers' get/multi_get percentiles)
_OP_NAMES = {RPC_PUT: "put", RPC_REMOVE: "remove",
             RPC_MULTI_PUT: "multi_put", RPC_MULTI_REMOVE: "multi_remove",
             RPC_INCR: "incr", RPC_CHECK_AND_SET: "check_and_set",
             RPC_CHECK_AND_MUTATE: "check_and_mutate",
             RPC_DUPLICATE: "duplicate", RPC_BULK_LOAD_INGEST: "bulk_load",
             RPC_TRIGGER_AUDIT: "trigger_audit"}


def _hk_hash32(hash_key: bytes):
    """32-bit hashkey hash for SST bloom probes — the same truncation the
    engine stores per record (db.get) and _bloom_build indexes. Returns
    None (= no pruning) for the EMPTY hashkey: key_hash falls back to
    hashing the sort key then (key_schema.py:60-72), so records under
    b'' carry per-sortkey hashes and no single probe covers them."""
    if not hash_key:
        return None
    return key_schema.key_hash(
        key_schema.generate_key(hash_key, b"")) & 0xFFFFFFFF


class _ReadSlot:
    __slots__ = ("key", "now", "event", "value", "err", "done")

    def __init__(self, key, now):
        self.key, self.now = key, now
        self.event = threading.Event()
        self.value = self.err = None
        self.done = False


class _RangeSlot:
    __slots__ = ("rng", "now", "h32", "event", "value", "err", "done")

    def __init__(self, rng, now, h32):
        self.rng, self.now, self.h32 = rng, now, h32
        self.event = threading.Event()
        self.value = self.err = None
        self.done = False


class _ReadCoalescer:
    """Groups CONCURRENT point reads into one engine.get_batch call — the
    read-path twin of the plog's leader/follower group commit: the first
    arriving thread claims the drain and serves queued slots (itself
    included) in device-batch-sized groups; threads that arrive mid-drain
    park on their slot. A solo get is a batch of one (no linger —
    lone-reader latency is unchanged, and db.get_batch routes a batch of
    one to the host walk anyway via the device_read_min_batch floor);
    under concurrency the queue forms the device batches by itself. A
    leader serves at most MAX_LEADER_ROUNDS batches past its own result
    (one client must never pay unbounded latency serving everyone else
    under saturation), then relinquishes; parked slots re-check on a
    bounded wait and self-promote, which also recovers leadership if a
    leader thread died non-locally. Only active when the engine's device
    reads are on — otherwise every get goes straight to engine.get."""

    MAX_LEADER_ROUNDS = 4

    def __init__(self, engine, max_batch: int = None):
        self.engine = engine
        self.max_batch = max_batch if max_batch is not None else \
            max(1, int(os.environ.get("PEGASUS_READ_BATCH_N", "64")))
        self._lock = lockrank.named_lock("read.coalescer")
        self._queue = []        #: guarded_by self._lock
        self._draining = False  #: guarded_by self._lock
        # hot-path counter resolved once (PR 6's rule: the registry lock
        # is per-lookup, and this fires on every point read)
        self._c_batch_size = counters.percentile("read.batch.size")

    def get(self, key: bytes, now: int):
        if not self.engine._device_reads_on():
            return self.engine.get(key, now=now)
        slot = _ReadSlot(key, now)
        self._join_many([slot])
        if slot.err is not None:
            raise slot.err
        return slot.value

    def get_many(self, keys, now: int):
        """Batch point reads from ONE caller thread (the native dispatch
        batch, ISSUE 20): the whole wave joins the coalescer as a slot
        GROUP — it merges with concurrent readers' slots into shared
        device batches, and parks a single connection thread instead of
        one thread per key. Raises the first slot error (the caller
        treats the wave as one read against one snapshot)."""
        if not keys:
            return []
        if not self.engine._device_reads_on():
            return self.engine.get_batch(keys, now=[now] * len(keys))
        slots = [_ReadSlot(k, now) for k in keys]
        self._join_many(slots)
        out = []
        for s in slots:
            if s.err is not None:
                raise s.err
            out.append(s.value)
        return out

    def _join_many(self, slots) -> None:
        """Queue every slot and drive the leader/follower drain until ALL
        are served — the group-commit loop shared with the range twin
        (_RangeCoalescer), which differs only in what _serve dispatches.
        Leadership rules are unchanged from the single-slot form: claim
        the drain when free, serve at most MAX_LEADER_ROUNDS batches past
        the round where every OWN slot is done, hand off on exit."""
        with self._lock:
            self._queue.extend(slots)
        while not all(s.done for s in slots):
            pending = next(s for s in slots if not s.done)
            with self._lock:
                lead = not self._draining and bool(self._queue)
                if lead:
                    self._draining = True
            if not lead:
                # parked; the bounded wait re-checks so a relinquished
                # (or dead) leader's leftover queue gets a new leader.
                # A poke without a result (leader handoff) clears the
                # event so the next park actually waits — slot.done, not
                # the event, is the loop's truth
                pending.event.wait(0.05)
                if not pending.done:
                    pending.event.clear()
                continue
            try:
                rounds = 0
                while True:
                    with self._lock:
                        batch = self._queue[: self.max_batch]
                        del self._queue[: self.max_batch]
                    if not batch:
                        break
                    self._serve(batch)
                    rounds += 1
                    if (rounds >= self.MAX_LEADER_ROUNDS
                            and all(s.done for s in slots)):
                        break
            finally:
                with self._lock:
                    self._draining = False
                    if self._queue:
                        # hand the drain off promptly: wake one parked
                        # slot so relinquished work doesn't wait out a
                        # 50ms poll tick
                        self._queue[0].event.set()

    def _serve(self, batch) -> None:
        self._c_batch_size.set(len(batch))
        try:
            vals = self.engine.get_batch([s.key for s in batch],
                                         now=[s.now for s in batch])
        except Exception as e:  # noqa: BLE001 - every waiter needs the outcome
            for s in batch:
                s.err, s.done = e, True
                s.event.set()
            return
        for s, v in zip(batch, vals):
            s.value, s.done = v, True
            s.event.set()


class _RangeCoalescer(_ReadCoalescer):
    """The _ReadCoalescer's range twin: concurrent bounded scans on the
    same partition (multi_get hash ranges, sortkey_count, filter-free
    scanner batches) group into ONE engine.scan_range_batch call — one
    device interval resolve per SST per GROUP instead of per request.
    Reverse ranges skip the queue entirely: the engine serves them
    host-side (and counts them in read.range.reverse_host_count) anyway,
    so there is nothing to share."""

    def __init__(self, engine, max_batch: int = None):
        super().__init__(engine, max_batch)
        self._lock = lockrank.named_lock("read.range_coalescer")
        self._c_batch_size = counters.percentile("read.range.batch.size")

    def scan_range(self, start: bytes, stop, now: int, hash32=None,
                   reverse: bool = False):
        """-> the merged-scan iterator scan(start, stop) would return
        (stop None = open end), device-resolved and group-coalesced when
        the engine's device reads are on."""
        if reverse or not self.engine._device_reads_on():
            return self.engine.scan_range_batch(
                [(start, stop)], now=now, reverse=reverse,
                hash32s=[hash32])[0]
        slot = _RangeSlot((start, stop), now, hash32)
        self._join_many([slot])
        if slot.err is not None:
            raise slot.err
        return slot.value

    def _serve(self, batch) -> None:
        self._c_batch_size.set(len(batch))
        try:
            its = self.engine.scan_range_batch(
                [s.rng for s in batch], now=[s.now for s in batch],
                hash32s=[s.h32 for s in batch])
        except Exception as e:  # noqa: BLE001 - every waiter needs the outcome
            for s in batch:
                s.err, s.done = e, True
                s.event.set()
            return
        for s, it in zip(batch, its):
            s.value, s.done = it, True
            s.event.set()


class PegasusServer:
    """One partition's storage server (a replication_app_base storage engine,
    registered by name like the reference's string-keyed factory,
    src/server/pegasus_server_impl.h:59-64)."""

    ENGINE_NAME = "pegasus-tpu"

    def __init__(self, path: str, app_id: int = 1, pidx: int = 0,
                 options: EngineOptions = None, server: str = "local",
                 app_envs: dict = None, cluster_id: int = 0):
        self.app_id = app_id
        self.pidx = pidx
        self.server = server
        opts = options or EngineOptions()
        opts.pidx = pidx
        self.engine = LsmEngine(path, opts)
        # cluster_id flows into every local write's value timetag — the
        # same provenance bits the duplicate apply path stores for its
        # ORIGIN cluster, so a row written locally on cluster 1 and its
        # duplicated copy on cluster 2 hold byte-identical values (the
        # cross-cluster digest compare depends on it; with the old
        # hardwired 0, every local row differed from its shipped twin by
        # exactly the cluster bits)
        self.write_service = WriteService(self.engine, app_id, pidx, server,
                                          cluster_id=cluster_id)
        self._schema = SCHEMAS[self.engine.data_version()]
        self._contexts = ScanContextCache()
        self._app_envs = {}
        self._default_ttl = 0
        self._slow_query_threshold_ms = 20  # reference default 20ms
        self._abnormal_get_size = 0                  # bytes; 0 = disabled
        self._abnormal_multi_get_size = 0            # bytes; 0 = disabled
        self._abnormal_multi_get_iterate_count = 0   # rows;  0 = disabled
        self._pfx = f"app.{app_id}.{pidx}."
        # hot read-path counters resolved ONCE: counters.rate(name) takes
        # the registry lock per call, and the per-RPC lookups convoyed
        # concurrent readers on it (part of BASELINE's 4T scan regression)
        self._c_get_qps = counters.rate(self._pfx + "get_qps")
        self._c_multi_get_qps = counters.rate(self._pfx + "multi_get_qps")
        self._c_scan_qps = counters.rate(self._pfx + "scan_qps")
        self._c_get_latency = counters.percentile(
            self._pfx + "get_latency_us")
        # device-served reads: concurrent on_get point reads coalesce into
        # engine.get_batch device batches, concurrent bounded scans into
        # engine.scan_range_batch ones (no-op passthroughs when the
        # engine's device reads are off)
        self._read_coalescer = _ReadCoalescer(self.engine)
        self._range_coalescer = _RangeCoalescer(self.engine)
        from .manual_compact_service import ManualCompactService

        self.manual_compact_service = ManualCompactService(self)
        from .capacity_unit_calculator import CapacityUnitCalculator
        from .hotkey_collector import HotkeyCollector

        self.read_hotkey = HotkeyCollector("read")
        self.write_hotkey = HotkeyCollector("write")
        from .throttling import DebtThrottle, ThrottlingController

        self.write_qps_throttler = ThrottlingController()
        self.write_size_throttler = ThrottlingController()
        self.read_qps_throttler = ThrottlingController()
        # compaction-debt admission control (ISSUE 10): graduated
        # backpressure keyed on the engine's L0 debt, charged alongside
        # the env throttles on every write
        self.debt_throttler = DebtThrottle(self.engine)
        self.cu_calculator = CapacityUnitCalculator(
            app_id, pidx, read_hotkey=self.read_hotkey,
            write_hotkey=self.write_hotkey)
        self.write_service.cu_calculator = self.cu_calculator
        # tenant accounting (ISSUE 18): wired by set_table_name once the
        # host learns which table this partition serves; None until then
        # (standalone engines without a table name stay unattributed)
        self.table_name = ""
        self.table_ledger = None
        if app_envs:
            self.update_app_envs(app_envs)

    # -------------------------------------------------------------- app envs

    def set_table_name(self, name: str) -> None:
        """Wire this partition to its tenant ledger (ISSUE 18): resolves
        the per-table ledger ONCE, registers the gpid -> table mapping
        (job/transport attribution), and hands the ledger to the debt
        throttle and the engine so delay-ms and device-read probes are
        charged at the source."""
        if not name or name == self.table_name:
            return
        from ..runtime.table_stats import TABLE_STATS

        self.table_name = name
        led = TABLE_STATS.register_gpid(self.app_id, self.pidx, name)
        self.table_ledger = led
        self.debt_throttler.ledger = led
        self.engine.table_ledger = led

    def update_app_envs(self, envs: dict) -> None:
        """Hot-apply per-table dynamic config (src/server/pegasus_server_impl.cpp:2406)."""
        self._app_envs.update(envs)
        ttl = envs.get(consts.TABLE_LEVEL_DEFAULT_TTL)
        if ttl is not None:
            self._default_ttl = max(0, int(ttl))
            self.engine.opts.default_ttl = self._default_ttl
        sq = envs.get(consts.ENV_SLOW_QUERY_THRESHOLD)
        if sq is not None:
            # validate ONCE here (the reference validates at env update);
            # a malformed value must never fail the read path
            try:
                self._slow_query_threshold_ms = max(0, int(sq))
            except (TypeError, ValueError):
                print(f"[app-envs] bad {consts.ENV_SLOW_QUERY_THRESHOLD}="
                      f"{sq!r} ignored", flush=True)
        # per-table write throttling (reference replica.write_throttling
        # env -> rDSN throttling_controller; by-qps and by-request-size)
        for env_key, ctl in ((consts.ENV_WRITE_THROTTLING,
                              self.write_qps_throttler),
                             (consts.ENV_WRITE_THROTTLING_BY_SIZE,
                              self.write_size_throttler),
                             (consts.ENV_READ_THROTTLING,
                              self.read_qps_throttler)):
            v = envs.get(env_key)
            if v is not None and v != ctl.env_value:
                if not ctl.parse_from_env(v):
                    print(f"[app-envs] bad {env_key}={v!r} ignored",
                          flush=True)
        # abnormal request/response SIZE tracing (reference
        # pegasus_server_impl.h:317-343 _abnormal_*_threshold gflags;
        # 0 = disabled): oversized reads are logged + counted even when fast
        for env_key, attr in (
                (consts.ENV_ABNORMAL_GET_SIZE, "_abnormal_get_size"),
                (consts.ENV_ABNORMAL_MULTI_GET_SIZE,
                 "_abnormal_multi_get_size"),
                (consts.ENV_ABNORMAL_MULTI_GET_ITERATE_COUNT,
                 "_abnormal_multi_get_iterate_count")):
            v = envs.get(env_key)
            if v is not None:
                try:
                    setattr(self, attr, max(0, int(v)))
                except (TypeError, ValueError):
                    print(f"[app-envs] bad {env_key}={v!r} ignored", flush=True)
        backend = envs.get(consts.COMPACTION_BACKEND_KEY)
        if backend in ("cpu", "tpu"):
            self.engine.opts.backend = backend
        if consts.USER_SPECIFIED_COMPACTION in envs:
            from .compaction_rules import parse_user_specified_compaction

            self.engine.opts.user_ops = tuple(parse_user_specified_compaction(
                envs[consts.USER_SPECIFIED_COMPACTION]))
        for env_key, opt in ((consts.CHECKPOINT_RESERVE_MIN_COUNT,
                              "checkpoint_reserve_min_count"),
                             (consts.CHECKPOINT_RESERVE_TIME_SECONDS,
                              "checkpoint_reserve_time_seconds")):
            v = envs.get(env_key)
            if v is not None:
                try:
                    setattr(self.engine.opts, opt, max(0, int(v)))
                except (TypeError, ValueError):
                    print(f"[app-envs] bad {env_key}={v!r} ignored", flush=True)
        comp = envs.get(consts.ROCKSDB_COMPRESSION_TYPE)
        if comp in ("none", "zlib"):
            self.engine.opts.compression = comp
        pv = envs.get(consts.REPLICA_PARTITION_VERSION)
        if pv is not None:
            # post-split ownership mask: compaction drops keys whose hash no
            # longer routes here (reference set_partition_version)
            self.engine.opts.partition_mask = max(0, int(pv))
        scenario = envs.get(consts.ENV_USAGE_SCENARIO_KEY)
        if scenario:
            self.set_usage_scenario(scenario)
        if any(k.startswith(consts.MANUAL_COMPACT_KEY_PREFIX) for k in envs):
            self.manual_compact_service.start_manual_compact_if_needed(
                self._app_envs)

    def set_usage_scenario(self, scenario: str) -> bool:
        """normal / prefer_write / bulk_load tuning profiles
        (src/server/pegasus_server_impl.cpp:2668-2738) mapped onto the full
        engine knob set the reference's SetOptions profiles reach:
        L0 trigger, memtable budget, output file sizing, and level budgets
        (bulk_load mirrors PrepareForBulkLoad: no auto compaction, huge
        write buffers, everything deferred to the post-load manual compact)."""
        o = self.engine.opts
        if scenario == consts.USAGE_SCENARIO_NORMAL:
            o.l0_compaction_trigger = 4
            o.memtable_bytes = 64 << 20
            o.target_file_size_bytes = 64 << 20
            o.level_base_bytes = 256 << 20
        elif scenario == consts.USAGE_SCENARIO_PREFER_WRITE:
            o.l0_compaction_trigger = 10
            o.memtable_bytes = 128 << 20
            o.target_file_size_bytes = 128 << 20
            o.level_base_bytes = 512 << 20
        elif scenario == consts.USAGE_SCENARIO_BULK_LOAD:
            o.l0_compaction_trigger = 1 << 30  # no auto compaction
            o.memtable_bytes = 256 << 20
            o.target_file_size_bytes = 256 << 20
            o.level_base_bytes = 1 << 62       # no cascades during the load
        else:
            return False
        self._app_envs[consts.ENV_USAGE_SCENARIO_KEY] = scenario
        return True

    @property
    def app_envs(self) -> dict:
        return dict(self._app_envs)

    def _make_limiter(self, count_only: bool = False) -> RangeReadLimiter:
        """Per-RPC iteration budget (src/server/range_read_limiter.h:29-100);
        thresholds come from app-envs with the reference's defaults."""
        envs = self._app_envs
        return RangeReadLimiter(
            max_iteration_count=int(envs.get(
                consts.ROCKSDB_ITERATION_THRESHOLD_COUNT, 1000)),
            max_iteration_size=0 if count_only else int(envs.get(
                consts.ROCKSDB_ITERATION_THRESHOLD_SIZE, 4 << 20)),
            max_duration_ms=int(envs.get(
                consts.ROCKSDB_ITERATION_THRESHOLD_TIME_MS, 5000)),
        )

    # ------------------------------------------------------------ write path

    def on_batched_write_window(self, window, now: int = None):
        """Apply a contiguous committed decree WINDOW — `window` is
        [(decree, timestamp_us, requests)] in decree order (the decree-
        pipelined replication path). Maximal stretches of batchable
        (put/remove) decrees collapse into ONE write_service call and ONE
        engine lock acquisition; everything else dispatches per decree
        exactly as on_batched_write_requests. -> {decree: response list}.
        Engine state advances stretch by stretch, so a mid-window failure
        leaves last_committed_decree at the last applied decree."""
        out = {}
        if not window:
            return out
        with REQUEST_TRACER.span("engine.apply", decree=window[-1][0],
                                 batch=sum(len(e[2]) for e in window)):
            i = 0
            while i < len(window):
                _, _, reqs = window[i]
                if reqs and all(c in BATCHABLE for c, _ in reqs):
                    j = i + 1
                    while j < len(window) and window[j][2] and \
                            all(c in BATCHABLE for c, _ in window[j][2]):
                        j += 1
                    out.update(self._apply_batchable_stretch(window[i:j]))
                    i = j
                else:
                    d, ts, reqs = window[i]
                    out[d] = self.on_batched_write_requests(d, ts, reqs,
                                                            now=now)
                    i += 1
        return out

    def _apply_batchable_stretch(self, entries):
        """One engine call for a stretch of batchable decrees; per-op
        qps/latency counters mirror the single-decree batch path (the
        stretch hits the engine as ONE write, so its elapsed time is every
        member's apply cost)."""
        t0 = time.perf_counter()
        resps = self.write_service.apply_batched_window(entries)
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        ops = set()
        n_ops = 0
        for _, _, reqs in entries:
            for code, _ in reqs:
                ops.add(_OP_NAMES[code])
                counters.rate(self._pfx + f"{_OP_NAMES[code]}_qps").increment()
                n_ops += 1
        for op in ops:
            counters.percentile(self._pfx + f"{op}_latency_us").set(elapsed_us)
        if self.table_ledger is not None:
            self.table_ledger.charge_write(elapsed_us, n_ops=n_ops)
        return resps

    def on_batched_write_requests(self, decree: int, timestamp_us: int, requests,
                                  now: int = None):
        """The replication->engine boundary
        (src/server/pegasus_server_write.cpp:39): `requests` is a list of
        (code, request) already committed at `decree`. Returns responses in
        order. Consecutive PUT/REMOVE coalesce into one engine write.
        `now` injects the read-modify-write clock for tests (the reference's
        PEGASUS_UNIT_TEST mock-time hook)."""
        if not requests:
            self.write_service.empty_put(decree)
            return []
        if len(requests) == 1 and requests[0][0] not in BATCHABLE:
            code, req = requests[0]
            return [self._dispatch_single(decree, timestamp_us, code, req, now)]
        # batch path: only batchable codes may be grouped (the reference
        # asserts non-batchable codes never arrive in a multi-request batch)
        t0 = time.perf_counter()
        responses = []
        ws = self.write_service
        with REQUEST_TRACER.span("engine.apply", decree=decree,
                                 batch=len(requests)):
            ws.batch_prepare()
            for code, req in requests:
                if code == RPC_PUT:
                    ws.batch_put(req, timestamp_us)
                    responses.append(ws._fill(msg.UpdateResponse(), decree))
                    counters.rate(self._pfx + "put_qps").increment()
                elif code == RPC_REMOVE:
                    ws.batch_remove(req.key)
                    responses.append(ws._fill(msg.UpdateResponse(), decree))
                    counters.rate(self._pfx + "remove_qps").increment()
                else:
                    ws.batch_abort()
                    raise ValueError(
                        f"non-batchable code {code} in batched request")
            ws.batch_commit(decree)
        # group-committed put/remove share the batch's engine latency:
        # they hit the engine as ONE write, so that is their apply cost
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        for op in {_OP_NAMES[code] for code, _ in requests}:
            counters.percentile(self._pfx + f"{op}_latency_us").set(elapsed_us)
        if self.table_ledger is not None:
            self.table_ledger.charge_write(elapsed_us, n_ops=len(requests))
        return responses

    def _dispatch_single(self, decree, timestamp_us, code, req, now=None):
        op = _OP_NAMES.get(code)
        if op is None:
            raise ValueError(f"unknown write code {code}")
        counters.rate(self._pfx + f"{op}_qps").increment()
        ws = self.write_service
        t0 = time.perf_counter()
        with REQUEST_TRACER.span("engine.apply", decree=decree, op=op):
            if code == RPC_PUT:
                resp = ws.put(decree, req, timestamp_us)
            elif code == RPC_REMOVE:
                resp = ws.remove(decree, req.key)
            elif code == RPC_MULTI_PUT:
                resp = ws.multi_put(decree, req, timestamp_us)
            elif code == RPC_MULTI_REMOVE:
                resp = ws.multi_remove(decree, req)
            elif code == RPC_INCR:
                resp = ws.incr(decree, req, now=now)
            elif code == RPC_CHECK_AND_SET:
                resp = ws.check_and_set(decree, req, now=now)
            elif code == RPC_CHECK_AND_MUTATE:
                resp = ws.check_and_mutate(decree, req, now=now)
            elif code == RPC_DUPLICATE:
                resp = ws.duplicate(decree, req, now=now)
            elif code == RPC_TRIGGER_AUDIT:
                resp = ws.trigger_audit(decree, req)
            else:
                resp = ws.ingestion_files(decree, req)
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        counters.percentile(self._pfx + f"{op}_latency_us").set(elapsed_us)
        if self.table_ledger is not None:
            self.table_ledger.charge_write(elapsed_us)
        return resp

    # ------------------------------------------------------------- read path

    def on_get(self, key: bytes, now: int = None) -> msg.ReadResponse:
        """src/server/pegasus_server_impl.cpp:265."""
        t0 = time.perf_counter()
        now = epoch_now() if now is None else now
        resp = msg.ReadResponse(app_id=self.app_id, partition_index=self.pidx,
                                server=self.server)
        raw = self._read_coalescer.get(key, now)
        if raw is None:
            resp.error = Status.NOT_FOUND
        else:
            resp.value = self._schema.extract_user_data(raw)
        try:
            hk, _ = key_schema.restore_key(key)
        except ValueError:
            hk = key  # malformed client key: still account, never raise
        self.cu_calculator.add_get_cu(hk, key, resp.value)
        size = len(key) + len(resp.value)
        self._check_abnormal_size("get", hk, size, self._abnormal_get_size)
        self._c_get_qps.increment()
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        self._c_get_latency.set(elapsed_us)
        if self.table_ledger is not None:
            self.table_ledger.charge_read(elapsed_us, size)
        self._check_slow_query("get", hk, elapsed_us)
        return resp

    def on_get_batch(self, keys, now: int = None) -> list:
        """on_get over a native dispatch batch (ISSUE 20): ONE coalescer
        slot-group join (or one engine.get_batch when device reads are
        off) serves the whole wave, then the per-key bookkeeping runs
        exactly as on_get runs it — same counters, same CU charges, same
        abnormal-size/slow-query tracing, byte-identical ReadResponses.
        Latency samples share the batch's elapsed time (the wave IS one
        storage operation)."""
        t0 = time.perf_counter()
        now = epoch_now() if now is None else now
        raws = self._read_coalescer.get_many(keys, now)
        out = []
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        for key, raw in zip(keys, raws):
            resp = msg.ReadResponse(app_id=self.app_id,
                                    partition_index=self.pidx,
                                    server=self.server)
            if raw is None:
                resp.error = Status.NOT_FOUND
            else:
                resp.value = self._schema.extract_user_data(raw)
            try:
                hk, _ = key_schema.restore_key(key)
            except ValueError:
                hk = key  # malformed client key: still account, never raise
            self.cu_calculator.add_get_cu(hk, key, resp.value)
            size = len(key) + len(resp.value)
            self._check_abnormal_size("get", hk, size,
                                      self._abnormal_get_size)
            self._c_get_qps.increment()
            self._c_get_latency.set(elapsed_us)
            if self.table_ledger is not None:
                self.table_ledger.charge_read(elapsed_us, size)
            self._check_slow_query("get", hk, elapsed_us)
            out.append(resp)
        return out

    def _check_abnormal_size(self, op: str, hash_key: bytes, size: int,
                             size_thr: int, rows: int = 0,
                             rows_thr: int = 0) -> None:
        """Oversized-read tracing (reference _abnormal_*_threshold,
        pegasus_server_impl.h:317-343): a read can be fast AND abusive;
        size/row thresholds flag it independently of latency."""
        if (size_thr and size >= size_thr) or (rows_thr and rows >= rows_thr):
            from ..base.utils import c_escape_string

            counters.rate(self._pfx + "recent_abnormal_count").increment()
            print(f"[abnormal-size] {op} hash_key="
                  f"\"{c_escape_string(hash_key[:64])}\" size={size}B "
                  f"rows={rows} (thresholds {size_thr}B/{rows_thr})",
                  flush=True)

    def _check_slow_query(self, op: str, hash_key: bytes, elapsed_us: int):
        """Slow/abnormal query tracing (reference _slow_query_threshold_ns,
        pegasus_server_impl.cpp:318-332): log offenders, bump the counter."""
        threshold_ms = self._slow_query_threshold_ms
        if threshold_ms > 0 and elapsed_us >= threshold_ms * 1000:
            counters.rate(self._pfx + "recent_abnormal_count").increment()
            from ..base.utils import c_escape_string

            print(f"[slow-query] app={self.app_id}.{self.pidx} op={op} "
                  f"hash_key=\"{c_escape_string(hash_key)}\" "
                  f"time_used={elapsed_us}us", flush=True)

    def on_multi_get(self, req: msg.MultiGetRequest, now: int = None) -> msg.MultiGetResponse:
        """src/server/pegasus_server_impl.cpp:343: specified sort_keys, or a
        bounded+filtered range under the hash_key. reverse=True keeps the
        LAST max_kv_count/size items of the range and returns them in
        descending sort_key order (the reference iterates with Prev())."""
        now = epoch_now() if now is None else now
        t0 = time.perf_counter()
        resp = msg.MultiGetResponse(app_id=self.app_id, partition_index=self.pidx,
                                    server=self.server)
        self._c_multi_get_qps.increment()
        if req.sort_keys:
            size = 0
            # a specified-sort_keys multi_get IS a point-read batch: one
            # engine.get_batch over one snapshot (device-served when the
            # SSTs are resident, host-walked otherwise)
            raws = self.engine.get_batch(
                [key_schema.generate_key(req.hash_key, sk)
                 for sk in req.sort_keys], now=now)
            for sk, raw in zip(req.sort_keys, raws):
                if raw is not None:
                    data = b"" if req.no_value else self._schema.extract_user_data(raw)
                    resp.kvs.append(msg.KeyValue(sk, data))
                    size += len(sk) + len(data)
            self.cu_calculator.add_multi_get_cu(req.hash_key, resp.kvs)
            self._check_abnormal_size(
                "multi_get", req.hash_key, size, self._abnormal_multi_get_size,
                rows=len(req.sort_keys),
                rows_thr=self._abnormal_multi_get_iterate_count)
            elapsed_us = int((time.perf_counter() - t0) * 1e6)
            if self.table_ledger is not None:
                self.table_ledger.charge_read(elapsed_us, size)
            self._check_slow_query("multi_get", req.hash_key, elapsed_us)
            return resp

        start = key_schema.generate_key(req.hash_key, req.start_sortkey)
        if req.stop_sortkey:
            stop = key_schema.generate_key(req.hash_key, req.stop_sortkey)
        else:
            stop = key_schema.generate_next_bytes(req.hash_key)

        out, complete = [], True
        size = 0
        iterated = 0
        h32 = _hk_hash32(req.hash_key)
        # both directions resolve the same bounded range [start, scan_hi)
        # through the range coalescer — device-served interval resolve for
        # forward scans, host-walked (and counted as such) for reverse
        scan_hi = stop + b"\x00" if req.stop_inclusive else stop
        it = self._range_coalescer.scan_range(start, scan_hi, now,
                                              hash32=h32,
                                              reverse=req.reverse)
        # reverse iterates the engine descending (the reference's Prev()
        # from the stop key), so bounded reads return the range's TAIL and
        # the limiter budget is spent at the correct end. The limiter
        # starts AFTER scan_range: the device interval resolve (its cold
        # jit especially) is bounded by the read lane's own deadline and
        # must not eat the per-RPC iteration-time budget — the host twin
        # pays no such setup, and byte-identity includes the
        # complete/INCOMPLETE verdict
        limiter = self._make_limiter()
        for k, raw, _ in it:
            if req.reverse:
                if k == start and not req.start_inclusive:
                    break
            else:
                if k >= stop:
                    if req.stop_inclusive and k == stop:
                        pass  # still include the stop key itself
                    else:
                        break
                if not req.start_inclusive and k == start:
                    continue
            limiter.add_count()
            iterated += 1
            if not limiter.valid():
                complete = False
                break
            _, sk = key_schema.restore_key(k)
            if not match_filter(req.sort_key_filter_type, req.sort_key_filter_pattern, sk):
                continue
            data = b"" if req.no_value else self._schema.extract_user_data(raw)
            out.append(msg.KeyValue(sk, data))
            size += len(sk) + len(data)
            limiter.add_size(len(sk) + len(data))
            if (req.max_kv_count > 0 and len(out) > req.max_kv_count) or (
                req.max_kv_size > 0 and size > req.max_kv_size
            ):
                out.pop()
                complete = False
                break
        self.cu_calculator.add_multi_get_cu(req.hash_key, out)
        self._check_abnormal_size(
            "multi_get", req.hash_key, size, self._abnormal_multi_get_size,
            rows=iterated, rows_thr=self._abnormal_multi_get_iterate_count)
        elapsed_us = int((time.perf_counter() - t0) * 1e6)
        if self.table_ledger is not None:
            self.table_ledger.charge_read(elapsed_us, size)
        self._check_slow_query("multi_get", req.hash_key, elapsed_us)
        resp.kvs = out
        resp.error = Status.OK if complete else Status.INCOMPLETE
        return resp

    def on_sortkey_count(self, hash_key: bytes, now: int = None) -> msg.CountResponse:
        """src/server/pegasus_server_impl.cpp:764."""
        now = epoch_now() if now is None else now
        resp = msg.CountResponse(app_id=self.app_id, partition_index=self.pidx,
                                 server=self.server)
        start = key_schema.generate_key(hash_key, b"")
        stop = key_schema.generate_next_bytes(hash_key)
        # counts resolve from the device intervals minus the host-filtered
        # deletions: the merged iterator already applies newest-wins /
        # tombstone / TTL, so counting its rows IS the filtered count.
        # scan_range (the eager device resolve, jit included) runs before
        # the limiter starts — see on_multi_get
        it = self._range_coalescer.scan_range(start, stop, now,
                                              hash32=_hk_hash32(hash_key))
        limiter = self._make_limiter(count_only=True)
        count = 0
        for _ in it:
            limiter.add_count()
            if not limiter.valid():
                resp.error = Status.INCOMPLETE
                break
            count += 1
        resp.count = count
        self.cu_calculator.add_sortkey_count_cu(hash_key)
        self._c_scan_qps.increment()
        return resp

    def on_ttl(self, key: bytes, now: int = None) -> msg.TTLResponse:
        """src/server/pegasus_server_impl.cpp:843."""
        now = epoch_now() if now is None else now
        resp = msg.TTLResponse(app_id=self.app_id, partition_index=self.pidx,
                               server=self.server)
        raw = self.engine.get(key, now=now)
        if raw is None:
            resp.error = Status.NOT_FOUND
            return resp
        expire = self._schema.extract_expire_ts(raw)
        resp.ttl_seconds = (expire - now) if expire > 0 else -1
        try:
            self.cu_calculator.add_ttl_cu(key_schema.restore_key(key)[0], key)
        except ValueError:
            pass
        return resp

    # ------------------------------------------------------------- scans

    def on_get_scanner(self, req: msg.GetScannerRequest, now: int = None) -> msg.ScanResponse:
        """src/server/pegasus_server_impl.cpp:904."""
        now = epoch_now() if now is None else now
        resp = msg.ScanResponse(app_id=self.app_id, partition_index=self.pidx,
                                server=self.server)
        self._c_scan_qps.increment()

        start = req.start_key
        stop = req.stop_key if req.stop_key else None
        # hash-key prefix filter narrows the LOWER bound like the reference
        # (:961-978): keys encode [u16 hashkey_len][hash_key][sort_key], and
        # any hash_key with this prefix has len >= len(pattern), so its
        # encoded key sorts >= [len(pattern)][pattern] — a valid lower bound.
        # (No tight upper bound exists: longer hash_keys sort by the leading
        # length field, not contiguously after the pattern range.)
        if (req.hash_key_filter_type == FilterType.MATCH_PREFIX
                and req.hash_key_filter_pattern):
            pstart = key_schema.generate_key(req.hash_key_filter_pattern, b"")
            if pstart > start:
                start = pstart
        # single-hashkey scans (the client's hash_scan shape) carry the
        # hashkey hash down so the file walk can bloom-prune
        h32 = None
        try:
            hk_start, _ = key_schema.restore_key(start)
            if hk_start and stop is not None and (
                    stop == key_schema.generate_next_bytes(hk_start)
                    or key_schema.restore_key(stop)[0] == hk_start):
                h32 = _hk_hash32(hk_start)
        except (ValueError, IndexError, struct.error):
            pass
        # the filter-free fast path (no row can be rejected server-side)
        # routes through the range coalescer so the scanner's batches
        # resolve their SST intervals on device; filtered scans keep the
        # plain host iterator — their effective ranges are sparse and the
        # per-row filters dominate anyway
        if self._scan_filter_free(req):
            it = self._range_coalescer.scan_range(start, stop, now,
                                                  hash32=h32)
        else:
            it = self.engine.scan(start, stop, now=now, hash32=h32)
        return self._fill_scan_batch(resp, it, req, now)

    def _scan_row_passes(self, req, k: bytes) -> bool:
        """The per-row filter set of append_key_value_for_scan
        (pegasus_server_impl.cpp:2094-2166)."""
        if not req.start_inclusive and k == req.start_key:
            return False
        if req.stop_key and k == req.stop_key and not req.stop_inclusive:
            return False
        hk, sk = key_schema.restore_key(k)
        if not match_filter(req.hash_key_filter_type,
                            req.hash_key_filter_pattern, hk):
            return False
        if not match_filter(req.sort_key_filter_type,
                            req.sort_key_filter_pattern, sk):
            return False
        if req.validate_partition_hash and self.engine.opts.partition_mask > 0:
            if not key_schema.check_key_hash(k, self.pidx,
                                             self.engine.opts.partition_mask):
                return False
        return True

    def on_scan(self, req: msg.ScanRequest, now: int = None) -> msg.ScanResponse:
        """src/server/pegasus_server_impl.cpp:1151: resume a pinned session."""
        now = epoch_now() if now is None else now
        resp = msg.ScanResponse(app_id=self.app_id, partition_index=self.pidx,
                                server=self.server)
        ctx = self._contexts.fetch(req.context_id)
        if ctx is None:
            resp.error = Status.NOT_FOUND
            resp.context_id = consts.SCAN_CONTEXT_ID_NOT_EXIST
            return resp
        return self._fill_scan_batch(resp, ctx.iterator, ctx.request, now, ctx=ctx)

    def on_clear_scanner(self, context_id: int) -> None:
        self._contexts.remove(context_id)

    def _scan_filter_free(self, req) -> bool:
        """No per-row filter can reject anything for this request: skip
        _scan_row_passes entirely (it restore_key()s EVERY row — two
        allocations per row for the overwhelmingly common filterless
        scan, a measurable slice of BASELINE's scan-path CPU)."""
        # (no stop_key clause: the engine iterator's upper bound is already
        # exclusive, so the row-level stop_inclusive check never fires)
        return (req.hash_key_filter_type == FilterType.NO_FILTER
                and req.sort_key_filter_type == FilterType.NO_FILTER
                and req.start_inclusive
                and not (req.validate_partition_hash
                         and self.engine.opts.partition_mask > 0))

    def _fill_scan_batch(self, resp, iterator, req, now, ctx=None):
        """Pull RAW engine rows: every iterated row (filtered out or not)
        charges the per-RPC limiter, so sparse-filter scans cannot pin a
        read thread unboundedly (reference scan loop under
        range_read_limiter, pegasus_server_impl.cpp:1000-1150)."""
        t0 = time.perf_counter()
        batch = max(1, req.batch_size)
        limiter = self._make_limiter()
        n = 0
        nbytes = 0
        exhausted = True
        filter_free = self._scan_filter_free(req)
        for k, raw, expire in iterator:
            limiter.add_count()
            if not limiter.valid():
                exhausted = False  # partial batch; session continues
                break
            if not filter_free and not self._scan_row_passes(req, k):
                continue
            data = b"" if req.no_value else self._schema.extract_user_data(raw)
            kv = msg.KeyValue(k, data)
            if req.return_expire_ts:
                kv.expire_ts_seconds = expire
            limiter.add_size(len(k) + len(data))
            nbytes += len(k) + len(data)
            resp.kvs.append(kv)
            n += 1
            if n >= batch:
                exhausted = False
                break
        self.cu_calculator.add_scan_cu(resp.kvs)
        if self.table_ledger is not None:
            self.table_ledger.charge_scan(
                int((time.perf_counter() - t0) * 1e6), nbytes)
        if exhausted:
            resp.context_id = consts.SCAN_CONTEXT_ID_COMPLETED
        else:
            if ctx is None:
                ctx = ScanContext(iterator, req)
            resp.context_id = self._contexts.put(ctx)
        return resp

    # -------------------------------------------------------------- hotkeys

    def on_detect_hotkey(self, kind: str, action: str) -> str:
        """detect_hotkey RPC (reference pegasus_server_impl.cpp:2976)."""
        if kind not in ("read", "write"):
            return f"ERROR: bad hotkey type {kind!r} (read|write)"
        if action not in ("start", "stop", "query"):
            return f"ERROR: bad action {action!r} (start|stop|query)"
        collector = self.read_hotkey if kind == "read" else self.write_hotkey
        if action == "start":
            return collector.start()
        if action == "stop":
            return collector.stop()
        return collector.query()

    # ------------------------------------------------------------ lifecycle

    def manual_compact(self, bottommost: bool = True, now: int = None) -> dict:
        t0 = time.perf_counter()
        stats = self.engine.manual_compact(bottommost=bottommost, now=now)
        counters.percentile(self._pfx + "manual_compact_s").set(
            time.perf_counter() - t0)
        return stats

    @property
    def last_audit(self):
        """Most recent decree-anchored consistency digest this replica
        computed (trigger_audit apply), or None."""
        return self.write_service.last_audit

    def stats(self) -> dict:
        return self.engine.stats()

    def close(self):
        self.engine.close()
