"""User-specified compaction rules as vectorized batch predicates.

Mirror of compaction_filter_rule + compaction_operation
(src/server/compaction_filter_rule.h:47-151, compaction_operation.{h,cpp};
RFC rfcs/2021-05-27-user-specified-compaction.md): the
`user_specified_compaction` app-env carries JSON

    {"ops": [{"type": "COT_DELETE"|"COT_UPDATE_TTL",
              "params": <op json>,
              "rules": [{"type": "FRT_HASHKEY_PATTERN"|"FRT_SORTKEY_PATTERN"
                                |"FRT_TTL_RANGE",
                         "params": <rule json>}]}]}

The reference evaluates rules record-at-a-time inside the RocksDB
compaction filter callback. Here an operation compiles into vectorized
column masks over a whole KVBlock — prefix/postfix/anywhere matches run as
2D numpy window compares over the padded key matrix, TTL ranges as
elementwise compares on the expire column — so rule filtering rides the
same batch pipeline as the TTL/tombstone filters instead of a per-record
callback. Sequential first-match-wins across ops, matching the reference's
filter loop.
"""

import json

import numpy as np

SMT_ANYWHERE = "SMT_MATCH_ANYWHERE"
SMT_PREFIX = "SMT_MATCH_PREFIX"
SMT_POSTFIX = "SMT_MATCH_POSTFIX"

UTOT_FROM_NOW = "UTOT_FROM_NOW"
UTOT_FROM_CURRENT = "UTOT_FROM_CURRENT"
UTOT_TIMESTAMP = "UTOT_TIMESTAMP"


def _key_parts_matrix(block):
    """-> (hk_matrix uint8[n, max_hk], hk_len[n], sk_matrix, sk_len[n]):
    padded 2D views of every record's hash_key and sort_key."""
    n = block.n
    off = block.key_off
    arena = block.key_arena
    hk_len = ((arena[off].astype(np.int64) << 8) | arena[off + 1]).astype(np.int64)
    sk_len = block.key_len.astype(np.int64) - 2 - hk_len
    max_hk = int(hk_len.max()) if n else 0
    max_sk = int(sk_len.max()) if n else 0

    def gather(base_off, lens, width):
        if width == 0:
            return np.zeros((n, 0), np.uint8)
        pos = np.arange(width, dtype=np.int64)
        idx = base_off[:, None] + pos[None, :]
        valid = pos[None, :] < lens[:, None]
        return np.where(valid, arena[np.minimum(idx, len(arena) - 1)], 0)

    hk = gather(off + 2, hk_len, max_hk)
    sk = gather(off + 2 + hk_len, sk_len, max_sk)
    return hk, hk_len, sk, sk_len


def _pattern_mask(matrix, lens, pattern: bytes, match_type: str) -> np.ndarray:
    n = matrix.shape[0]
    plen = len(pattern)
    if plen == 0:
        return np.zeros(n, dtype=bool)
    if plen > matrix.shape[1]:
        return np.zeros(n, dtype=bool)
    pat = np.frombuffer(pattern, dtype=np.uint8)
    fits = lens >= plen
    if match_type == SMT_PREFIX:
        return fits & (matrix[:, :plen] == pat).all(axis=1)
    if match_type == SMT_POSTFIX:
        # gather the last plen bytes of each record
        starts = np.maximum(lens - plen, 0)
        idx = starts[:, None] + np.arange(plen)[None, :]
        idx = np.minimum(idx, matrix.shape[1] - 1)
        tail = np.take_along_axis(matrix, idx, axis=1)
        return fits & (tail == pat).all(axis=1)
    if match_type == SMT_ANYWHERE:
        out = np.zeros(n, dtype=bool)
        width = matrix.shape[1]
        for s in range(0, width - plen + 1):
            out |= (lens >= s + plen) & (matrix[:, s : s + plen] == pat).all(axis=1)
        return out
    raise ValueError(f"bad match type {match_type}")


class Rule:
    def match_mask(self, ctx) -> np.ndarray:
        raise NotImplementedError


class HashkeyPatternRule(Rule):
    def __init__(self, params: dict):
        self.pattern = params["pattern"].encode() if isinstance(
            params["pattern"], str) else params["pattern"]
        self.match_type = params["match_type"]

    def match_mask(self, ctx):
        hk, hk_len, _, _ = ctx["parts"]
        return _pattern_mask(hk, hk_len, self.pattern, self.match_type)


class SortkeyPatternRule(Rule):
    def __init__(self, params: dict):
        self.pattern = params["pattern"].encode() if isinstance(
            params["pattern"], str) else params["pattern"]
        self.match_type = params["match_type"]

    def match_mask(self, ctx):
        _, _, sk, sk_len = ctx["parts"]
        return _pattern_mask(sk, sk_len, self.pattern, self.match_type)


class TtlRangeRule(Rule):
    """compaction_filter_rule.cpp:74-90: start/stop of 0/0 matches no-TTL
    records; otherwise remaining TTL in [start_ttl, stop_ttl]."""

    def __init__(self, params: dict):
        self.start_ttl = int(params.get("start_ttl", 0))
        self.stop_ttl = int(params.get("stop_ttl", 0))

    def match_mask(self, ctx):
        expire = ctx["block"].expire_ts.astype(np.int64)
        now = ctx["now"]
        if self.start_ttl == 0 and self.stop_ttl == 0:
            return expire == 0
        in_range = ((self.start_ttl + now <= expire)
                    & (self.stop_ttl + now >= expire))
        return np.asarray(in_range)


class Operation:
    def __init__(self, rules):
        self.rules = rules

    def all_rules_match(self, ctx) -> np.ndarray:
        mask = np.ones(ctx["block"].n, dtype=bool)
        for r in self.rules:
            mask &= r.match_mask(ctx)
        return mask


class DeleteKeyOp(Operation):
    pass


class UpdateTtlOp(Operation):
    def __init__(self, rules, params: dict):
        super().__init__(rules)
        self.type = params["type"]
        self.value = int(params.get("value", 0))

    def new_expire(self, ctx, mask) -> np.ndarray:
        now = ctx["now"]
        expire = ctx["block"].expire_ts.astype(np.int64)
        if self.type == UTOT_FROM_NOW:
            ne = np.full(len(expire), now + self.value, np.int64)
        elif self.type == UTOT_FROM_CURRENT:
            ne = np.where(expire > 0, expire + self.value, 0)
            mask = mask & (expire > 0)  # FROM_CURRENT keeps no-ttl untouched
        elif self.type == UTOT_TIMESTAMP:
            # value is a unix timestamp; stored expire is 2016-epoch based
            from ..base.utils import epoch_begin

            ne = np.full(len(expire), self.value - epoch_begin, np.int64)
        else:
            raise ValueError(f"bad update_ttl type {self.type}")
        return np.where(mask, ne, expire).astype(np.uint32), mask


_RULE_TYPES = {
    "FRT_HASHKEY_PATTERN": HashkeyPatternRule,
    "FRT_SORTKEY_PATTERN": SortkeyPatternRule,
    "FRT_TTL_RANGE": TtlRangeRule,
}


def parse_user_specified_compaction(spec: str):
    """JSON env value -> list of Operations (invalid entries skipped, like
    create_compaction_operations logging + continuing)."""
    try:
        doc = json.loads(spec)
    except (ValueError, TypeError):
        return []
    ops = []
    for op in doc.get("ops", []):
        rules = []
        for r in op.get("rules", []):
            cls = _RULE_TYPES.get(r.get("type"))
            if cls is None:
                continue
            params = r.get("params", {})
            if isinstance(params, str):
                params = json.loads(params)
            try:
                rules.append(cls(params))
            except (KeyError, ValueError):
                continue
        if not rules:
            continue
        params = op.get("params", {})
        if isinstance(params, str):
            params = json.loads(params) if params else {}
        if op.get("type") == "COT_DELETE":
            ops.append(DeleteKeyOp(rules))
        elif op.get("type") == "COT_UPDATE_TTL":
            try:
                ops.append(UpdateTtlOp(rules, params))
            except (KeyError, ValueError):
                continue
    return ops


def apply_operations(block, ops, now: int):
    """-> (drop_mask bool[n], changed: bool). Applies sequential
    first-match-wins semantics: a record is handled by the FIRST op whose
    rules all match; update_ttl rewrites expire_ts (and the value header)
    in place."""
    n = block.n
    drop = np.zeros(n, dtype=bool)
    if not ops or n == 0:
        return drop, False
    ctx = {"block": block, "now": now, "parts": _key_parts_matrix(block)}
    # Deletion markers are never offered to the filter (RocksDB invokes
    # compaction filters on values only, never on tombstones).
    unhandled = ~np.asarray(block.deleted, dtype=bool)
    changed = False
    for op in ops:
        mask = op.all_rules_match(ctx) & unhandled
        if not mask.any():
            continue
        unhandled &= ~mask
        if isinstance(op, DeleteKeyOp):
            drop |= mask
        else:
            new_expire, eff = op.new_expire(ctx, mask)
            if eff.any():
                _rewrite_expire(block, new_expire, eff)
                changed = True
    return drop, changed


def _rewrite_expire(block, new_expire: np.ndarray, mask: np.ndarray) -> None:
    """In-place expire_ts rewrite in both the column and the value bytes
    (v0/v1: offset 0; self-describing v2: offset 1)."""
    idx = np.nonzero(mask)[0]
    block.expire_ts[idx] = new_expire[idx]
    # Records whose serialized value cannot hold the expire header (zero-
    # length tombstone/empty values) must not be written through: 4 bytes at
    # their offset land in the NEXT record's header (or off the arena end).
    idx = idx[block.val_len[idx] > 0]
    if len(idx) == 0:
        return
    off = block.val_off[idx]
    first = block.val_arena[off]
    is_v2 = (first & 0x80) != 0
    fits = block.val_len[idx] >= np.where(is_v2, 5, 4)
    idx, off, is_v2 = idx[fits], off[fits], is_v2[fits]
    off = off + np.where(is_v2, 1, 0)
    vals = new_expire[idx]
    for j, shift in enumerate((24, 16, 8, 0)):
        block.val_arena[off + j] = ((vals >> shift) & 0xFF).astype(np.uint8)
