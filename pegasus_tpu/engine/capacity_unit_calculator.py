"""Capacity-unit accounting: size-normalized read/write units per op.

Mirror of src/server/capacity_unit_calculator.{h,cpp}: every data op adds
ceil(bytes / {read,write}_cu_size) units to the replica's CU counters (the
billing/throttling surface), and feeds the hotkey collectors with the
op's hash_key so detection sees real traffic.
"""

from ..runtime.perf_counters import counters


class CapacityUnitCalculator:
    def __init__(self, app_id: int, pidx: int, read_cu_size: int = 4096,
                 write_cu_size: int = 4096, read_hotkey=None, write_hotkey=None):
        self.read_cu_size = read_cu_size
        self.write_cu_size = write_cu_size
        pfx = f"app.{app_id}.{pidx}."
        self._read_cu = counters.rate(pfx + "recent_read_cu")
        self._write_cu = counters.rate(pfx + "recent_write_cu")
        self.read_hotkey = read_hotkey
        self.write_hotkey = write_hotkey

    def _units(self, nbytes: int, unit: int) -> int:
        return max(1, -(-max(nbytes, 1) // unit))

    def add_read(self, hash_key: bytes, nbytes: int) -> None:
        self._read_cu.add(self._units(nbytes, self.read_cu_size))
        if self.read_hotkey is not None:
            self.read_hotkey.capture(hash_key)

    def add_write(self, hash_key: bytes, nbytes: int) -> None:
        self._write_cu.add(self._units(nbytes, self.write_cu_size))
        if self.write_hotkey is not None:
            self.write_hotkey.capture(hash_key)
