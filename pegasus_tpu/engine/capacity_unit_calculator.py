"""Capacity-unit accounting: per-op read/write units + byte counters.

Mirror of src/server/capacity_unit_calculator.{h,cpp}: each data op has
its own add_*_cu entry point that (a) adds ceil(bytes / {read,write}_
cu_size) units to the replica's CU counters (the billing/throttling
surface), (b) bumps a per-op bytes counter (get_bytes, multi_get_bytes,
scan_bytes, put_bytes, ...), and (c) feeds the hotkey collectors with the
reference's weight rules (capacity_unit_calculator.h:107-117): multi-ops
weigh by their kv count, scans don't capture, and read-modify-write ops
(incr / check_and_set / check_and_mutate) charge BOTH read and write CU
because they perform both a read and a write.
"""

from ..runtime.perf_counters import counters


class CapacityUnitCalculator:
    def __init__(self, app_id: int, pidx: int, read_cu_size: int = 4096,
                 write_cu_size: int = 4096, read_hotkey=None, write_hotkey=None):
        self.read_cu_size = read_cu_size
        self.write_cu_size = write_cu_size
        self._pfx = f"app.{app_id}.{pidx}."
        self._read_cu = counters.rate(self._pfx + "recent_read_cu")
        self._write_cu = counters.rate(self._pfx + "recent_write_cu")
        self.read_hotkey = read_hotkey
        self.write_hotkey = write_hotkey

    # ------------------------------------------------------------ internals

    def _units(self, nbytes: int, unit: int) -> int:
        return max(1, -(-max(nbytes, 1) // unit))

    def _charge_read(self, nbytes: int, hash_key=None, weight: int = 1):
        self._read_cu.add(self._units(nbytes, self.read_cu_size))
        if hash_key is not None and self.read_hotkey is not None:
            self.read_hotkey.capture(hash_key, weight=weight)

    def _charge_write(self, nbytes: int, hash_key=None, weight: int = 1):
        self._write_cu.add(self._units(nbytes, self.write_cu_size))
        if hash_key is not None and self.write_hotkey is not None:
            self.write_hotkey.capture(hash_key, weight=weight)

    def _bytes(self, op: str, nbytes: int):
        counters.rate(self._pfx + op + "_bytes").add(nbytes)

    # ------------------------------------------------------------ read ops

    def add_get_cu(self, hash_key: bytes, key: bytes, value: bytes) -> None:
        b = len(key) + len(value)
        self._bytes("get", b)
        self._charge_read(b, hash_key)

    def add_multi_get_cu(self, hash_key: bytes, kvs) -> None:
        b = sum(len(kv.key) + len(kv.value) for kv in kvs)
        self._bytes("multi_get", b)
        self._charge_read(b, hash_key, weight=max(1, len(kvs)))

    def add_scan_cu(self, kvs) -> None:
        # reference: scan charges read CU but captures no hotkey (:110)
        b = sum(len(kv.key) + len(kv.value) for kv in kvs)
        self._bytes("scan", b)
        self._charge_read(b)

    def add_sortkey_count_cu(self, hash_key: bytes) -> None:
        self._charge_read(1, hash_key)

    def add_ttl_cu(self, hash_key: bytes, key: bytes) -> None:
        self._charge_read(len(key), hash_key)

    # ----------------------------------------------------------- write ops

    def add_put_cu(self, hash_key: bytes, key: bytes, value: bytes) -> None:
        b = len(key) + len(value)
        self._bytes("put", b)
        self._charge_write(b, hash_key)

    def add_remove_cu(self, hash_key: bytes, key: bytes) -> None:
        self._charge_write(len(key), hash_key)

    def add_multi_put_cu(self, hash_key: bytes, kvs) -> None:
        b = len(hash_key) + sum(len(kv.key) + len(kv.value) for kv in kvs)
        self._bytes("multi_put", b)
        self._charge_write(b, hash_key, weight=max(1, len(kvs)))

    def add_multi_remove_cu(self, hash_key: bytes, sort_keys) -> None:
        b = len(hash_key) + sum(len(sk) for sk in sort_keys)
        self._charge_write(b, hash_key, weight=max(1, len(sort_keys)))

    # ------------------------------------------- read-modify-write ops

    def add_incr_cu(self, hash_key: bytes, key: bytes) -> None:
        # incr reads the old value then writes the new: both CU pools
        self._charge_read(len(key))
        self._charge_write(len(key), hash_key)

    def add_check_and_set_cu(self, hash_key: bytes, check_sort_key: bytes,
                             set_sort_key: bytes, value: bytes) -> None:
        b = len(hash_key) + len(check_sort_key) + len(set_sort_key) + len(value)
        self._bytes("check_and_set", b)
        self._charge_read(len(hash_key) + len(check_sort_key))
        self._charge_write(b, hash_key)

    def add_check_and_mutate_cu(self, hash_key: bytes, check_sort_key: bytes,
                                mutate_bytes: int, mutate_count: int) -> None:
        b = len(hash_key) + len(check_sort_key) + mutate_bytes
        self._bytes("check_and_mutate", b)
        self._charge_read(len(hash_key) + len(check_sort_key))
        self._charge_write(b, hash_key, weight=max(1, mutate_count))

