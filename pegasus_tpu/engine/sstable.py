"""SST ("sorted string table") file format — columnar, device-loadable.

Unlike RocksDB's row-oriented block format, an SST here is a serialized
KVBlock: byte arenas + fixed-width columns, so a compaction input loads with
a handful of large reads straight into numpy arrays and the fixed-width
columns stream to HBM with zero per-record host work. Layout:

    magic "PGTS1\\n" | u32 header_len | header json | sections (raw bytes)

The header carries section offsets/dtypes/shapes plus engine metadata
(min/max key, record count, level, data_version, smallest decree info).
"""

import json
import os
import struct

import numpy as np

from .block import KVBlock
from ..runtime.perf_counters import counters

MAGIC = b"PGTS1\n"

# zero-copy mmap loads (ISSUE 20): flatlines when PEGASUS_NATIVE=0
_C_SST_MMAP = counters.rate("native.sst_mmap_count")


class CorruptionError(ValueError):
    """Typed on-disk corruption: bad magic, truncated file, unparseable
    header, or a section whose crc32 no longer matches what write_sst
    recorded. Subclasses ValueError so pre-existing broad handlers (e.g.
    manifest orphan adoption) keep treating a rotten file as unusable
    rather than crashing, while new code can catch corruption by type.
    Raised by read_header/read_sst/verify_sst — never a raw struct.error
    or JSONDecodeError."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"{path}: {detail}")
        self.path = path
        self.detail = detail


_COLUMNS = [
    ("key_arena", np.uint8),
    ("key_off", np.int64),
    ("key_len", np.int32),
    ("val_arena", np.uint8),
    ("val_off", np.int64),
    ("val_len", np.int32),
    ("expire_ts", np.uint32),
    ("hash32", np.uint32),
    ("deleted", np.bool_),
]


_BLOOM_SALTS = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F, 0x165667B1)


def _bloom_build(hash32: np.ndarray) -> tuple:
    """Bloom filter over the per-record hashkey hash (the reference's
    hashkey prefix bloom, src/server/hashkey_transform.h:31-60: one probe
    set per hash_key, shared by all its sort_keys). ~10 bits/distinct-hash,
    k=5; returns (bits bytes, log2_m)."""
    uniq = np.unique(hash32)
    m = 64
    while m < len(uniq) * 10:
        m <<= 1
    log2m = m.bit_length() - 1
    bits = np.zeros(m // 8, dtype=np.uint8)
    h = uniq.astype(np.uint64)
    for salt in _BLOOM_SALTS:
        pos = ((h * np.uint64(salt)) & np.uint64(0xFFFFFFFF)) >> np.uint64(32 - log2m)
        np.bitwise_or.at(bits, (pos >> np.uint64(3)).astype(np.int64),
                         (np.uint8(1) << (pos & np.uint64(7)).astype(np.uint8)))
    return bits.tobytes(), log2m


def write_sst(path: str, block: KVBlock, meta: dict = None,
              compression: str = "none", bloom: tuple = None) -> dict:
    """Write atomically (tmp+rename). Returns the header dict.

    compression="zlib" deflates each section (the per-table rocksdb
    compression knob, reference value-compression options); readers
    auto-detect from the header, so tables can mix files.
    bloom=(hex, log2m) reuses a precomputed bloom for this exact block
    (deferred installs already built one in SSTable.from_block — the
    multi-hash O(n) pass must not run twice per file)."""
    import time as _time

    from ..runtime.fail_points import inject
    from ..runtime.perf_counters import counters
    from ..runtime.tracing import COMPACT_TRACER

    t0 = _time.perf_counter()
    nbytes = block.key_bytes_total + block.val_bytes_total
    with COMPACT_TRACER.span("sst_write", records=block.n, nbytes=nbytes):
        inject("engine.sst_write")
        header = _write_sst_impl(path, block, meta, compression, bloom)
    counters.rate("engine.sst_write_count").increment()
    counters.rate("engine.sst_write_bytes").increment(nbytes)
    counters.percentile("engine.sst_write_s").set(
        round(_time.perf_counter() - t0, 6))
    return header


def _write_sst_impl(path: str, block: KVBlock, meta: dict,
                    compression: str, bloom: tuple = None) -> dict:
    import zlib

    sections = {}
    payload = []
    offset = 0
    for name, dtype in _COLUMNS:
        arr = np.ascontiguousarray(getattr(block, name), dtype=dtype)
        raw = arr.tobytes()
        stored = zlib.compress(raw, 1) if compression == "zlib" else raw
        sections[name] = {"offset": offset, "nbytes": len(stored),
                          "raw_nbytes": len(raw),
                          "dtype": np.dtype(dtype).str,
                          "shape": list(arr.shape),
                          "compression": compression,
                          "crc32": zlib.crc32(stored) & 0xFFFFFFFF}
        payload.append(stored)
        offset += len(stored)
    if bloom is not None:
        bloom_hex, bloom_log2m = bloom
    else:
        bloom_hex, bloom_log2m = "", 0
        if block.n:
            bloom_bits, bloom_log2m = _bloom_build(block.hash32)
            bloom_hex = bloom_bits.hex()
    header = {
        "sections": sections,
        "meta": dict(meta or {}),
        "n": block.n,
        "min_key": block.key(0).hex() if block.n else None,
        "max_key": block.key(block.n - 1).hex() if block.n else None,
        "data_bytes": block.key_bytes_total + block.val_bytes_total,
        "bloom": bloom_hex,
        "bloom_log2m": bloom_log2m,
    }
    hdr = json.dumps(header).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(hdr)))
        f.write(hdr)
        for raw in payload:
            f.write(raw)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return header


def _read_header_open(f, path: str) -> dict:
    """Header parse over an open file; every failure mode is typed."""
    magic = f.read(len(MAGIC))
    if magic != MAGIC:
        raise CorruptionError(path, f"bad SST magic {magic!r}")
    raw_len = f.read(4)
    if len(raw_len) < 4:
        raise CorruptionError(path, "truncated before header length")
    (hlen,) = struct.unpack("<I", raw_len)
    raw_hdr = f.read(hlen)
    if len(raw_hdr) < hlen:
        raise CorruptionError(
            path, f"truncated header ({len(raw_hdr)}/{hlen} bytes)")
    try:
        return json.loads(raw_hdr)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CorruptionError(path, f"unparseable header: {e}") from e


def read_header(path: str) -> dict:
    with open(path, "rb") as f:
        return _read_header_open(f, path)


def _read_section(f, path: str, base: int, name: str, sec: dict) -> bytes:
    """One stored section, crc-checked when the header carries a crc32
    (legacy pre-checksum headers don't — they stay readable unchecked)."""
    import zlib

    f.seek(base + sec["offset"])
    stored = f.read(sec["nbytes"])
    if len(stored) < sec["nbytes"]:
        raise CorruptionError(
            path, f"section {name} truncated "
                  f"({len(stored)}/{sec['nbytes']} bytes)")
    want = sec.get("crc32")
    if want is not None and (zlib.crc32(stored) & 0xFFFFFFFF) != want:
        raise CorruptionError(
            path, f"section {name} crc32 mismatch "
                  f"(stored {want:#010x}, "
                  f"computed {zlib.crc32(stored) & 0xFFFFFFFF:#010x})")
    if sec.get("compression", "none") == "zlib":
        try:
            stored = zlib.decompress(stored)
        except zlib.error as e:
            raise CorruptionError(
                path, f"section {name} undecompressable: {e}") from e
    return stored


def read_sst(path: str) -> tuple:
    """-> (KVBlock, header dict). With PEGASUS_NATIVE on (the default)
    uncompressed sections are ZERO-COPY views over an mmap of the file
    (ISSUE 20); with the knob off, the classic read()+copy path."""
    from .. import native

    if native.native_on():
        return _read_sst_mmap(path)
    with open(path, "rb") as f:
        header = _read_header_open(f, path)
        base = f.tell()
        cols = {}
        for name, _ in _COLUMNS:
            try:
                sec = header["sections"][name]
            except (KeyError, TypeError) as e:
                raise CorruptionError(
                    path, f"header missing section {name}") from e
            raw = _read_section(f, path, base, name, sec)
            try:
                cols[name] = np.frombuffer(
                    raw, dtype=np.dtype(sec["dtype"])
                ).reshape(sec["shape"]).copy()
            except (ValueError, TypeError) as e:
                raise CorruptionError(
                    path, f"section {name} unmaterializable: {e}") from e
    return KVBlock(**cols), header


def _read_sst_mmap(path: str) -> tuple:
    """read_sst's zero-copy twin: ONE mmap of the whole file, each
    uncompressed section materialized as an np.frombuffer view over the
    mapping — no f.read() double copy, and page-cache pages are shared
    across processes opening the same SST.

    Lifetime: every view's .base chain pins the memoryview, which pins
    the mmap object, which holds the kernel mapping open — and a mapped
    inode's data stays valid after the path is UNLINKED (compaction
    removes its inputs while readers may still hold their blocks). So a
    block loaded here stays readable for exactly as long as any of its
    arrays is referenced, file deletion notwithstanding — the lifetime
    regression test in test_native_dataplane.py pins this. The views are
    read-only (ACCESS_READ), which is safe because SST-loaded blocks are
    never mutated in place: compaction's in-place rewrites
    (_rewrite_expire / _apply_default_ttl) only touch freshly gathered
    output blocks. zlib-compressed sections decompress into fresh bytes
    as before (nothing to alias).
    """
    import mmap
    import zlib

    with open(path, "rb") as f:
        header = _read_header_open(f, path)
        base = f.tell()
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except (ValueError, OSError) as e:  # empty / unmappable file
            raise CorruptionError(path, f"unmappable: {e}") from e
    _C_SST_MMAP.increment()
    mv = memoryview(mm)
    cols = {}
    for name, _ in _COLUMNS:
        try:
            sec = header["sections"][name]
        except (KeyError, TypeError) as e:
            raise CorruptionError(
                path, f"header missing section {name}") from e
        off, n = base + sec["offset"], sec["nbytes"]
        if off < base or n < 0 or off + n > len(mm):
            raise CorruptionError(
                path, f"section {name} truncated "
                      f"({max(0, len(mm) - off)}/{n} bytes)")
        stored = mv[off:off + n]
        want = sec.get("crc32")
        if want is not None and (zlib.crc32(stored) & 0xFFFFFFFF) != want:
            raise CorruptionError(
                path, f"section {name} crc32 mismatch "
                      f"(stored {want:#010x}, "
                      f"computed {zlib.crc32(stored) & 0xFFFFFFFF:#010x})")
        if sec.get("compression", "none") == "zlib":
            try:
                stored = zlib.decompress(stored)
            except zlib.error as e:
                raise CorruptionError(
                    path, f"section {name} undecompressable: {e}") from e
        try:
            cols[name] = np.frombuffer(
                stored, dtype=np.dtype(sec["dtype"])).reshape(sec["shape"])
        except (ValueError, TypeError) as e:
            raise CorruptionError(
                path, f"section {name} unmaterializable: {e}") from e
    return KVBlock(**cols), header


def verify_sst(path: str) -> int:
    """Full-file integrity pass (scrub + fsck): magic, header parse, and
    every section's length + crc32 — without materializing a KVBlock.
    Returns the byte count read; raises CorruptionError on any finding."""
    with open(path, "rb") as f:
        header = _read_header_open(f, path)
        base = f.tell()
        scanned = base
        sections = header.get("sections")
        if not isinstance(sections, dict):
            raise CorruptionError(path, "header missing sections")
        for name, _ in _COLUMNS:
            sec = sections.get(name)
            if not isinstance(sec, dict):
                raise CorruptionError(path, f"header missing section {name}")
            scanned += len(_read_section(f, path, base, name, sec))
    return scanned


class SSTable:
    """An open SST: header always resident, block lazily loaded.

    Point lookups binary-search the key arena; min/max keys let the level
    structure skip files without touching their data.
    """

    def __init__(self, path: str):
        self.path = path
        self.header = read_header(path)
        self._init_runtime_state()

    def _init_runtime_state(self):
        self._block = None
        self._device_run = None
        self._device_uncacheable = False
        self._values_uncacheable = False
        # deferred write-out (engine pipelined installs): False while the
        # file has not landed on disk yet — the manifest writer must not
        # reference it until it has
        self._on_disk = True
        # set when the engine released this file's device columns for
        # good (inputs consumed by a merge): a late async residency prime
        # must not re-pin HBM for a dead file
        self._device_retired = False
        # engine-side prime coordination: _prime_inflight keeps an async
        # prime and an inline caller from double-uploading one file;
        # _device_budgeted records whether _device_run's bytes were added
        # to the engine's HBM budget (a release only subtracts then)
        self._prime_inflight = False
        self._device_budgeted = False
        self._bloom = None
        if self.header.get("bloom"):
            self._bloom = np.frombuffer(
                bytes.fromhex(self.header["bloom"]), dtype=np.uint8)
        self._bloom_log2m = int(self.header.get("bloom_log2m", 0))

    @classmethod
    def from_block(cls, path: str, block: KVBlock,
                   meta: dict = None) -> "SSTable":
        """In-memory SSTable over a not-yet-written block, for the
        engine's deferred (pipelined) installs: the header is synthesized
        from the block so reads/blooms/level bookkeeping work immediately,
        while write_sst lands the file on a pool worker. _on_disk stays
        False until it does; `sections` is empty because the cached block
        makes the disk read path unreachable (and the real header is
        written by write_sst)."""
        self = cls.__new__(cls)
        self.path = path
        bloom_hex, bloom_log2m = "", 0
        if block.n:
            bloom_bits, bloom_log2m = _bloom_build(block.hash32)
            bloom_hex = bloom_bits.hex()
        self.header = {
            "sections": {},
            "meta": dict(meta or {}),
            "n": block.n,
            "min_key": block.key(0).hex() if block.n else None,
            "max_key": block.key(block.n - 1).hex() if block.n else None,
            "data_bytes": block.key_bytes_total + block.val_bytes_total,
            "bloom": bloom_hex,
            "bloom_log2m": bloom_log2m,
        }
        self._init_runtime_state()
        self._block = block
        self._on_disk = False
        return self

    @property
    def n(self) -> int:
        return self.header["n"]

    @property
    def data_bytes(self) -> int:
        db = self.header.get("data_bytes")
        if db is None:  # pre-data_bytes header: derive from the sections
            db = (self.header["sections"]["key_arena"]["nbytes"]
                  + self.header["sections"]["val_arena"]["nbytes"])
        return int(db)

    def maybe_contains_hash(self, h32) -> bool:
        """Hashkey bloom probe; False = definitely absent (no disk read)."""
        if self._bloom is None:
            return self.n > 0
        h = np.uint64(h32)
        for salt in _BLOOM_SALTS:
            pos = ((h * np.uint64(salt)) & np.uint64(0xFFFFFFFF)) \
                >> np.uint64(32 - self._bloom_log2m)
            if not (self._bloom[int(pos >> np.uint64(3))]
                    >> np.uint8(pos & np.uint64(7))) & 1:
                return False
        return True

    @property
    def min_key(self):
        mk = self.header["min_key"]
        return bytes.fromhex(mk) if mk else None

    @property
    def max_key(self):
        mk = self.header["max_key"]
        return bytes.fromhex(mk) if mk else None

    @property
    def meta(self) -> dict:
        return self.header["meta"]

    def block(self) -> KVBlock:
        if self._block is None:
            from ..runtime.perf_counters import counters

            counters.rate("engine.sst_block_load").increment()
            self._block, _ = read_sst(self.path)
        return self._block

    def maybe_contains(self, key: bytes) -> bool:
        return self.n > 0 and self.min_key <= key <= self.max_key

    def find(self, key: bytes) -> int:
        """Index of `key` or -1; binary search over the sorted key column."""
        if not self.maybe_contains(key):
            return -1
        b = self.block()
        lo, hi = 0, b.n - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            k = b.key(mid)
            if k < key:
                lo = mid + 1
            elif k > key:
                hi = mid - 1
            else:
                return mid
        return -1

    def lower_bound(self, key: bytes) -> int:
        """First index with block.key(i) >= key (n if none)."""
        return self.block().lower_bound(key)

    @property
    def device_index(self):
        """The HBM-resident read index for this file, or None when the
        file is not device-servable: the DeviceRun primed at flush/
        compaction time, carrying the fence-pointer index its prime built
        as a byproduct (ops/device_lookup.py). The engine's batched read
        path (db.get_batch) probes this instead of the host binary
        search; a retired run (consumed by a merge) stops serving."""
        dr = self._device_run
        if dr is None or self._device_retired or \
                getattr(dr, "fence", None) is None:
            return None
        return dr

    def device_run(self, prefix_u32: int, with_values: bool = False):
        """Lazily pack + upload this file's sort columns to the device and
        PIN them for its lifetime (the engine's HBM-resident run cache,
        SURVEY §5.7c): compactions this file joins read HBM instead of
        re-packing and re-crossing PCIe every time. Returns None when the
        run is uncacheable (keys beyond the prefix window need per-merge
        suffix ranks). with_values additionally pins uniform-layout value
        rows (value residency; see EngineOptions.device_values)."""
        needs_pack = self._device_run is None or (
            # upgrade a value-less cached run when values are now wanted
            # (e.g. primed earlier by a caller with the default flag) —
            # unless this file's values already proved unpackable
            # (non-uniform layout): retrying would re-upload the whole
            # run to HBM on every compaction it joins
            with_values and self._device_run.val2d is None
            and not self._values_uncacheable)
        if needs_pack and not self._device_uncacheable:
            from ..ops.compact import pack_run_device

            self._device_run = pack_run_device(self.block(), prefix_u32,
                                               with_values=with_values)
            if self._device_run is None:
                self._device_uncacheable = True
            elif with_values and self._device_run.val2d is None:
                self._values_uncacheable = True
        return self._device_run

    def release(self):
        self._block = None
        self._device_run = None
        self._device_uncacheable = False
        self._values_uncacheable = False
