"""Bulk load: external-file ingestion through the engine's device sort.

Mirror of the reference bulk-load framework's storage side (SURVEY.md §2.4
'Bulk load framework'; engine ingestion pegasus_write_service_impl.h:484 +
rocksdb_wrapper.cpp:185 IngestExternalFile): a provider directory holds
per-partition ingest sets; each replica ingests its partition's files.

TPU-first twist: the reference requires pre-sorted SSTs from an offline
Spark job; here ingest sets may be UNSORTED record files — the external
sort runs as the same device kernel as flush (ops.sort_block), making
bulk load the second big batched-kernel consumer (SURVEY §7 M6).

Ingest file format: either a native SST (engine/sstable.py, ingested
as-is after a sortedness check) or a "raw set" file:

    magic "PGRAW1\n" then framed records
    [u16 hk_len][hash_key][u32 sk_len][sort_key][u32 v_len][value][u32 ttl]

Provider layout (the bulk_load_provider_root):
    <root>/<app_name>/<partition_count>/<pidx>/*.sst|*.raw
    <root>/<app_name>/bulk_load_metadata (json: file list + sizes)
"""

import json
import os
import struct

import numpy as np

from ..base.key_schema import generate_key
from .block import KVBlock
from .sstable import MAGIC as SST_MAGIC, SSTable

RAW_MAGIC = b"PGRAW1\n"


def write_raw_set(path: str, records) -> int:
    """records: iterable of (hash_key, sort_key, value, ttl_seconds_abs).
    Returns record count. The offline-producer helper (the Spark job role)."""
    n = 0
    with open(path, "wb") as f:
        f.write(RAW_MAGIC)
        for hk, sk, value, ttl in records:
            f.write(struct.pack("<H", len(hk)))
            f.write(hk)
            f.write(struct.pack("<I", len(sk)))
            f.write(sk)
            f.write(struct.pack("<I", len(value)))
            f.write(value)
            f.write(struct.pack("<I", ttl))
            n += 1
    return n


def read_raw_set(path: str):
    """-> yields (hash_key, sort_key, value, expire_ts)."""
    with open(path, "rb") as f:
        if f.read(len(RAW_MAGIC)) != RAW_MAGIC:
            raise ValueError(f"{path}: bad raw-set magic")
        data = f.read()
    off = 0
    while off < len(data):
        (hl,) = struct.unpack_from("<H", data, off)
        off += 2
        hk = bytes(data[off:off + hl]); off += hl
        (sl,) = struct.unpack_from("<I", data, off); off += 4
        sk = bytes(data[off:off + sl]); off += sl
        (vl,) = struct.unpack_from("<I", data, off); off += 4
        v = bytes(data[off:off + vl]); off += vl
        (ttl,) = struct.unpack_from("<I", data, off); off += 4
        yield hk, sk, v, ttl


def load_ingest_file(path: str, schema) -> KVBlock:
    """One ingest file -> a KVBlock (values encoded with the table schema)."""
    with open(path, "rb") as f:
        magic = f.read(len(SST_MAGIC))
    if magic == SST_MAGIC:
        return SSTable(path).block()
    rows = []
    for hk, sk, v, ttl in read_raw_set(path):
        rows.append((generate_key(hk, sk), schema.generate_value(ttl, 0, v),
                     ttl, False))
    return KVBlock.from_records(rows)


def metadata_path(provider_root: str, app_name: str) -> str:
    return os.path.join(provider_root, app_name, "bulk_load_metadata")


def write_metadata(provider_root: str, app_name: str, partition_count: int) -> dict:
    """Scan the provider tree and write the metadata file the meta server
    validates before starting a load (reference bulk_load_metadata)."""
    app_root = os.path.join(provider_root, app_name, str(partition_count))
    meta = {"app_name": app_name, "partition_count": partition_count,
            "partitions": {}}
    for pidx in range(partition_count):
        pdir = os.path.join(app_root, str(pidx))
        files = []
        if os.path.isdir(pdir):
            for name in sorted(os.listdir(pdir)):
                if name.startswith("."):
                    continue  # tool state (learn-ship sidecars), not data
                p = os.path.join(pdir, name)
                files.append({"name": name, "size": os.path.getsize(p)})
        meta["partitions"][str(pidx)] = files
    with open(metadata_path(provider_root, app_name), "w") as f:
        json.dump(meta, f)
    return meta


def ingest_partition(engine, provider_root: str, app_name: str,
                     partition_count: int, pidx: int, schema,
                     verify_hash: bool = True) -> dict:
    """Replica-side ingestion (the ingestion_files write): load every file
    of this partition's ingest set, device-sort, drop rows that don't hash
    here, and install as L0 runs. Returns stats."""
    from ..ops.compact import CompactOptions, compact_blocks

    pdir = os.path.join(provider_root, app_name, str(partition_count), str(pidx))
    if not os.path.isdir(pdir):
        return {"files": 0, "records": 0}
    blocks = []
    for name in sorted(os.listdir(pdir)):
        if name.startswith("."):
            continue  # tool state (learn-ship sidecars), not data
        blocks.append(load_ingest_file(os.path.join(pdir, name), schema))
    if not blocks:
        return {"files": 0, "records": 0}
    opts = CompactOptions(
        backend=engine.opts.backend, prefix_u32=engine.opts.prefix_u32,
        filter=verify_hash,
        pidx=pidx, partition_mask=(partition_count - 1) if verify_hash else 0,
        bottommost=False, runs_sorted=False, now=0,
    )
    merged = compact_blocks(blocks, opts).block
    engine.install_ingested_block(merged)
    return {"files": len(blocks), "records": int(merged.n)}
