"""Server-pinned scan sessions (src/server/pegasus_scan_context.h:35-140).

A get_scanner/scan sequence holds state on the server between RPCs. Context
ids carry random high bits so a stale id from before a restart/failover
misses instead of resuming someone else's iterator (reference :100-110).
One session keeps ONE id for its whole life (the reference's fetch/put dance
re-inserts under the same id, :86-140); eviction is LRU, O(1) per op.

Evicted/cleared sessions get their iterator CLOSED, not just dropped: the
live generator pins the engine snapshot it was opened over (memtable
copies, SST handles), and the range-read iterators additionally flush
their row accounting from a ``finally`` — waiting for GC to fire those
would hold the snapshot for an unbounded time and undercount
``read.range.rows`` until collection.
"""

import random
import threading
from collections import OrderedDict


class ScanContext:
    def __init__(self, iterator, request):
        self.iterator = iterator      # the live generator over the engine
        self.request = request        # the originating GetScannerRequest
        self.id = None                # assigned by the cache at first put
        self.lock = threading.Lock()  # one scan RPC at a time per context


def _close_iterator(ctx: ScanContext) -> None:
    """Release the session's engine snapshot now (and fire the range
    iterators' accounting finallys). A parked session is never mid-pull
    (fetch removes it from the cache for the duration of a scan RPC),
    but a racing close is harmless — swallow it."""
    close = getattr(ctx.iterator, "close", None)
    if close is None:
        return
    try:
        close()
    except Exception:  # noqa: BLE001 — best-effort release
        pass


class ScanContextCache:
    def __init__(self, max_contexts: int = 1000):
        self._lock = threading.Lock()
        self._contexts = OrderedDict()  # cid -> ScanContext, LRU order
        self._max = max_contexts
        self._high_bits = random.getrandbits(16) << 32
        self._next = 0

    def put(self, ctx: ScanContext) -> int:
        """Insert (or re-insert after a fetch) keeping the session's id."""
        evicted = []
        with self._lock:
            if ctx.id is None:
                ctx.id = self._high_bits | self._next
                self._next += 1
            self._contexts[ctx.id] = ctx
            self._contexts.move_to_end(ctx.id)
            while len(self._contexts) > self._max:
                evicted.append(self._contexts.popitem(last=False)[1])
        for old in evicted:   # close outside the lock: may run finallys
            _close_iterator(old)
        return ctx.id

    def fetch(self, cid: int):
        """Remove and return (re-inserted after use via put, same id)."""
        with self._lock:
            return self._contexts.pop(cid, None)

    def remove(self, cid: int):
        with self._lock:
            ctx = self._contexts.pop(cid, None)
        if ctx is not None:
            _close_iterator(ctx)

    def __len__(self):
        with self._lock:
            return len(self._contexts)
