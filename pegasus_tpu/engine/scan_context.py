"""Server-pinned scan sessions (src/server/pegasus_scan_context.h:35-140).

A get_scanner/scan sequence holds state on the server between RPCs. Context
ids carry random high bits so a stale id from before a restart/failover
misses instead of resuming someone else's iterator (reference :100-110).
One session keeps ONE id for its whole life (the reference's fetch/put dance
re-inserts under the same id, :86-140); eviction is LRU, O(1) per op.
"""

import random
import threading
from collections import OrderedDict


class ScanContext:
    def __init__(self, iterator, request):
        self.iterator = iterator      # the live generator over the engine
        self.request = request        # the originating GetScannerRequest
        self.id = None                # assigned by the cache at first put
        self.lock = threading.Lock()  # one scan RPC at a time per context


class ScanContextCache:
    def __init__(self, max_contexts: int = 1000):
        self._lock = threading.Lock()
        self._contexts = OrderedDict()  # cid -> ScanContext, LRU order
        self._max = max_contexts
        self._high_bits = random.getrandbits(16) << 32
        self._next = 0

    def put(self, ctx: ScanContext) -> int:
        """Insert (or re-insert after a fetch) keeping the session's id."""
        with self._lock:
            if ctx.id is None:
                ctx.id = self._high_bits | self._next
                self._next += 1
            self._contexts[ctx.id] = ctx
            self._contexts.move_to_end(ctx.id)
            while len(self._contexts) > self._max:
                self._contexts.popitem(last=False)
            return ctx.id

    def fetch(self, cid: int):
        """Remove and return (re-inserted after use via put, same id)."""
        with self._lock:
            return self._contexts.pop(cid, None)

    def remove(self, cid: int):
        with self._lock:
            self._contexts.pop(cid, None)

    def __len__(self):
        with self._lock:
            return len(self._contexts)
