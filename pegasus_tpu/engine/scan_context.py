"""Server-pinned scan sessions (src/server/pegasus_scan_context.h:35-140).

A get_scanner/scan sequence holds state on the server between RPCs. Context
ids carry random high bits so a stale id from before a restart/failover
misses instead of resuming someone else's iterator (reference :100-110).
"""

import random
import threading


class ScanContext:
    def __init__(self, iterator, request):
        self.iterator = iterator      # the live generator over the engine
        self.request = request        # the originating GetScannerRequest
        self.lock = threading.Lock()  # one scan RPC at a time per context


class ScanContextCache:
    def __init__(self, max_contexts: int = 1000):
        self._lock = threading.Lock()
        self._contexts = {}
        self._order = []
        self._max = max_contexts
        self._high_bits = random.getrandbits(16) << 32
        self._next = 0

    def put(self, ctx: ScanContext) -> int:
        with self._lock:
            cid = self._high_bits | self._next
            self._next += 1
            self._contexts[cid] = ctx
            self._order.append(cid)
            while len(self._order) > self._max:
                old = self._order.pop(0)
                self._contexts.pop(old, None)
            return cid

    def fetch(self, cid: int):
        """Remove and return (re-inserted after use, like the reference's
        fetch/put dance that keeps eviction order fresh)."""
        with self._lock:
            ctx = self._contexts.pop(cid, None)
            if ctx is not None:
                self._order.remove(cid)
            return ctx

    def remove(self, cid: int):
        with self._lock:
            if self._contexts.pop(cid, None) is not None:
                self._order.remove(cid)

    def __len__(self):
        with self._lock:
            return len(self._contexts)
