"""Write service: one function per mutation type over the engine.

Mirror of pegasus_write_service(_impl) (src/server/pegasus_write_service.{h,cpp},
_impl.h): typed mutations arrive post-commit from replication with a decree;
each either builds a WriteBatch (batched put/remove path) or performs its
read-modify-write atomically (incr :179, check_and_set :261,
check_and_mutate :358) — safe because PacificA serializes writes per
partition. Every committed decree lands in the engine meta store even for
rejected mutations (empty_put), preserving the last_flushed_decree invariant.
"""

from ..base import key_schema
from ..base.utils import epoch_now
from ..base.value_schema import SCHEMAS, check_if_ts_expired, generate_timetag
from ..runtime.tracing import REQUEST_TRACER
from ..rpc import messages as msg, task_codes
from ..rpc.messages import CasCheckType, MutateOperation, Status
from .db import LsmEngine, WriteBatch

# inner request type per duplicable task code (duplicate_request.raw_message)
_DUP_INNER = {
    task_codes.RPC_PUT: msg.UpdateRequest,
    task_codes.RPC_REMOVE: msg.KeyRequest,
    task_codes.RPC_MULTI_PUT: msg.MultiPutRequest,
    task_codes.RPC_MULTI_REMOVE: msg.MultiRemoveRequest,
    task_codes.RPC_INCR: msg.IncrRequest,
    task_codes.RPC_CHECK_AND_SET: msg.CheckAndSetRequest,
    task_codes.RPC_CHECK_AND_MUTATE: msg.CheckAndMutateRequest,
}


def buf2int64(data: bytes):
    """dsn::buf2int64: strict ascii int64 parse; None on failure."""
    try:
        text = data.decode("ascii")
    except UnicodeDecodeError:
        return None
    if not text or text.strip() != text:
        return None
    try:
        v = int(text, 10)
    except ValueError:
        return None
    if not (-(1 << 63) <= v < (1 << 63)):
        return None
    return v


class WriteService:
    def __init__(self, engine: LsmEngine, app_id: int = 1, pidx: int = 0,
                 server: str = "", cluster_id: int = 0):
        self.engine = engine
        self.app_id = app_id
        self.pidx = pidx
        self.server = server
        self.cluster_id = cluster_id
        self._schema = SCHEMAS[engine.data_version()]
        self._batch = None
        self.cu_calculator = None  # set by PegasusServer
        # most recent decree-anchored consistency digest (trigger_audit);
        # the replica stub's query-audit command + beacon states read it
        self.last_audit = None

    def _hk(self, key: bytes) -> bytes:
        return key_schema.restore_key(key)[0]

    def _engine_write(self, batch, decree: int) -> None:
        """All mutations reach the engine through here so the request
        trace separates engine-write time from the surrounding
        read-modify-write (incr/CAS read the old value first)."""
        with REQUEST_TRACER.span("engine.write", decree=decree):
            self.engine.write(batch, decree)

    # ----------------------------------------------------------- helpers

    def _fill(self, resp, decree):
        resp.app_id = self.app_id
        resp.partition_index = self.pidx
        if hasattr(resp, "decree"):
            resp.decree = decree
        resp.server = self.server
        return resp

    def _encode(self, user_data: bytes, expire_ts: int, timestamp_us: int = 0,
                deleted: bool = False) -> bytes:
        timetag = 0
        if self._schema.VERSION >= 1:
            timetag = generate_timetag(timestamp_us, self.cluster_id, deleted)
        return self._schema.generate_value(expire_ts, timetag, user_data)

    def _get_live(self, key: bytes, now: int):
        """-> (found, user_data, expire_ts); found=False when missing/expired/
        tombstoned (the db_get_context equivalent)."""
        raw = self.engine.get(key, now=now)
        if raw is None:
            return False, b"", 0
        return True, self._schema.extract_user_data(raw), self._schema.extract_expire_ts(raw)

    def empty_put(self, decree: int):
        """Advance last_flushed_decree with no data mutation
        (src/server/pegasus_write_service.cpp empty_put)."""
        self._engine_write(WriteBatch(), decree)
        return Status.OK

    # ------------------------------------------------------------ writes

    def put(self, decree: int, req: msg.UpdateRequest, timestamp_us: int = 0):
        resp = self._fill(msg.UpdateResponse(), decree)
        value = self._encode(req.value, req.expire_ts_seconds, timestamp_us)
        self._engine_write(WriteBatch().put(req.key, value, req.expire_ts_seconds), decree)
        if self.cu_calculator:
            self.cu_calculator.add_put_cu(self._hk(req.key), req.key, req.value)
        return resp

    def remove(self, decree: int, key: bytes):
        resp = self._fill(msg.UpdateResponse(), decree)
        self._engine_write(WriteBatch().delete(key), decree)
        if self.cu_calculator:
            self.cu_calculator.add_remove_cu(self._hk(key), key)
        return resp

    def multi_put(self, decree: int, req: msg.MultiPutRequest, timestamp_us: int = 0):
        resp = self._fill(msg.UpdateResponse(), decree)
        if not req.kvs:
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        batch = WriteBatch()
        total = 0
        for kv in req.kvs:
            key = key_schema.generate_key(req.hash_key, kv.key)
            value = self._encode(kv.value, req.expire_ts_seconds, timestamp_us)
            batch.put(key, value, req.expire_ts_seconds)
            total += len(key) + len(kv.value)
        self._engine_write(batch, decree)
        if self.cu_calculator:
            self.cu_calculator.add_multi_put_cu(req.hash_key, req.kvs)
        return resp

    def multi_remove(self, decree: int, req: msg.MultiRemoveRequest):
        resp = self._fill(msg.MultiRemoveResponse(), decree)
        if not req.sort_keys:
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        batch = WriteBatch()
        total = 0
        for sk in req.sort_keys:
            batch.delete(key_schema.generate_key(req.hash_key, sk))
            total += len(req.hash_key) + len(sk)
        self._engine_write(batch, decree)
        if self.cu_calculator:
            self.cu_calculator.add_multi_remove_cu(req.hash_key, req.sort_keys)
        resp.count = len(req.sort_keys)
        return resp

    def incr(self, decree: int, req: msg.IncrRequest, now: int = None):
        """src/server/pegasus_write_service_impl.h:179-258 semantics."""
        resp = self._fill(msg.IncrResponse(), decree)
        now = epoch_now() if now is None else now
        found, old_data, old_expire = self._get_live(req.key, now)
        if not found:
            new_value = req.increment
            new_expire = req.expire_ts_seconds if req.expire_ts_seconds > 0 else 0
        else:
            if len(old_data) == 0:
                new_value = req.increment
            else:
                old_int = buf2int64(old_data)
                if old_int is None:
                    resp.error = Status.INVALID_ARGUMENT
                    self.empty_put(decree)
                    return resp
                new_value = old_int + req.increment
                # int64 overflow rejection (impl.h:137-143); explicit range
                # check because python ints never wrap
                if not (-(1 << 63) <= new_value < (1 << 63)):
                    resp.error = Status.INVALID_ARGUMENT
                    resp.new_value = old_int
                    self.empty_put(decree)
                    return resp
            if req.expire_ts_seconds == 0:
                new_expire = old_expire
            elif req.expire_ts_seconds < 0:
                new_expire = 0
            else:
                new_expire = req.expire_ts_seconds
        value = self._encode(str(new_value).encode(), new_expire)
        self._engine_write(WriteBatch().put(req.key, value, new_expire), decree)
        if self.cu_calculator:  # RMW: read CU for the old value + write CU
            self.cu_calculator.add_incr_cu(self._hk(req.key), req.key)
        resp.new_value = new_value
        return resp

    def check_and_set(self, decree: int, req: msg.CheckAndSetRequest, now: int = None):
        """src/server/pegasus_write_service_impl.h:261-357 semantics."""
        resp = self._fill(msg.CheckAndSetResponse(), decree)
        now = epoch_now() if now is None else now
        if not self._check_type_supported(req.check_type):
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        check_key = key_schema.generate_key(req.hash_key, req.check_sort_key)
        exist, check_data, _ = self._get_live(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            resp.check_value_exist = exist
            if exist:
                resp.check_value = check_data
        passed, invalid = self._validate_check(req.check_type, req.check_operand,
                                               exist, check_data)
        if invalid:
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        if not passed:
            resp.error = Status.TRY_AGAIN
            self.empty_put(decree)
            return resp
        set_sk = req.set_sort_key if req.set_diff_sort_key else req.check_sort_key
        set_key = key_schema.generate_key(req.hash_key, set_sk)
        value = self._encode(req.set_value, req.set_expire_ts_seconds)
        self._engine_write(
            WriteBatch().put(set_key, value, req.set_expire_ts_seconds), decree
        )
        if self.cu_calculator:  # RMW: the check read charges read CU too
            self.cu_calculator.add_check_and_set_cu(
                req.hash_key, req.check_sort_key, set_sk, req.set_value)
        return resp

    def check_and_mutate(self, decree: int, req: msg.CheckAndMutateRequest, now: int = None):
        """src/server/pegasus_write_service_impl.h:358-483 semantics."""
        resp = self._fill(msg.CheckAndMutateResponse(), decree)
        now = epoch_now() if now is None else now
        if not req.mutate_list:
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        if not self._check_type_supported(req.check_type):
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        check_key = key_schema.generate_key(req.hash_key, req.check_sort_key)
        exist, check_data, _ = self._get_live(check_key, now)
        if req.return_check_value:
            resp.check_value_returned = True
            resp.check_value_exist = exist
            if exist:
                resp.check_value = check_data
        passed, invalid = self._validate_check(req.check_type, req.check_operand,
                                               exist, check_data)
        if invalid:
            resp.error = Status.INVALID_ARGUMENT
            self.empty_put(decree)
            return resp
        if not passed:
            resp.error = Status.TRY_AGAIN
            self.empty_put(decree)
            return resp
        batch = WriteBatch()
        total = 0
        for m in req.mutate_list:
            key = key_schema.generate_key(req.hash_key, m.sort_key)
            if m.operation == MutateOperation.PUT:
                value = self._encode(m.value, m.set_expire_ts_seconds)
                batch.put(key, value, m.set_expire_ts_seconds)
                total += len(key) + len(value)
            else:
                batch.delete(key)
                total += len(key)
        self._engine_write(batch, decree)
        if self.cu_calculator:  # RMW: the check read charges read CU too
            self.cu_calculator.add_check_and_mutate_cu(
                req.hash_key, req.check_sort_key, total, len(req.mutate_list))
        return resp

    def ingestion_files(self, decree: int, req: msg.BulkLoadIngestRequest):
        """Replicated bulk-load ingestion (the ingestion_files write,
        reference pegasus_write_service_impl.h:484): every replica of the
        partition applies this at the same decree, reading the SHARED
        provider set — so bulk-loaded data has a decree and survives
        failover like any other committed write."""
        from .bulk_load import ingest_partition

        resp = self._fill(msg.BulkLoadIngestResponse(), decree)
        try:
            stats = ingest_partition(self.engine, req.provider_root,
                                     req.app_name, req.partition_count,
                                     self.pidx, self._schema)
            resp.ingested_records = stats["records"]
        except (OSError, ValueError) as e:
            resp.error = Status.IO_ERROR
            print(f"[bulk_load] ingest failed: {e!r}")
        self.empty_put(decree)  # the decree itself still advances
        return resp

    def duplicate(self, decree: int, req: msg.DuplicateRequest, now: int = None):
        """Apply a mutation shipped from another cluster (the remote side of
        pegasus_mutation_duplicator). verify_timetag resolves write-write
        conflicts last-writer-wins with cluster-id tiebreak (value schema v1
        timetag, reference pegasus_write_service::duplicate +
        rocksdb_wrapper's verify_timetag get)."""
        from ..rpc import codec, task_codes

        resp = self._fill(msg.DuplicateResponse(), decree)
        inner_cls = _DUP_INNER.get(req.task_code)
        if inner_cls is None:
            resp.error = Status.INVALID_ARGUMENT
            resp.error_hint = f"non-duplicable task code {req.task_code}"
            self.empty_put(decree)
            return resp
        inner = codec.decode(inner_cls, req.raw_message)
        if req.verify_timetag and self._schema.VERSION >= 1 \
                and hasattr(inner, "key"):
            incoming = generate_timetag(req.timestamp, req.cluster_id,
                                        req.task_code == task_codes.RPC_REMOVE)
            raw = self.engine.get(inner.key, now=epoch_now() if now is None else now)
            if raw is not None and self._schema.extract_timetag(raw) > incoming:
                # local version is newer: drop the stale duplicate
                self.empty_put(decree)
                resp.error_hint = "ignored stale duplicate"
                return resp
        # apply with the ORIGIN timestamp so timetags carry provenance
        if req.task_code == task_codes.RPC_PUT:
            value = self._encode_with_origin(inner.value, inner.expire_ts_seconds,
                                             req.timestamp, req.cluster_id, False)
            self._engine_write(WriteBatch().put(inner.key, value,
                                                inner.expire_ts_seconds),
                               decree)
        elif req.task_code == task_codes.RPC_REMOVE:
            self._engine_write(WriteBatch().delete(inner.key), decree)
        elif req.task_code == task_codes.RPC_MULTI_PUT:
            batch = WriteBatch()
            for kv in inner.kvs:
                key = key_schema.generate_key(inner.hash_key, kv.key)
                value = self._encode_with_origin(kv.value, inner.expire_ts_seconds,
                                                 req.timestamp, req.cluster_id,
                                                 False)
                batch.put(key, value, inner.expire_ts_seconds)
            self._engine_write(batch, decree)
        elif req.task_code == task_codes.RPC_MULTI_REMOVE:
            batch = WriteBatch()
            for sk in inner.sort_keys:
                batch.delete(key_schema.generate_key(inner.hash_key, sk))
            self._engine_write(batch, decree)
        else:
            # read-modify-write codes re-run locally (incr/CAS duplicate as
            # their effect is deterministic given the shipped arguments)
            handler = {task_codes.RPC_INCR: self.incr,
                       task_codes.RPC_CHECK_AND_SET: self.check_and_set,
                       task_codes.RPC_CHECK_AND_MUTATE: self.check_and_mutate}
            handler[req.task_code](decree, inner, now=now)
        return resp

    def trigger_audit(self, decree: int, req: msg.TriggerAuditRequest):
        """Decree-anchored consistency digest (ISSUE 8): this mutation is
        a no-op for data — it only advances the decree — but because it
        rides the normal PacificA apply path, every replica executes it
        with exactly the decrees < `decree` applied and nothing after, so
        the engine digest each computes is anchored at the SAME point in
        the mutation stream. Layout independence comes from the digest
        itself (engine.state_digest: commutative per-record combine over
        the recency-merged logical contents).

        COST, deliberately: the digest fold is O(live records) and runs
        IN the apply path (under the replica lock), so the partition's
        writes stall for its duration — that stall IS the decree anchor
        (no later decree may apply before the snapshot is taken, and the
        fold-now-publish-later variant would have to pin SST files
        against compaction unlinks). Audits are explicit admin ops, not
        a background cadence; `audit.digest_us` records what each one
        cost.

        The `audit.digest` fail point corrupts THIS replica's digest when
        armed as return(<node>) or return(<node>@<app_id>.<pidx>) — node
        "" matches every replica — simulating silent divergence for the
        chaos suite without touching real data."""
        import time as _time

        from ..runtime.fail_points import fail_point
        from ..runtime.perf_counters import counters

        resp = self._fill(msg.TriggerAuditResponse(), decree)
        self.empty_put(decree)  # the decree itself advances like any write
        t0 = _time.perf_counter()
        try:
            # the auditor-chosen ownership mask rides the mutation: every
            # replica excludes split-stale rows against the SAME mask at
            # the same decree (the env-spread mask is async per replica)
            dig = self.engine.state_digest(now=req.now or None,
                                           pmask=req.pmask or None)
        except Exception as e:  # noqa: BLE001 - an audit must never wedge
            # the apply path; a digest failure reports as inconclusive
            resp.error = Status.IO_ERROR
            resp.server = f"{self.server} (digest failed: {e!r})"
            self.last_audit = {"audit_id": req.audit_id, "decree": decree,
                               "digest": "", "error": repr(e),
                               "ts": _time.time()}
            return resp
        digest = dig["digest"]
        fp = fail_point("audit.digest")
        if fp is not None and fp[0] == "return":
            node, _, gpid = fp[1].partition("@")
            if (not node or node == self.server) and \
                    (not gpid or gpid == f"{self.app_id}.{self.pidx}"):
                digest = "deadbeef" + digest[8:]
        counters.rate("audit.trigger_count").increment()
        counters.percentile("audit.digest_us").set(
            int((_time.perf_counter() - t0) * 1e6))
        # flight-recorder timeline: the audit landing on THIS replica at
        # THIS decree is what the incident correlator orders against the
        # breaker/fail-point events around it
        from ..runtime import events

        events.emit("audit.applied", gpid=f"{self.app_id}.{self.pidx}",
                    decree=decree, node=self.server)
        self.last_audit = {"audit_id": req.audit_id, "decree": decree,
                           "digest": digest, "records": dig["records"],
                           "now": dig["now"], "ts": _time.time()}
        resp.decree = decree
        resp.digest = digest
        resp.records = dig["records"]
        return resp

    def _encode_with_origin(self, user_data, expire_ts, timestamp_us,
                            cluster_id, deleted) -> bytes:
        timetag = 0
        if self._schema.VERSION >= 1:
            timetag = generate_timetag(timestamp_us, cluster_id, deleted)
        return self._schema.generate_value(expire_ts, timetag, user_data)

    # ------------------------------------------------- batched put/remove

    def apply_batched_window(self, entries):
        """Apply a contiguous committed decree window of BATCHABLE
        mutations — `entries` is [(decree, timestamp_us, [(code, req)])]
        — in ONE engine call (engine.write_batch: one lock acquisition
        for the whole window) instead of k. -> {decree: response list}."""
        from ..rpc.task_codes import RPC_PUT

        pairs, resps = [], {}
        for decree, timestamp_us, reqs in entries:
            wb = WriteBatch()
            rl = []
            for code, req in reqs:
                if code == RPC_PUT:
                    value = self._encode(req.value, req.expire_ts_seconds,
                                         timestamp_us)
                    wb.put(req.key, value, req.expire_ts_seconds)
                else:
                    wb.delete(req.key)
                rl.append(self._fill(msg.UpdateResponse(), decree))
            pairs.append((wb, decree))
            resps[decree] = rl
        with REQUEST_TRACER.span("engine.write", decree=entries[-1][0],
                                 records=sum(len(e[2]) for e in entries)):
            self.engine.write_batch(pairs)
        return resps

    def batch_prepare(self):
        self._batch = WriteBatch()

    def batch_put(self, req: msg.UpdateRequest, timestamp_us: int = 0):
        value = self._encode(req.value, req.expire_ts_seconds, timestamp_us)
        self._batch.put(req.key, value, req.expire_ts_seconds)

    def batch_remove(self, key: bytes):
        self._batch.delete(key)

    def batch_commit(self, decree: int):
        batch, self._batch = self._batch, None
        self._engine_write(batch, decree)
        return Status.OK

    def batch_abort(self):
        self._batch = None

    # ----------------------------------------------------------- checks

    @staticmethod
    def _check_type_supported(check_type: int) -> bool:
        return CasCheckType.NO_CHECK <= check_type <= CasCheckType.VALUE_INT_GREATER

    @staticmethod
    def _validate_check(check_type: int, operand: bytes, exist: bool, value: bytes):
        """-> (passed, invalid_argument); the 17-variant matrix of
        src/server/pegasus_write_service_impl.h:570-663."""
        ct = check_type
        if ct == CasCheckType.NO_CHECK:
            return True, False
        if ct == CasCheckType.VALUE_NOT_EXIST:
            return not exist, False
        if ct == CasCheckType.VALUE_NOT_EXIST_OR_EMPTY:
            return (not exist) or len(value) == 0, False
        if ct == CasCheckType.VALUE_EXIST:
            return exist, False
        if ct == CasCheckType.VALUE_NOT_EMPTY:
            return exist and len(value) != 0, False
        if ct in (CasCheckType.VALUE_MATCH_ANYWHERE, CasCheckType.VALUE_MATCH_PREFIX,
                  CasCheckType.VALUE_MATCH_POSTFIX):
            if not exist:
                return False, False
            if len(operand) == 0:
                return True, False
            if len(value) < len(operand):
                return False, False
            if ct == CasCheckType.VALUE_MATCH_ANYWHERE:
                return operand in value, False
            if ct == CasCheckType.VALUE_MATCH_PREFIX:
                return value.startswith(operand), False
            return value.endswith(operand), False
        if CasCheckType.VALUE_BYTES_LESS <= ct <= CasCheckType.VALUE_BYTES_GREATER:
            if not exist:
                return False, False
            if value < operand:
                return ct <= CasCheckType.VALUE_BYTES_LESS_OR_EQUAL, False
            if value == operand:
                return (CasCheckType.VALUE_BYTES_LESS_OR_EQUAL <= ct
                        <= CasCheckType.VALUE_BYTES_GREATER_OR_EQUAL), False
            return ct >= CasCheckType.VALUE_BYTES_GREATER_OR_EQUAL, False
        if CasCheckType.VALUE_INT_LESS <= ct <= CasCheckType.VALUE_INT_GREATER:
            if not exist:
                return False, False
            v = buf2int64(value)
            if v is None:
                return False, True
            o = buf2int64(operand)
            if o is None:
                return False, True
            if v < o:
                return ct <= CasCheckType.VALUE_INT_LESS_OR_EQUAL, False
            if v == o:
                return (CasCheckType.VALUE_INT_LESS_OR_EQUAL <= ct
                        <= CasCheckType.VALUE_INT_GREATER_OR_EQUAL), False
            return ct >= CasCheckType.VALUE_INT_GREATER_OR_EQUAL, False
        return False, False
