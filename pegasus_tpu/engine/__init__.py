from .block import KVBlock
from .memtable import Memtable
from .sstable import SSTable, read_sst, write_sst

__all__ = [
    "KVBlock",
    "EngineOptions",
    "LsmEngine",
    "WriteBatch",
    "Memtable",
    "SSTable",
    "read_sst",
    "write_sst",
]


def __getattr__(name):
    # db imports ops.compact, which imports engine.block via this package;
    # resolve the engine-level classes lazily to keep the import DAG acyclic
    if name in ("EngineOptions", "LsmEngine", "WriteBatch"):
        from . import db

        return getattr(db, name)
    raise AttributeError(name)
