"""Hotkey detection: per-replica READ/WRITE collectors with the
coarse->fine state machine.

Mirror of src/server/hotkey_collector.{h,cpp} (+hotkey_collector_state.h):
STOPPED -> COARSE (bucket histogram over hash of hash_key) -> FINE
(per-key queues within the winning bucket) -> FINISHED (hotkey published).
An outlier bucket/key is declared by the 68-95-99.7 rule: a bucket whose
count exceeds mean + 3*stddev of the others (hotkey_collector.cpp's
variance analysis). Driven by the `detect_hotkey` remote command from the
shell/collector (reference on_detect_hotkey, pegasus_server_impl.cpp:2976).
"""

import threading
import time
from collections import Counter as PyCounter

BUCKETS = 37  # prime bucket count, like the reference's FIND_BUCKET macro
MAX_DETECT_SECONDS = 150  # reference FLAGS_max_seconds_to_detect_hotkey

STOPPED = "STOPPED"
COARSE = "COARSE_DETECTING"
FINE = "FINE_DETECTING"
FINISHED = "FINISHED"


def _bucket(hash_key: bytes) -> int:
    h = 2166136261
    for b in hash_key:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % BUCKETS


class HotkeyCollector:
    """One collector per (replica, READ|WRITE) kind."""

    def __init__(self, kind: str, coarse_threshold: int = 100,
                 fine_threshold: int = 50,
                 max_seconds: float = MAX_DETECT_SECONDS):
        self.kind = kind
        self.state = STOPPED
        self.coarse_threshold = coarse_threshold
        self.fine_threshold = fine_threshold
        self.max_seconds = max_seconds
        self._deadline = 0.0
        self._lock = threading.Lock()
        self._buckets = [0] * BUCKETS
        self._hot_bucket = -1
        self._fine = PyCounter()
        self.result = None

    # ------------------------------------------------------------- control

    def start(self) -> str:
        with self._lock:
            self._buckets = [0] * BUCKETS
            self._fine.clear()
            self._hot_bucket = -1
            self.result = None
            self.state = COARSE
            # a detection that never converges self-terminates (reference
            # terminate_if_timeout, FLAGS_max_seconds_to_detect_hotkey)
            self._deadline = time.monotonic() + self.max_seconds
            return f"{self.kind} hotkey detection started (coarse)"

    def stop(self) -> str:
        with self._lock:
            self.state = STOPPED
            return f"{self.kind} hotkey detection stopped"

    def query(self) -> str:
        with self._lock:
            if self.state == FINISHED and self.result is not None:
                return (f"{self.kind} hotkey: {self.result!r}")
            if (self.state in (COARSE, FINE)
                    and time.monotonic() >= self._deadline):
                self.state = STOPPED
                return (f"{self.kind} detection state: {STOPPED} "
                        "(timed out without an outlier)")
            return f"{self.kind} detection state: {self.state}"

    # -------------------------------------------------------------- capture

    def capture(self, hash_key: bytes, weight: int = 1) -> None:
        if self.state == STOPPED or self.state == FINISHED:
            return
        if time.monotonic() >= self._deadline:
            with self._lock:
                if self.state in (COARSE, FINE):
                    self.state = STOPPED
            return
        with self._lock:
            if self.state == COARSE:
                b = _bucket(hash_key)
                self._buckets[b] += weight
                total = sum(self._buckets)
                if total >= self.coarse_threshold:
                    hot = self._outlier_index(self._buckets)
                    if hot >= 0:
                        self._hot_bucket = hot
                        self.state = FINE
                        self._fine.clear()
                    else:
                        self._buckets = [0] * BUCKETS  # analyse next window
            elif self.state == FINE:
                if _bucket(hash_key) != self._hot_bucket:
                    return
                self._fine[bytes(hash_key)] += weight
                if sum(self._fine.values()) >= self.fine_threshold:
                    counts = list(self._fine.values())
                    keys = list(self._fine.keys())
                    hot = self._outlier_index(counts)
                    if hot >= 0:
                        self.result = keys[hot]
                        self.state = FINISHED
                    else:
                        self._fine.clear()

    @staticmethod
    def _outlier_index(counts) -> int:
        """68-95-99.7 rule: index whose count > mean + 3*stddev of the REST
        (hotkey_collector.cpp variance analysis); -1 if none."""
        n = len(counts)
        if n < 2:
            return 0 if n == 1 and counts[0] > 0 else -1
        best = max(range(n), key=lambda i: counts[i])
        rest = [c for i, c in enumerate(counts) if i != best]
        mean = sum(rest) / len(rest)
        var = sum((c - mean) ** 2 for c in rest) / len(rest)
        threshold = mean + 3 * (var ** 0.5)
        return best if counts[best] > threshold and counts[best] > 0 else -1
