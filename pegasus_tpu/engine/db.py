"""The LSM engine: memtable + L0 runs + leveled SSTs, device-offloaded
flush/compaction.

Replaces the reference's RocksDB-behind-rocksdb_wrapper
(src/server/rocksdb_wrapper.{h,cpp}) with a from-scratch LSM designed around
KVBlocks: writes land in a dict memtable, flush sorts the block on the
configured backend, compaction feeds whole levels to ops.compact_blocks.
There is deliberately NO internal WAL: exactly like the reference (which
disables RocksDB's WAL), the replication mutation log is the WAL and replays
into the engine on recovery (SURVEY.md §3.2 note).

Durability/decree bookkeeping mirrors the reference invariants (SURVEY.md §7b):
  - every committed batch records its decree in the in-memory meta store
    (reference: LAST_FLUSHED_DECREE put into the meta CF within each
    WriteBatch, src/server/rocksdb_wrapper.cpp:143);
  - flush persists that decree into the manifest; `last_durable_decree` is
    what the manifest holds — the replica learns/replays from there.
"""

import bisect
import heapq
import json
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..base.key_schema import key_hash
from ..base.utils import epoch_now
from ..base.value_schema import check_if_ts_expired
from ..runtime.fail_points import fail_point
from ..ops.compact import CompactOptions, compact_blocks, sort_block
from .block import KVBlock
from .memtable import Memtable
from .sstable import SSTable, write_sst

MANIFEST = "MANIFEST"

# meta-store keys (reference: src/server/meta_store.cpp:29)
META_DATA_VERSION = "pegasus_data_version"
META_LAST_FLUSHED_DECREE = "pegasus_last_flushed_decree"
META_LAST_MANUAL_COMPACT_FINISH_TIME = "pegasus_last_manual_compact_finish_time"


@dataclass
class EngineOptions:
    memtable_bytes: int = 64 << 20
    l0_compaction_trigger: int = 4
    backend: str = "cpu"            # compaction_backend: "cpu" | "tpu"
    prefix_u32: int = 8
    data_version: int = 2
    pidx: int = 0
    partition_mask: int = 0         # >0 enables split stale-key GC in compaction
    default_ttl: int = 0            # table-level default_ttl app-env
    max_levels: int = 2             # L0 + one sorted level this round


@dataclass
class WriteBatch:
    """Atomic mutation set for one decree (one on_batched_write_requests)."""

    ops: list = field(default_factory=list)  # ("put", key, value, expire) | ("del", key)

    def put(self, key: bytes, value: bytes, expire_ts: int = 0):
        self.ops.append(("put", key, value, expire_ts))
        return self

    def delete(self, key: bytes):
        self.ops.append(("del", key, b"", 0))
        return self


class LsmEngine:
    def __init__(self, path: str, options: EngineOptions = None):
        self.path = path
        self.opts = options or EngineOptions()
        self._lock = threading.RLock()
        self._mem = Memtable()
        self._imm = []          # immutable memtables pending flush, newest first
        self._l0 = []           # list[SSTable], newest first
        self._levels = {}       # level(int>=1) -> list[SSTable] sorted by min_key
        self._meta = {}         # the meta-CF equivalent
        self._next_file = 1
        self._last_committed_decree = 0
        os.makedirs(path, exist_ok=True)
        self._load_manifest()

    # ------------------------------------------------------------------ meta

    @property
    def meta_store(self) -> dict:
        return self._meta

    def last_durable_decree(self) -> int:
        """Decree covered by on-disk SSTs (manifest's last_flushed_decree)."""
        return int(self._durable_meta.get(META_LAST_FLUSHED_DECREE, 0))

    def last_committed_decree(self) -> int:
        return self._last_committed_decree

    def data_version(self) -> int:
        return int(self._meta.get(META_DATA_VERSION, self.opts.data_version))

    # ----------------------------------------------------------------- write

    def write(self, batch: WriteBatch, decree: int) -> None:
        """Apply one committed batch; analogue of rocksdb_wrapper::write
        (src/server/rocksdb_wrapper.cpp:143): data ops + decree meta update,
        atomically under the engine lock."""
        if fail_point("db_write"):
            raise IOError("injected db_write failure")
        with self._lock:
            for op in batch.ops:
                kind, key, value, expire = op
                if kind == "put":
                    if fail_point("db_write_batch_put"):
                        raise IOError("injected db_write_batch_put failure")
                    self._mem.put(key, value, expire)
                elif kind == "del":
                    if fail_point("db_write_batch_delete"):
                        raise IOError("injected db_write_batch_delete failure")
                    self._mem.delete(key)
                else:
                    raise ValueError(f"unknown op {kind}")
            self._last_committed_decree = decree
            self._meta[META_LAST_FLUSHED_DECREE] = decree
            if self._mem.approximate_bytes >= self.opts.memtable_bytes:
                self._rotate_memtable_locked()

    def put(self, key: bytes, value: bytes, expire_ts: int = 0, decree: int = None):
        d = decree if decree is not None else self._last_committed_decree + 1
        self.write(WriteBatch().put(key, value, expire_ts), d)

    def delete(self, key: bytes, decree: int = None):
        d = decree if decree is not None else self._last_committed_decree + 1
        self.write(WriteBatch().delete(key), d)

    # ------------------------------------------------------------------ read

    def get(self, key: bytes, now: int = None):
        """-> value bytes, or None (missing / deleted / expired).

        Search order = recency: memtable, immutables, L0 newest-first, then
        sorted levels (analogue of the read path in
        src/server/pegasus_server_impl.cpp:265-341 over our structure).
        """
        if fail_point("db_get"):
            raise IOError("injected db_get failure")
        now = epoch_now() if now is None else now
        with self._lock:
            hit = self._mem.get(key)
            if hit is None:
                for imm in self._imm:
                    hit = imm.get(key)
                    if hit is not None:
                        break
            sources = list(self._l0)
            levels = {lv: list(fs) for lv, fs in self._levels.items()}
        if hit is not None:
            value, expire, deleted = hit
            if deleted or check_if_ts_expired(now, expire):
                return None
            return value
        for sst in sources:
            i = sst.find(key)
            if i >= 0:
                return self._record_or_none(sst.block(), i, now)
        for lv in sorted(levels):
            files = levels[lv]
            j = bisect.bisect_right([f.min_key for f in files], key) - 1
            if j >= 0:
                i = files[j].find(key)
                if i >= 0:
                    return self._record_or_none(files[j].block(), i, now)
        return None

    @staticmethod
    def _record_or_none(block: KVBlock, i: int, now: int):
        if block.deleted[i] or check_if_ts_expired(now, int(block.expire_ts[i])):
            return None
        return block.value(i)

    def scan(self, start_key: bytes = b"", stop_key: bytes = None, now: int = None,
             include_deleted: bool = False):
        """Merged iterator over [start_key, stop_key): yields (key, value,
        expire_ts) newest-version-wins, tombstones/expired filtered."""
        now = epoch_now() if now is None else now
        with self._lock:
            mem_snapshot = sorted(
                (k, v) for k, v in self._mem.items()
                if k >= start_key and (stop_key is None or k < stop_key)
            )
            imm_snapshots = [
                sorted((k, v) for k, v in imm.items()
                       if k >= start_key and (stop_key is None or k < stop_key))
                for imm in self._imm
            ]
            ssts = list(self._l0)
            for lv in sorted(self._levels):
                ssts.extend(self._levels[lv])

        def mem_source(snap):
            for k, (v, e, d) in snap:
                yield k, v, e, d

        def sst_source(sst):
            if sst.n == 0:
                return
            b = sst.block()
            i = sst.lower_bound(start_key) if start_key else 0
            while i < b.n:
                k = b.key(i)
                if stop_key is not None and k >= stop_key:
                    return
                yield k, b.value(i), int(b.expire_ts[i]), bool(b.deleted[i])
                i += 1

        sources = [mem_source(mem_snapshot)]
        sources += [mem_source(s) for s in imm_snapshots]
        sources += [sst_source(s) for s in ssts]
        # recency rank = position in `sources`; lower wins for equal keys
        heap = []
        for rank, src in enumerate(sources):
            it = iter(src)
            first = next(it, None)
            if first is not None:
                heap.append((first[0], rank, first, it))
        heapq.heapify(heap)
        prev_key = None
        while heap:
            k, rank, rec, it = heap[0]
            nxt = next(it, None)
            if nxt is not None:
                heapq.heapreplace(heap, (nxt[0], rank, nxt, it))
            else:
                heapq.heappop(heap)
            if k == prev_key:
                continue  # an older version of a key already emitted/skipped
            prev_key = k
            _, v, e, d = rec
            if not include_deleted:
                if d or check_if_ts_expired(now, e):
                    continue
            yield k, v, e

    # ----------------------------------------------------------- flush/compact

    def flush(self) -> None:
        """Rotate the memtable and flush every immutable to an L0 SST
        (device-sorted). Synchronous."""
        with self._lock:
            self._rotate_memtable_locked()
            imms = list(self._imm)
        for imm in reversed(imms):  # oldest first keeps L0 recency order
            self._flush_one(imm)

    def _rotate_memtable_locked(self):
        if len(self._mem) == 0:
            return
        self._imm.insert(0, self._mem)
        self._mem = Memtable()

    def _flush_one(self, imm: Memtable) -> None:
        block = imm.to_block()
        opts = CompactOptions(backend=self.opts.backend, prefix_u32=self.opts.prefix_u32)
        sorted_block = sort_block(block, opts)
        with self._lock:
            decree = int(self._meta.get(META_LAST_FLUSHED_DECREE, 0))
            name = self._alloc_file_locked()
            path = os.path.join(self.path, name)
        write_sst(path, sorted_block, {"level": 0, "last_flushed_decree": decree})
        with self._lock:
            self._l0.insert(0, SSTable(path))
            self._imm.remove(imm)
            self._write_manifest_locked()
        if len(self._l0) >= self.opts.l0_compaction_trigger:
            self.compact(bottommost=True)

    def compact(self, bottommost: bool = True, now: int = None) -> dict:
        """Merge all L0 runs + the sorted level into one new sorted run on the
        configured backend — the CompactRange analogue and the TPU seam
        (reference executor: src/server/pegasus_server_impl.cpp:2814)."""
        with self._lock:
            inputs = list(self._l0)
            old_level = list(self._levels.get(1, []))
            input_blocks = [s.block() for s in inputs] + [s.block() for s in old_level]
            if not input_blocks:
                return {"input_records": 0, "output_records": 0, "dropped": 0}
        opts = CompactOptions(
            now=now,
            pidx=self.opts.pidx,
            partition_mask=self.opts.partition_mask,
            bottommost=bottommost,
            default_ttl=self.opts.default_ttl,
            prefix_u32=self.opts.prefix_u32,
            backend=self.opts.backend,
        )
        result = compact_blocks(input_blocks, opts)
        with self._lock:
            name = self._alloc_file_locked()
            path = os.path.join(self.path, name)
            decree = int(self._meta.get(META_LAST_FLUSHED_DECREE, 0))
        write_sst(path, result.block, {"level": 1, "last_flushed_decree": decree})
        with self._lock:
            self._levels[1] = [SSTable(path)]
            for s in inputs:
                self._l0.remove(s)
            self._write_manifest_locked()
        for s in inputs + old_level:
            s.release()
            try:
                os.unlink(s.path)
            except OSError:
                pass
        return result.stats

    def manual_compact(self, bottommost: bool = True, now: int = None) -> dict:
        self.flush()
        stats = self.compact(bottommost=bottommost, now=now)
        self._meta[META_LAST_MANUAL_COMPACT_FINISH_TIME] = int(time.time())
        with self._lock:
            self._write_manifest_locked()
        return stats

    # ------------------------------------------------------------- checkpoint

    def checkpoint(self, dest_dir: str) -> int:
        """Hardlink-based consistent snapshot: checkpoint.{decree} layout
        (reference: sync_checkpoint / copy_checkpoint_to_dir_unsafe,
        src/server/pegasus_server_impl.cpp:1666,1863). Returns the decree."""
        self.flush()
        with self._lock:
            os.makedirs(dest_dir, exist_ok=True)
            for sst in self._all_ssts_locked():
                dst = os.path.join(dest_dir, os.path.basename(sst.path))
                if not os.path.exists(dst):
                    try:
                        os.link(sst.path, dst)
                    except OSError:
                        import shutil

                        shutil.copy2(sst.path, dst)
            with open(os.path.join(dest_dir, MANIFEST), "w") as f:
                json.dump(self._manifest_dict_locked(), f)
            return self.last_durable_decree()

    # -------------------------------------------------------------- manifest

    def _all_ssts_locked(self):
        out = list(self._l0)
        for lv in sorted(self._levels):
            out.extend(self._levels[lv])
        return out

    def _alloc_file_locked(self) -> str:
        name = f"{self._next_file:06d}.sst"
        self._next_file += 1
        return name

    def _manifest_dict_locked(self) -> dict:
        return {
            "next_file": self._next_file,
            "l0": [os.path.basename(s.path) for s in self._l0],
            "levels": {str(lv): [os.path.basename(s.path) for s in fs]
                       for lv, fs in self._levels.items()},
            "meta": {k: v for k, v in self._meta.items()},
        }

    def _write_manifest_locked(self):
        data = self._manifest_dict_locked()
        tmp = os.path.join(self.path, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, MANIFEST))
        self._durable_meta = dict(data["meta"])

    def _load_manifest(self):
        mpath = os.path.join(self.path, MANIFEST)
        if not os.path.exists(mpath):
            self._meta = {META_DATA_VERSION: self.opts.data_version}
            self._durable_meta = {}
            self._write_manifest_locked()
            return
        with open(mpath) as f:
            m = json.load(f)
        self._next_file = m["next_file"]
        self._l0 = [SSTable(os.path.join(self.path, n)) for n in m["l0"]]
        self._levels = {int(lv): [SSTable(os.path.join(self.path, n)) for n in fs]
                        for lv, fs in m["levels"].items()}
        self._meta = dict(m["meta"])
        self._durable_meta = dict(m["meta"])
        self._last_committed_decree = int(self._meta.get(META_LAST_FLUSHED_DECREE, 0))

    def close(self):
        pass

    # ------------------------------------------------------------- statistics

    def stats(self) -> dict:
        with self._lock:
            return {
                "memtable_records": len(self._mem),
                "memtable_bytes": self._mem.approximate_bytes,
                "immutable_memtables": len(self._imm),
                "l0_files": len(self._l0),
                "level_files": {lv: len(fs) for lv, fs in self._levels.items()},
                "total_sst_records": sum(s.n for s in self._all_ssts_locked()),
                "last_committed_decree": self._last_committed_decree,
                "last_durable_decree": self.last_durable_decree(),
            }
