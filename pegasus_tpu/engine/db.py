"""The LSM engine: memtable + L0 runs + leveled SSTs, device-offloaded
flush/compaction.

Replaces the reference's RocksDB-behind-rocksdb_wrapper
(src/server/rocksdb_wrapper.{h,cpp}) with a from-scratch LSM designed around
KVBlocks: writes land in a dict memtable, flush sorts the block on the
configured backend, compaction feeds sorted runs to ops.compact_blocks.
There is deliberately NO internal WAL: exactly like the reference (which
disables RocksDB's WAL), the replication mutation log is the WAL and replays
into the engine on recovery (SURVEY.md §3.2 note; replication.mutation_log).

Structure:
  - L0: overlapping whole-keyspace runs, newest first (flush outputs).
  - L1..max_levels: runs of non-overlapping range-partitioned files sorted
    by min_key; compaction output is split at target_file_size_bytes so a
    later ranged compaction touches a bounded byte budget, not the whole DB.
  - L0 threshold merges L0 + overlapping L1 files into L1; size-ratio
    overflow cascades one file (+ overlap) per step into the next level.

Durability/decree bookkeeping mirrors the reference invariants (SURVEY.md §7b):
  - every committed batch records its decree in the in-memory meta store
    (reference: LAST_FLUSHED_DECREE put into the meta CF within each
    WriteBatch, src/server/rocksdb_wrapper.cpp:143);
  - the manifest's last_flushed_decree only advances to decrees whose data
    is FULLY covered by on-disk SSTs: each memtable records the last decree
    it contains at rotation, and flushing (oldest-first) advances durability
    to that memtable's decree — never to decrees still sitting in younger
    memtables (the reference reads the meta CF with kPersistedTier for the
    same reason, src/server/meta_store.cpp:129).
"""

import bisect
import heapq
import json
import os
import shutil
import struct
import threading
import time
from dataclasses import dataclass, field

import numpy as np

_UNRESOLVED = object()  # LsmEngine._resolved_mesh: "not probed yet"

from ..base.crc64 import crc64
from ..base.key_schema import key_hash
from ..base.utils import epoch_now
from ..base.value_schema import check_if_ts_expired
from ..runtime.fail_points import fail_point
from ..runtime import events, lockrank
from ..ops.compact import CompactOptions, compact_blocks, sort_block
from .block import KVBlock
from .memtable import Memtable
from .sstable import CorruptionError, SSTable, verify_sst, write_sst

MANIFEST = "MANIFEST"
CHECKPOINT_PREFIX = "checkpoint."

# range-read totals resolved once (PR 6's rule: the registry lock is
# per-lookup and these fire on every multi_get range / sortkey_count /
# scanner batch)
from ..runtime.perf_counters import counters as _counters  # noqa: E402

_C_RANGE_BATCH = _counters.number("read.range.batch_count")
_C_RANGE_ROWS = _counters.number("read.range.rows")
_C_RANGE_DEVICE = _counters.number("read.range.device_count")
_C_RANGE_HOST = _counters.number("read.range.host_count")
_C_RANGE_REV_HOST = _counters.number("read.range.reverse_host_count")


def _count_rows(it):
    """Wrap a merged-scan iterator with read.range.rows accounting — one
    bulk counter add per iterator lifetime (close/exhaustion), not one
    registry hit per row."""
    c = 0
    try:
        for rec in it:
            c += 1
            yield rec
    finally:
        if c:
            _C_RANGE_ROWS.increment(c)

# meta-store keys (reference: src/server/meta_store.cpp:29)
META_DATA_VERSION = "pegasus_data_version"
META_LAST_FLUSHED_DECREE = "pegasus_last_flushed_decree"
META_LAST_MANUAL_COMPACT_FINISH_TIME = "pegasus_last_manual_compact_finish_time"


@dataclass
class EngineOptions:
    memtable_bytes: int = 64 << 20
    l0_compaction_trigger: int = 4
    backend: str = "cpu"            # compaction_backend: "cpu" | "tpu"
    prefix_u32: int = 8
    data_version: int = 2
    pidx: int = 0
    partition_mask: int = 0         # >0 enables split stale-key GC in compaction
    default_ttl: int = 0            # table-level default_ttl app-env
    max_levels: int = 3             # L0 + sorted levels 1..max_levels
    target_file_size_bytes: int = 64 << 20   # split compaction output files
    level_base_bytes: int = 256 << 20        # L1 budget; Ln = base * ratio^(n-1)
    level_size_ratio: int = 10
    device_cache_bytes: int = 8 << 30  # HBM budget for resident run columns
    # device-served point reads (ISSUE 7): route get/multi_get batches
    # through the HBM-resident lookup kernels (ops/device_lookup.py)
    # under the read lane guard. None = on for backend=="tpu" unless
    # PEGASUS_DEVICE_READS=0. device_read_min_batch: smallest per-SST
    # candidate batch worth a device dispatch (below it the host binary
    # search wins; None = PEGASUS_DEVICE_READ_MIN_BATCH, default 2, so a
    # lone sequential get never pays kernel-dispatch latency).
    device_reads: bool = None
    device_read_min_batch: int = None
    # value residency: pin uniform-layout value rows in HBM alongside the
    # key columns so compaction outputs materialize on device (host gather
    # was the r3 bottleneck: 1.27s vs 0.375s merge at 10M). Off until the
    # hardware session proves the download beats the host gather on this
    # tunnel; engine_bench measures both.
    device_values: bool = False
    checkpoint_reserve_min_count: int = 2
    checkpoint_reserve_time_seconds: int = 0  # 0 = no time-based retention
    user_ops: tuple = ()            # parsed user-specified compaction rules
    compression: str = "none"       # SST section compression: none | zlib
    # multi-chip compaction (VERDICT-r3 item 7): when the mesh spans >1
    # device, manual_compact routes through the all_to_all hash-sharded
    # kernel (parallel.sharded_compact) instead of the single-chip merge.
    # sharded_compaction=True resolves a mesh over every visible device at
    # first use; compaction_mesh injects one explicitly (tests, dryrun).
    sharded_compaction: bool = False
    compaction_mesh: object = None  # jax.sharding.Mesh | None


@dataclass
class WriteBatch:
    """Atomic mutation set for one decree (one on_batched_write_requests)."""

    ops: list = field(default_factory=list)  # ("put", key, value, expire) | ("del", key)

    def put(self, key: bytes, value: bytes, expire_ts: int = 0):
        self.ops.append(("put", key, value, expire_ts))
        return self

    def delete(self, key: bytes):
        self.ops.append(("del", key, b"", 0))
        return self


class _RevBytes:
    """bytes wrapper with inverted ordering, for descending heap merges."""

    __slots__ = ("k",)

    def __init__(self, k: bytes):
        self.k = k

    def __lt__(self, other):
        return self.k > other.k

    def __eq__(self, other):
        return self.k == other.k


class _HbmGauges:
    """Process-wide HBM-residency accounting behind the
    `engine.hbm.budget_bytes` / `engine.hbm.resident_bytes` /
    `engine.hbm.resident_ssts` gauges on /metrics: each tpu-backend
    engine (one per partition) reports its budget/usage here on every
    prime/release, and the gauges publish the process sums — the numbers
    the collector/scheduler items queued behind the budget need to see.
    Leaf lock: never takes an engine lock (callers may hold theirs)."""

    def __init__(self):
        self._lock = lockrank.named_lock("engine.hbm_gauges")
        # id(engine) -> (budget, used_bytes, ssts)
        self._per_engine = {}  #: guarded_by self._lock

    def _publish_locked(self):  #: requires self._lock
        from ..runtime.perf_counters import counters

        vals = list(self._per_engine.values())
        counters.number("engine.hbm.budget_bytes").set(
            sum(v[0] for v in vals))
        counters.number("engine.hbm.resident_bytes").set(
            sum(v[1] for v in vals))
        counters.number("engine.hbm.resident_ssts").set(
            sum(v[2] for v in vals))

    def update(self, engine) -> None:
        with self._lock:
            self._per_engine[id(engine)] = (
                engine.opts.device_cache_bytes,
                engine._device_cache_used,
                engine._device_resident_ssts)
            self._publish_locked()

    def drop(self, engine) -> None:
        with self._lock:
            self._per_engine.pop(id(engine), None)
            self._publish_locked()


HBM_GAUGES = _HbmGauges()


class _SchedGate:
    """Per-node concurrent device-compaction cap (ISSUE 10): the cluster
    compaction scheduler bounds how many device merges run at once on
    one node so the TPU lane never convoys behind a burst of L0
    triggers. Elective (trigger-path) compactions defer at the cap;
    urgent/ceiling compactions and manual compacts always proceed — the
    cap shapes timing, never availability. max=0 (the default, knob
    PEGASUS_SCHED_MAX_DEVICE_COMPACT) disables the gate entirely, so the
    scheduler-off behavior is byte-identical to the pre-gate engine.
    Leaf lock: never takes an engine lock (callers hold theirs)."""

    def __init__(self):
        from ..runtime.perf_counters import counters

        self._lock = lockrank.named_lock("engine.sched_gate")
        # resolved once: enter/exit run under self._lock on every device
        # compaction, and a per-call registry lookup would nest the
        # registry lock under the gate lock each time
        self._c_running = counters.number(
            "engine.compact.sched.device_running")
        self._default = int(os.environ.get(
            "PEGASUS_SCHED_MAX_DEVICE_COMPACT", "0"))
        self._ttl_default = float(os.environ.get("PEGASUS_SCHED_TTL_S",
                                                 "30"))
        self._max = self._default      #: guarded_by self._lock
        # set caps are LEASES like the policy tokens: expiry reverts to
        # the env default, so a dead scheduler (or a one-off hand
        # delivery) can never leave a node capped forever
        self._max_expire = None        #: guarded_by self._lock
        self._running = 0              #: guarded_by self._lock

    def set_max(self, n, ttl_s: float = None) -> None:
        """Install a cap lease (ttl_s default PEGASUS_SCHED_TTL_S —
        every set expires; only the env default is permanent)."""
        with self._lock:
            changed = self._max != max(0, int(n))
            self._max = max(0, int(n))
            self._max_expire = time.monotonic() + (
                self._ttl_default if ttl_s is None else float(ttl_s))
            cap = self._max
        if changed:
            events.emit("sched.device_cap", cap=cap)

    def _max_locked(self) -> int:  #: requires self._lock
        if self._max_expire is not None \
                and time.monotonic() >= self._max_expire:
            self._max, self._max_expire = self._default, None
        return self._max

    def at_cap(self) -> bool:
        with self._lock:
            m = self._max_locked()
            return m > 0 and self._running >= m

    def enter(self) -> None:
        with self._lock:
            self._running += 1
            self._c_running.set(self._running)

    def exit(self) -> None:
        with self._lock:
            self._running -= 1
            self._c_running.set(self._running)

    def state(self) -> dict:
        with self._lock:
            return {"max": self._max_locked(), "default": self._default,
                    "running": self._running}


SCHED_GATE = _SchedGate()


def _fail(name: str):
    """FAIL_POINT_INJECT_F call-site helper: only the 'return' verb injects
    a failure; 'print' logs and continues (ADVICE r1: a print-armed point
    must not raise)."""
    fp = fail_point(name)
    if fp is None:
        return False
    verb, arg = fp
    if verb == "print":
        print(f"[fail_point] {name}: print({arg})")
        return False
    return True


class LsmEngine:
    def __init__(self, path: str, options: EngineOptions = None):
        self.path = path
        self.opts = options or EngineOptions()
        self._lock = lockrank.named_rlock("engine.lock")
        self._mem = Memtable()  #: guarded_by self._lock
        # immutable memtables pending flush, newest first
        self._imm = []          #: guarded_by self._lock
        # list[SSTable], newest first
        self._l0 = []           #: guarded_by self._lock
        # level(int>=1) -> list[SSTable] sorted by min_key
        self._levels = {}       #: guarded_by self._lock
        # the meta-CF equivalent (live, unflushed view)
        self._meta = {}         #: guarded_by self._lock
        self._next_file = 1     #: guarded_by self._lock
        self._last_committed_decree = 0  #: guarded_by self._lock
        self._durable_decree = 0         #: guarded_by self._lock
        # level -> round-robin cursor for cascades
        self._compact_round = {}  #: guarded_by self._lock
        # serializes checkpoint create/rename/GC (the shared checkpoint.tmp
        # dir would otherwise race between the maintenance timer and RPC
        # threads); RLock so callers can hold it across create+consume
        self.checkpoint_lock = lockrank.named_rlock("engine.checkpoint")
        # learn-shipping checkpoint pins (ISSUE 13): decree -> {lease
        # token: expiry}, one lease per active learn. A pinned decree's
        # checkpoint.{decree} dir is held out of gc_checkpoints while a
        # learner streams its blocks; pins are TTL leases renewed by
        # fetch activity, so a dead learner can never wedge GC forever
        self._ckpt_pins = {}          #: guarded_by self.checkpoint_lock
        self._pin_token = 0           #: guarded_by self.checkpoint_lock
        # decree -> cached decree-anchored digest of that checkpoint
        # (one scan per pinned checkpoint, not one per learner)
        self._ckpt_digests = {}       #: guarded_by self.checkpoint_lock
        # one flush drainer at a time
        self._flush_lock = lockrank.named_lock("engine.flush")
        # serializes compact()/_maybe_cascade()/manual_compact() merge
        # phases: two concurrent merges over overlapping input snapshots
        # would write the same records into two output sets and double-
        # unlink inputs (ADVICE r2 medium). RLock: compact -> cascade nests.
        self._compaction_lock = lockrank.named_rlock("engine.compaction")
        # tenant accounting (ISSUE 18): set by the host's set_table_name;
        # device-read probes and HBM residency charge here when wired.
        # Plain attribute write — readers tolerate None (lock-free).
        self.table_ledger = None
        # bytes of HBM pinned by resident runs
        self._device_cache_used = 0  #: guarded_by self._lock
        # files currently holding a run
        self._device_resident_ssts = 0  #: guarded_by self._lock
        # read-residency policy flag (collector hotkey loop drives it via
        # the set-read-residency remote command): hot partitions keep
        # their SSTs primed so point reads hit the device path
        self._read_hot = False  #: guarded_by self._lock
        # same-SST prime coordination (see _device_run_budgeted): waiters
        # block on this until the in-flight prime finishes and notifies
        self._prime_cv = lockrank.named_condition("engine.prime_cv",
                                                  self._lock)
        # deferred (pipelined) installs: futures for in-flight pool work,
        # consumed-input files awaiting unlink, and the manifest-write
        # debt (see _install_merge_deferred for the durability invariant)
        self._pending_installs = []  #: guarded_by self._lock
        self._pending_unlinks = []   #: guarded_by self._lock
        self._manifest_dirty = False  #: guarded_by self._lock
        # lazy sharded-compaction mesh
        self._resolved_mesh = _UNRESOLVED  #: guarded_by self._compaction_lock
        # cluster compaction scheduler (ISSUE 10): the per-partition
        # policy token the scheduler delivers over compact-sched-policy.
        # Tokens EXPIRE (ttl) back to "normal": a dead scheduler reverts
        # the engine to its local triggers, never wedges them
        self._sched_policy = "normal"  #: guarded_by self._lock
        self._sched_reasons = ()       #: guarded_by self._lock
        self._sched_expire = 0.0       #: guarded_by self._lock
        # the job-trace id riding the delivered token (ISSUE 16): the
        # compaction the token triggers adopts it, so scheduler decision
        # and engine merge share ONE timeline; cleared on adoption and
        # on lease expiry (a later local trigger mints its own id)
        self._sched_job = ""           #: guarded_by self._lock
        # compaction-offload placement (ISSUE 14): the WHERE half of the
        # scheduler's (when, where) token — a remote compaction service
        # address this cpu-only engine ships its merges to. Same lease
        # semantics as the policy token: expiry reverts to local
        # compaction, so a dead scheduler or service strands nothing
        self._offload_addr = ""        #: guarded_by self._lock
        self._offload_expire = 0.0     #: guarded_by self._lock
        # hard debt ceiling (L0 files) above which the engine-local
        # trigger ALWAYS wins, defer token or not — the availability
        # floor under any scheduler decision. 0 = 3x the L0 trigger.
        ceil = int(os.environ.get("PEGASUS_SCHED_DEBT_CEILING_FILES", "0"))
        self._sched_ceiling = ceil if ceil > 0 else max(
            1, self.opts.l0_compaction_trigger * 3)
        self._sched_ttl_s = float(os.environ.get("PEGASUS_SCHED_TTL_S",
                                                 "30"))
        # trigger-path counters resolved ONCE (the L0 gate runs on every
        # flush drain and maintenance poke — no per-call registry lookup)
        from ..runtime.perf_counters import counters
        self._c_sched_ceiling = counters.rate(
            "engine.compact.sched.ceiling_override_count")
        self._c_sched_deferred = counters.rate(
            "engine.compact.sched.deferred_count")
        self._c_sched_urgent = counters.rate(
            "engine.compact.sched.urgent_count")
        self._c_sched_gate_deferred = counters.rate(
            "engine.compact.sched.gate_deferred_count")
        self._c_offload = counters.rate("engine.compact.offload_count")
        # device-read knobs resolved ONCE (the coalescer consults them on
        # every point read — no per-get environ parse); the backend check
        # stays dynamic because app-envs can flip it at runtime
        dv = self.opts.device_reads
        self._device_reads_flag = ((os.environ.get("PEGASUS_DEVICE_READS",
                                                   "") != "0")
                                   if dv is None else bool(dv))
        mb = self.opts.device_read_min_batch
        self._device_read_min = max(1, int(
            os.environ.get("PEGASUS_DEVICE_READ_MIN_BATCH", "2"))
            if mb is None else mb)
        # corruption callout (ISSUE 17): the hosting replica stub installs
        # a callable(exc) here right after open, before the engine serves —
        # a read path or compaction hitting a CorruptionError notifies it
        # (quarantine driver) and re-raises the typed error to the caller
        self.corruption_hook = None  #: unguarded_ok set once at open, before the engine is published to serving threads
        os.makedirs(path, exist_ok=True)
        self._load_manifest()
        if self.opts.backend == "tpu":
            HBM_GAUGES.update(self)  # budget visible before the first prime

    # ------------------------------------------------------------------ meta

    @property
    def meta_store(self) -> dict:
        return self._meta  #: unguarded_ok ref snapshot: callers get the live dict by design (reference meta-CF semantics)

    def last_durable_decree(self) -> int:
        """Decree covered by on-disk SSTs (manifest's last_flushed_decree)."""
        return int(self._durable_meta.get(META_LAST_FLUSHED_DECREE, 0))  #: unguarded_ok ref snapshot of a dict REPLACED wholesale under the lock; monotone durable watermark

    def last_committed_decree(self) -> int:
        return self._last_committed_decree  #: unguarded_ok racy read of a monotone int (gauges, decree hints)

    def data_version(self) -> int:
        return int(self._meta.get(META_DATA_VERSION, self.opts.data_version))  #: unguarded_ok data_version is written once at open

    # ----------------------------------------------------------------- write

    def write(self, batch: WriteBatch, decree: int) -> None:
        """Apply one committed batch; analogue of rocksdb_wrapper::write
        (src/server/rocksdb_wrapper.cpp:143): data ops + decree meta update,
        atomically under the engine lock."""
        if _fail("db_write"):
            raise IOError("injected db_write failure")
        rotated = False
        with self._lock:
            for op in batch.ops:
                kind, key, value, expire = op
                if kind == "put":
                    if _fail("db_write_batch_put"):
                        raise IOError("injected db_write_batch_put failure")
                    self._mem.put(key, value, expire)
                elif kind == "del":
                    if _fail("db_write_batch_delete"):
                        raise IOError("injected db_write_batch_delete failure")
                    self._mem.delete(key)
                else:
                    raise ValueError(f"unknown op {kind}")
            self._last_committed_decree = decree
            self._meta[META_LAST_FLUSHED_DECREE] = decree
            self._mem.last_decree = decree
            if self._mem.approximate_bytes >= self.opts.memtable_bytes:
                self._rotate_memtable_locked()
                rotated = True
        if rotated:
            # a full memtable must reach disk; done outside the mutation
            # loop's critical section (the reference stalls writes the same
            # way when memtables back up)
            self._drain_imms()

    def write_batch(self, pairs) -> None:
        """Apply a contiguous committed decree window — `pairs` is
        [(WriteBatch, decree)] in decree order — under ONE engine lock
        acquisition. Consecutive same-kind ops collapse into memtable
        put_batch/delete_batch calls; decree bookkeeping still advances
        per decree, so a mid-window failure (fail points) leaves
        last_committed_decree exactly at the last fully-applied decree."""
        if not pairs:
            return
        if _fail("db_write"):
            raise IOError("injected db_write failure")
        fail_put = _fail("db_write_batch_put")
        fail_del = _fail("db_write_batch_delete")
        rotated = False
        with self._lock:
            for batch, decree in pairs:
                run_kind, run = None, []
                for op in batch.ops + [None]:  # None flushes the last run
                    kind = op[0] if op is not None else None
                    if kind != run_kind and run:
                        if run_kind == "put":
                            if fail_put:
                                raise IOError(
                                    "injected db_write_batch_put failure")
                            self._mem.put_batch(run)
                        else:
                            if fail_del:
                                raise IOError(
                                    "injected db_write_batch_delete failure")
                            self._mem.delete_batch(run)
                        run = []
                    if op is None:
                        break
                    run_kind = kind
                    if kind == "put":
                        run.append((op[1], op[2], op[3]))
                    elif kind == "del":
                        run.append(op[1])
                    else:
                        raise ValueError(f"unknown op {kind}")
                self._last_committed_decree = decree
                self._meta[META_LAST_FLUSHED_DECREE] = decree
                self._mem.last_decree = decree
                if self._mem.approximate_bytes >= self.opts.memtable_bytes:
                    self._rotate_memtable_locked()
                    rotated = True
        if rotated:
            self._drain_imms()

    def put(self, key: bytes, value: bytes, expire_ts: int = 0, decree: int = None):
        d = decree if decree is not None else self._last_committed_decree + 1  #: unguarded_ok single-writer convenience path (tests/tools); replication always passes the decree
        self.write(WriteBatch().put(key, value, expire_ts), d)

    def delete(self, key: bytes, decree: int = None):
        d = decree if decree is not None else self._last_committed_decree + 1  #: unguarded_ok single-writer convenience path (tests/tools); replication always passes the decree
        self.write(WriteBatch().delete(key), d)

    # ------------------------------------------------------------------ read

    def get(self, key: bytes, now: int = None):
        """-> value bytes, or None (missing / deleted / expired).

        Search order = recency: memtable, immutables, L0 newest-first, then
        sorted levels (analogue of the read path in
        src/server/pegasus_server_impl.cpp:265-341 over our structure).
        Point reads prune files by key range and hashkey bloom filter
        (reference: hashkey_transform.h prefix bloom) before loading data.
        """
        if _fail("db_get"):
            raise IOError("injected db_get failure")
        now = epoch_now() if now is None else now
        h32 = np.uint32(key_hash(key) & 0xFFFFFFFF)
        with self._lock:
            hit = self._mem.get(key)
            if hit is None:
                for imm in self._imm:
                    hit = imm.get(key)
                    if hit is not None:
                        break
            sources = list(self._l0)
            levels = {lv: list(fs) for lv, fs in self._levels.items()}
        if hit is not None:
            value, expire, deleted = hit
            if deleted or check_if_ts_expired(now, expire):
                return None
            return value
        # the SAME recency walk get_batch's host fallback runs (one copy
        # of the ordering/pruning rules); a lone get stays host-served —
        # device batches enter through get_batch
        res = self._walk_sources([key], [now], [h32], [0], sources, levels,
                                 use_device=False)
        return res.get(0)

    @staticmethod
    def _record_or_none(block: KVBlock, i: int, now: int):
        if block.deleted[i] or check_if_ts_expired(now, int(block.expire_ts[i])):
            return None
        return block.value(i)

    # -------------------------------------------------- device-served reads

    def _device_reads_on(self) -> bool:
        return self.opts.backend == "tpu" and self._device_reads_flag

    def set_read_residency(self, on: bool) -> None:
        """Read-residency policy hook (the collector's hotkey loop drives
        this through the set-read-residency remote command): a read-hot
        partition primes every current SST into HBM — fire-and-forget on
        the pipeline pool — and may fill its WHOLE HBM budget, where a
        cold partition's primes stop at 7/8 of it (the reserved headroom
        this pin claims; see _device_run_budgeted). Off only clears the
        flag: resident runs stay (compaction still wants them) and age
        out through the normal merge lifecycle."""
        with self._lock:
            # under the engine lock: _device_run_budgeted reads the flag
            # to size the prime budget, and an unlocked flip could let a
            # cold prime claim the reserved read-hot headroom mid-check
            # (caught by tools/analyze lock_discipline)
            self._read_hot = bool(on)
            ssts = self._all_ssts_locked() \
                if on and self.opts.backend == "tpu" else []
        for sst in ssts:
            self._prime_async(sst)

    def get_batch(self, keys, now=None) -> list:
        """Batched point lookup, semantically identical to
        [get(k) for k in keys] against one consistent snapshot. `now` is
        a scalar or a per-key list (the server's read coalescer groups
        requests that resolved their clocks independently).

        Memtable/immutable hits resolve on the host; the SST walk runs
        device-side when HBM-resident runs with indexes exist — one
        batched probe per SST (ops/device_lookup.py) under the read lane
        guard, whose fallback reruns the identical walk with host binary
        search, byte-identical by construction (both return the same row
        index into the same cached block)."""
        if _fail("db_get"):
            raise IOError("injected db_get failure")
        n = len(keys)
        if now is None:
            now = epoch_now()
        nows = list(now) if isinstance(now, (list, tuple)) else [now] * n
        from ..runtime.tracing import COMPACT_TRACER

        with COMPACT_TRACER.span("read.batch", records=n):
            return self._get_batch_impl(keys, nows)

    def _get_batch_impl(self, keys, nows) -> list:
        n = len(keys)
        out = [_UNRESOLVED] * n
        h32s = [np.uint32(key_hash(k) & 0xFFFFFFFF) for k in keys]
        with self._lock:
            for i, k in enumerate(keys):
                hit = self._mem.get(k)
                if hit is None:
                    for imm in self._imm:
                        hit = imm.get(k)
                        if hit is not None:
                            break
                if hit is not None:
                    value, expire, deleted = hit
                    out[i] = (None if deleted
                              or check_if_ts_expired(nows[i], expire)
                              else value)
            sources = list(self._l0)
            levels = {lv: list(fs) for lv, fs in self._levels.items()}
        pending = [i for i in range(n) if out[i] is _UNRESOLVED]
        if pending:
            all_ssts = sources + [f for fs in levels.values() for f in fs]
            device_ok = (self._device_reads_on()
                         and any(s.device_index is not None
                                 for s in all_ssts))

            def walk(use_device):
                return self._walk_sources(keys, nows, h32s, pending,
                                          sources, levels, use_device)

            if device_ok:
                from ..runtime.lane_guard import READ_LANE_GUARD

                res = READ_LANE_GUARD.run(lambda: walk(True),
                                          lambda: walk(False), op="read")
            else:
                res = walk(False)
            for i, v in res.items():
                out[i] = v
        return [None if v is _UNRESOLVED else v for v in out]

    def _walk_sources(self, keys, nows, h32s, pending, sources, levels,
                      use_device) -> dict:
        """Recency-ordered SST walk for a key batch over a snapshot.
        Pure function of the snapshot (no engine state mutated): the read
        lane's fallback reruns it with use_device=False and must see the
        exact same inputs. -> {key index: value | None(resolved)}."""
        res = {}
        pend = list(pending)
        for sst in sources:
            if not pend:
                break
            cand = [i for i in pend if sst.maybe_contains_hash(h32s[i])]
            self._probe_sst(sst, cand, keys, nows, res, use_device)
            pend = [i for i in pend if i not in res]
        for lv in sorted(levels):
            if not pend:
                break
            files = levels[lv]
            mins = [f.min_key for f in files]
            by_file = {}
            for i in pend:
                j = bisect.bisect_right(mins, keys[i]) - 1
                if j >= 0 and files[j].maybe_contains_hash(h32s[i]):
                    by_file.setdefault(j, []).append(i)
            for j, cand in sorted(by_file.items()):
                self._probe_sst(files[j], cand, keys, nows, res, use_device)
            pend = [i for i in pend if i not in res]
        return res

    def _notify_corruption(self, exc) -> None:
        """Best-effort callout on a typed CorruptionError: counted,
        evented, and forwarded to the hosting stub's corruption_hook
        (which pulls this replica off the serving path). Callers always
        re-raise — the client gets the typed error, never garbage."""
        from ..runtime import events
        from ..runtime.perf_counters import counters

        counters.rate("engine.corruption_count").increment()
        events.emit("engine.corruption", "error",
                    path=str(getattr(exc, "path", "")),
                    detail=str(getattr(exc, "detail", exc)))
        hook = self.corruption_hook
        if hook is not None:
            try:
                hook(exc)
            except Exception as e:  # the hook must never mask the error
                print(f"[engine] corruption hook failed: {e!r}", flush=True)

    def _probe_sst(self, sst, cand, keys, nows, res, use_device) -> None:
        """Resolve one SST's candidates into `res` (hits only — a found
        tombstone/expired record resolves to None exactly like db.get).
        Device path when the file holds an indexed resident run and the
        candidate batch is worth a dispatch; host binary search otherwise
        — identical row indexes either way."""
        if not cand:
            return
        try:
            self._probe_sst_impl(sst, cand, keys, nows, res, use_device)
        except CorruptionError as e:
            self._notify_corruption(e)
            raise

    def _probe_sst_impl(self, sst, cand, keys, nows, res, use_device) -> None:
        dr = sst.device_index if use_device else None
        if dr is not None and len(cand) >= self._device_read_min:
            from ..ops.device_lookup import lookup_batch
            from ..runtime.tracing import COMPACT_TRACER

            rows = lookup_batch(dr, [keys[i] for i in cand])
            if self.table_ledger is not None:
                self.table_ledger.charge_device_read(len(cand))
            hits = [(i, int(r)) for i, r in zip(cand, rows) if r >= 0]
            with COMPACT_TRACER.span("read.gather", records=len(hits)):
                block = sst.block()
                for i, row in hits:
                    res[i] = self._record_or_none(block, row, nows[i])
            return
        for i in cand:
            row = sst.find(keys[i])
            if row >= 0:
                res[i] = self._record_or_none(sst.block(), row, nows[i])

    def scan(self, start_key: bytes = b"", stop_key: bytes = None, now: int = None,
             include_deleted: bool = False, reverse: bool = False,
             hash32=None):
        """Merged iterator over [start_key, stop_key): yields (key, value,
        expire_ts) newest-version-wins, tombstones/expired filtered.
        reverse=True iterates the same range descending (the engine-level
        Prev() the reference's reverse multi_get uses), so a bounded reader
        sees the TAIL of the range first.

        hash32: when the whole range lives under ONE hashkey (multi_get /
        sortkey_count / hash scans), its 32-bit hashkey hash lets the file
        walk probe each SST's hashkey bloom and skip files that cannot hold
        the hashkey — the reference's prefix-bloom range pruning
        (src/server/hashkey_transform.h:31-60 + ReadOptions prefix_same_as_
        start), which min/max-key overlap alone cannot provide."""
        return self._scan_over(None, start_key, stop_key, now,
                               include_deleted, reverse, hash32)

    def _scan_snapshot(self):
        """One consistent source snapshot for a merged scan — the part of
        scan() that must hold the engine lock. snapshot-only under it: the
        old code SORTED and range-filtered the whole memtable inside, so
        concurrent scanners convoyed on the lock (BASELINE's
        4-thread-slower-than-1-thread scan). list(dict.items()) is a plain
        O(n) copy; the sort/filter runs lock-free in _scan_over."""
        with self._lock:
            mem_items = list(self._mem.items())
            imm_items = [list(imm.items()) for imm in self._imm]
            ssts = list(self._l0)
            for lv in sorted(self._levels):
                ssts.extend(self._levels[lv])
        return mem_items, imm_items, ssts

    def _scan_over(self, snap, start_key, stop_key, now,
                   include_deleted=False, reverse=False, hash32=None,
                   sst_bounds=None):
        """The merged-scan generator over a _scan_snapshot (None = take
        one lazily on first pull, preserving scan()'s generator
        semantics). `sst_bounds` ({id(sst): (lo, hi)}) injects
        pre-resolved per-SST row intervals — the device range path
        (scan_range_batch) supplies them so the IDENTICAL merge below
        yields byte-identical rows with the host binary searches elided;
        absent entries mean the SST was pruned."""
        if snap is None:
            snap = self._scan_snapshot()
        now = epoch_now() if now is None else now
        mem_items, imm_items, ssts = snap

        def in_range(k):
            return k >= start_key and (stop_key is None or k < stop_key)

        mem_snapshot = sorted((k, v) for k, v in mem_items if in_range(k))
        imm_snapshots = [sorted((k, v) for k, v in items if in_range(k))
                         for items in imm_items]

        def mem_source(snap):
            it = reversed(snap) if reverse else snap
            for k, (v, e, d) in it:
                yield k, v, e, d

        def sst_source(sst):
            if sst_bounds is not None:
                lohi = sst_bounds.get(id(sst))
                if lohi is None or lohi[0] >= lohi[1]:
                    return  # pruned or empty interval
                try:
                    b = sst.block()
                except CorruptionError as e:
                    self._notify_corruption(e)
                    raise
                lo, hi = lohi
            else:
                if sst.n == 0:
                    return
                if stop_key is not None and sst.min_key and sst.min_key >= stop_key:
                    return
                if start_key and sst.max_key and sst.max_key < start_key:
                    return
                if hash32 is not None and not sst.maybe_contains_hash(hash32):
                    return
                try:
                    b = sst.block()
                except CorruptionError as e:
                    self._notify_corruption(e)
                    raise
                lo = sst.lower_bound(start_key) if start_key else 0
                hi = sst.lower_bound(stop_key) if stop_key is not None else b.n
            rng = range(hi - 1, lo - 1, -1) if reverse else range(lo, hi)
            for i in rng:
                yield b.key(i), b.value(i), int(b.expire_ts[i]), bool(b.deleted[i])

        sources = [mem_source(mem_snapshot)]
        sources += [mem_source(s) for s in imm_snapshots]
        sources += [sst_source(s) for s in ssts]
        # recency rank = position in `sources`; lower wins for equal keys.
        # descending merges invert the key order, not the recency order.
        hk = (lambda k: _RevBytes(k)) if reverse else (lambda k: k)
        heap = []
        for rank, src in enumerate(sources):
            it = iter(src)
            first = next(it, None)
            if first is not None:
                heap.append((hk(first[0]), rank, first, it))
        heapq.heapify(heap)
        prev_key = None
        while heap:
            _, rank, rec, it = heap[0]
            k = rec[0]
            nxt = next(it, None)
            if nxt is not None:
                heapq.heapreplace(heap, (hk(nxt[0]), rank, nxt, it))
            else:
                heapq.heappop(heap)
            if k == prev_key:
                continue  # an older version of a key already emitted/skipped
            prev_key = k
            _, v, e, d = rec
            if not include_deleted:
                if d or check_if_ts_expired(now, e):
                    continue
            yield k, v, e

    def scan_range_batch(self, ranges, now=None, reverse=False,
                         hash32s=None) -> list:
        """Batched bounded scans over ONE consistent snapshot: for each
        (start_key, stop_key) in `ranges` (stop None = open end), yields
        exactly what scan(start, stop) would — newest-wins / tombstone /
        TTL filtered by the same merge generator — but every indexed
        resident SST resolves its per-query lower_bound row intervals
        device-side in ONE batched kernel dispatch per SST
        (ops/device_lookup.py range_batch) under READ_LANE_GUARD, whose
        fallback recomputes the same intervals with host binary search
        over the SAME snapshot. Both paths feed identical intervals to
        the identical generator (_scan_over), so results are
        byte-identical by construction. reverse=True (and engines without
        device reads) serve entirely host-side and say so in
        read.range.{reverse_host_count,host_count}.

        `now` is a scalar or per-range list (the server's range coalescer
        groups requests that resolved their clocks independently).
        -> list of iterators, one per range, in order."""
        n = len(ranges)
        if n == 0:
            return []
        if now is None:
            now = epoch_now()
        nows = list(now) if isinstance(now, (list, tuple)) else [now] * n
        h32s = list(hash32s) if hash32s is not None else [None] * n
        _C_RANGE_BATCH.increment()
        snap = self._scan_snapshot()
        device_ok = (not reverse and self._device_reads_on()
                     and any(s.device_index is not None for s in snap[2]))
        if not device_ok:
            (_C_RANGE_REV_HOST if reverse else _C_RANGE_HOST).increment(n)
            return [_count_rows(self._scan_over(
                        snap, s, t, nows[i], False, reverse, h32s[i]))
                    for i, (s, t) in enumerate(ranges)]
        from ..runtime.lane_guard import READ_LANE_GUARD

        bounds = READ_LANE_GUARD.run(
            lambda: self._resolve_sst_bounds(snap[2], ranges, h32s, True),
            lambda: self._resolve_sst_bounds(snap[2], ranges, h32s, False),
            op="range")
        return [_count_rows(self._scan_over(snap, s, t, nows[i], False,
                                            False, h32s[i],
                                            sst_bounds=bounds[i]))
                for i, (s, t) in enumerate(ranges)]

    def _resolve_sst_bounds(self, ssts, ranges, h32s, use_device) -> list:
        """Per-(query, SST) row intervals for a range batch over a
        snapshot. Pure function of the snapshot (the read lane's fallback
        reruns it with use_device=False and must see the exact same
        inputs). -> one {id(sst): (lo, hi)} dict per query; an SST absent
        from a query's dict was pruned by exactly the host iterator's
        metadata/bloom conditions, so _scan_over skips it identically."""
        bounds = [dict() for _ in ranges]
        for sst in ssts:
            if sst.n == 0:
                continue
            cand = []
            for qi, (start_key, stop_key) in enumerate(ranges):
                if stop_key is not None and sst.min_key \
                        and sst.min_key >= stop_key:
                    continue
                if start_key and sst.max_key and sst.max_key < start_key:
                    continue
                if h32s[qi] is not None \
                        and not sst.maybe_contains_hash(h32s[qi]):
                    continue
                if not start_key and stop_key is None:
                    # whole-run query: no bound to resolve on any path
                    bounds[qi][id(sst)] = (0, sst.n)
                    continue
                cand.append(qi)
            if not cand:
                continue
            dr = sst.device_index if use_device else None
            try:
                if dr is not None and len(cand) >= self._device_read_min:
                    from ..ops.device_lookup import range_batch

                    iv = range_batch(dr, [ranges[qi] for qi in cand])
                    if self.table_ledger is not None:
                        self.table_ledger.charge_device_read(len(cand))
                    for qi, (lo, hi) in zip(cand, iv):
                        bounds[qi][id(sst)] = (int(lo), int(hi))
                    continue
                for qi in cand:
                    start_key, stop_key = ranges[qi]
                    lo = sst.lower_bound(start_key) if start_key else 0
                    hi = sst.lower_bound(stop_key) \
                        if stop_key is not None else sst.n
                    bounds[qi][id(sst)] = (lo, hi)
            except CorruptionError as e:
                self._notify_corruption(e)
                raise
        (_C_RANGE_DEVICE if use_device else _C_RANGE_HOST).increment(
            len(ranges))
        return bounds

    # ------------------------------------------------------------------ audit

    def state_digest(self, now: int = None, pmask: int = None) -> dict:
        """Order-independent digest of the LIVE logical state — the
        consistency-audit primitive (ISSUE 8). Walks memtable + immutables
        + every SST through the one merged recency iterator (scan: same
        newest-wins / tombstone / TTL rules as the read path), folding one
        crc64 per record (key, value bytes, expire_ts) into an XOR and an
        additive sum plus a count — commutative combines, so the PHYSICAL
        layout (what compacted where, which level holds what) cannot
        matter, only the logical contents can.

        Tombstones and expired records are EXCLUDED: per-replica
        compaction independently drops both, so their physical presence is
        legitimately divergent state. `now` must be the auditor-chosen
        clock (the trigger_audit mutation carries it) so every replica
        filters expiry against the same instant.

        Records the partition no longer OWNS after a split (the
        partition-version rule: ``key_hash % partition_count != pidx``,
        the same ownership split stale-key GC enforces in compaction)
        are excluded for the same reason: after a split, a replica that
        compacted has physically dropped its stale half while a sibling
        that has not compacted yet still holds it — comparing them would
        fake a mismatch — and the cross-CLUSTER table fold (ISSUE 11)
        would double-count every key still physically present in both
        the parent and the child partition. `pmask` must be the
        AUDITOR-chosen mask carried in the trigger-audit mutation (the
        env-spread partition_version lands at different times per
        replica; None falls back to the engine's own mask for direct
        engine-level callers)."""
        now = epoch_now() if now is None else now
        pmask = self.opts.partition_mask if pmask is None else pmask
        xor = add = n = 0
        for k, v, e in self.scan(now=now):
            if pmask and key_hash(k) % (pmask + 1) != self.opts.pidx:
                continue
            c = crc64(struct.pack("<I", len(k)) + k
                      + struct.pack("<q", int(e)) + v)
            xor ^= c
            add = (add + c) & 0xFFFFFFFFFFFFFFFF
            n += 1
        return {"digest": f"{xor:016x}{add:016x}", "records": n, "now": now}

    # ------------------------------------------------------------------ scrub

    def scrub(self, rate_bytes_per_s: float = None) -> dict:
        """Background integrity pass (ISSUE 17): re-verify every landed
        SST's section checksums OFF the serving path (raw file reads, no
        block materialization, no device work — lane guards untouched by
        construction) and recompute the manifest-referenced file set
        against the directory. Rate-limited to `rate_bytes_per_s` when
        set. Returns {"files", "bytes", "findings": [{"path","detail"}]}.
        Findings are returned, not acted on — the hosting stub owns the
        quarantine decision. Files that vanish mid-scan (compacted away)
        or are still landing (deferred installs) are skipped, and a
        manifest reference is only a finding while the live version still
        claims it."""
        from ..runtime.fail_points import FailPointError, inject
        from ..runtime.job_trace import JOB_TRACER
        from ..runtime.perf_counters import counters

        with self._lock:
            paths = [s.path for s in self._all_ssts_locked() if s._on_disk]
        findings = []
        errors = []
        scanned_files = scanned_bytes = 0
        t0 = time.monotonic()
        with JOB_TRACER.job("engine.scrub", path=self.path):
            with JOB_TRACER.hop("scrub.files") as attrs:
                for p in paths:
                    try:
                        inject("scrub.verify")
                        scanned_bytes += verify_sst(p)
                        scanned_files += 1
                    except FileNotFoundError:
                        continue  # compacted away mid-scan
                    except FailPointError as e:
                        # injected scrub fault (chaos): the file was NOT
                        # verified — an error to retry next cadence, never
                        # a corruption finding (a finding quarantines the
                        # replica; chaos must not nuke healthy copies)
                        errors.append({"path": p, "detail": str(e)})
                    except CorruptionError as e:
                        findings.append({"path": p, "detail": e.detail})
                    if rate_bytes_per_s and rate_bytes_per_s > 0:
                        budget_s = scanned_bytes / rate_bytes_per_s
                        lag = budget_s - (time.monotonic() - t0)
                        if lag > 0:
                            time.sleep(min(lag, 1.0))
                attrs.update(files=scanned_files, bytes=scanned_bytes,
                             findings=len(findings))
            with JOB_TRACER.hop("scrub.manifest") as attrs:
                missing = self._scrub_manifest()
                attrs.update(missing=len(missing))
                findings.extend(missing)
        counters.rate("scrub.files_count").increment(scanned_files)
        counters.rate("scrub.bytes").increment(scanned_bytes)
        if findings:
            counters.rate("scrub.corruption_count").increment(len(findings))
        return {"files": scanned_files, "bytes": scanned_bytes,
                "findings": findings, "errors": errors}

    def _scrub_manifest(self) -> list:
        """Every file the on-disk MANIFEST references must exist — unless
        the live version no longer claims it (a compaction landed between
        the disk read and the existence check)."""
        mpath = os.path.join(self.path, MANIFEST)
        try:
            with open(mpath) as f:
                m = json.load(f)
            referenced = list(m.get("l0", []))
            for fs in m.get("levels", {}).values():
                referenced.extend(fs)
        except FileNotFoundError:
            return []  # fresh dir: nothing referenced yet
        except (ValueError, KeyError, TypeError) as e:
            return [{"path": mpath, "detail": f"unparseable manifest: {e}"}]
        gone = [n for n in referenced
                if not os.path.exists(os.path.join(self.path, n))]
        if not gone:
            return []
        with self._lock:
            live = self._manifest_dict_locked()
            still = set(live["l0"])
            for fs in live["levels"].values():
                still.update(fs)
        return [{"path": os.path.join(self.path, n),
                 "detail": "manifest references missing file"}
                for n in gone if n in still]

    # ----------------------------------------------------------- flush/compact

    def flush(self) -> None:
        """Rotate the memtable and flush every immutable to an L0 SST
        (device-sorted). Synchronous; oldest-first keeps both L0 recency
        order and the durable-decree invariant. Settles the currently
        queued deferred installs (light: no compaction-lock exclusion, so
        a flush never stalls behind a whole in-flight cascade)."""
        with self._lock:
            self._rotate_memtable_locked()
        self._drain_imms()
        self._settle_installs()

    def _drain_imms(self) -> None:
        """Flush pending immutables oldest-first. The flush lock serializes
        concurrent drainers (writer threads + explicit flush calls): without
        it two threads could flush the same memtable, or a newer one could
        reach disk first and falsely advance the durable decree.

        The L0 compaction trigger fires AFTER the flush lock is released:
        lockrank caught the inversion — compaction under the flush lock
        orders flush->compaction, while batched_manual_compact flushes
        engine i+1 with engine i's compaction lock held
        (compaction->flush), a deadlock waiting for the interleaving —
        and holding the flush lock across a whole compaction convoyed
        every writer behind it anyway."""
        drained = False
        with self._flush_lock:
            while True:
                with self._lock:
                    if not self._imm:
                        break
                    imm = self._imm[-1]  # list is newest-first: take oldest
                self._flush_one(imm)
                drained = True
        if drained:
            self._maybe_trigger_l0()

    def _rotate_memtable_locked(self):  #: requires self._lock
        if len(self._mem) == 0:
            return
        self._imm.insert(0, self._mem)
        self._mem = Memtable()
        self._mem.last_decree = self._last_committed_decree

    def _flush_one(self, imm: Memtable) -> None:
        # event-listener counters (reference pegasus_event_listener.h:30-52)
        from ..runtime.perf_counters import counters

        t0 = time.perf_counter()
        block = imm.to_block()
        opts = CompactOptions(backend=self.opts.backend, prefix_u32=self.opts.prefix_u32)
        sorted_block = sort_block(block, opts)
        counters.rate("engine.flush_completed_count").increment()
        counters.percentile("engine.flush_s").set(time.perf_counter() - t0)
        with self._lock:
            name = self._alloc_file_locked()
            path = os.path.join(self.path, name)
        write_sst(path, sorted_block, {"level": 0,
                                       "last_flushed_decree": imm.last_decree},
                  compression=self.opts.compression)
        sst = SSTable(path)
        sst._block = sorted_block  # already in memory: skip the disk re-read
        # flush-time residency prime: upload the newborn run's packed
        # columns off the WRITE PATH (pipeline pool) so its first
        # compaction already reads HBM without the flush paying the
        # upload; a compaction that wins the race simply host-packs once
        self._prime_async(sst)
        with self._lock:
            self._l0.insert(0, sst)
            self._imm.remove(imm)
            # durability advances exactly to this memtable's decree: every
            # older memtable has already flushed (oldest-first), younger ones
            # hold strictly later decrees (ADVICE r1 high)
            self._durable_decree = max(self._durable_decree, imm.last_decree)
            self._write_manifest_locked()

    def _prime_async(self, sst):
        """Fire-and-forget device-residency prime on the pipeline pool.
        No future is tracked: a wedged device prime must never hang a
        drain/flush/close (the per-SST in-flight marker keeps later
        callers from stacking behind it — they simply host-pack)."""
        if self.opts.backend != "tpu":
            return
        from ..ops.pipeline import submit

        submit(self._device_run_budgeted, sst)

    def _device_run_budgeted(self, sst):
        """Prime/fetch an SST's device-resident run under the HBM budget:
        past the budget (or on a device allocation failure) the file simply
        stays host-packed — compaction falls back gracefully instead of
        OOMing the write path. Concurrency: a per-SST in-flight marker
        (under the engine lock) keeps an async prime and an inline caller
        from double-uploading one file, without serializing primes of
        DIFFERENT files or holding any lock across the device upload;
        budget accounting is settled under the lock against the retired
        flag, so a release can never subtract bytes that were not added."""
        if self.opts.backend != "tpu":
            return None
        from ..runtime.lane_guard import LANE_GUARD

        want_values = self.opts.device_values
        with self._lock:
            # same-SST coordination: if another thread is mid-prime on
            # THIS file, wait for its result instead of double-uploading
            # or returning a spurious None (a compaction racing the async
            # flush prime must still get the HBM run). Bounded: a wedged
            # prime is abandoned at the lane deadline, never stacked on.
            deadline = None
            while sst._prime_inflight:
                if deadline is None:
                    eff = LANE_GUARD.effective_deadline_s()
                    # deadline <= 0 means "deadline disabled", not "give
                    # up immediately" — wait as long as the lane would
                    bound = eff if eff and eff > 0 else 3600.0
                    deadline = time.monotonic() + bound
                self._prime_cv.wait(timeout=0.05)
                if time.monotonic() > deadline:
                    return sst._device_run
            cached = sst._device_run
            if sst._device_retired:
                return None
            if cached is not None and (not want_values
                                       or cached.val2d is not None):
                return cached
            sst._prime_inflight = True
        try:
            if LANE_GUARD.breaker_open(probe=False):
                # the breaker routes all compaction to cpu; priming HBM
                # for a device the guard has declared dead would only
                # re-wedge. probe=False: the write path must never block
                # on a half-open device probe — the next guarded
                # compaction does that
                return cached
            with self._lock:
                # read-residency priority: a partition NOT flagged
                # read-hot stops priming at 7/8 of its budget, reserving
                # headroom the hotkey loop's set-read-residency pin can
                # claim — the flag is a real input to what stays
                # resident, not just a stat
                budget = self.opts.device_cache_bytes
                if not self._read_hot:
                    budget -= budget >> 3
                if self._device_cache_used >= budget:
                    return cached  # a value-less cached run still serves
            old_bytes = cached.nbytes() if cached is not None else 0
            try:
                dr = sst.device_run(self.opts.prefix_u32,
                                    with_values=want_values)
            except Exception as e:  # device OOM / backend failure: one policy
                # breaker=False: an oversized sst OOMing its prime is
                # capacity-local, not device death — it must not flap every
                # compaction onto cpu
                LANE_GUARD.record_device_failure("device_run_prime", repr(e),
                                                 breaker=False)
                print(f"[engine] device-run prime failed for {sst.path}: "
                      f"{e!r}", flush=True)
                sst._device_uncacheable = True
                return None
            with self._lock:
                if sst._device_retired:
                    # an async prime lost the race against the merge that
                    # consumed this file: drop the upload, never the budget
                    sst._device_run = None
                    return None
                if dr is not None:
                    self._device_cache_used += dr.nbytes() - old_bytes
                    if not sst._device_budgeted:
                        self._device_resident_ssts += 1
                    sst._device_budgeted = True
                    HBM_GAUGES.update(self)
            return dr
        finally:
            with self._lock:
                sst._prime_inflight = False
                self._prime_cv.notify_all()

    def _release_device_run(self, sst):
        with self._lock:
            sst._device_retired = True
            if sst._device_run is not None and sst._device_budgeted:
                self._device_cache_used -= sst._device_run.nbytes()
                self._device_resident_ssts -= 1
                HBM_GAUGES.update(self)
            sst._device_budgeted = False
            sst._device_run = None

    # ------------------------------------------------- compaction scheduling

    def set_compact_policy(self, policy: str, reasons=(),
                           ttl_s: float = None, job: str = "") -> None:
        """Install the cluster scheduler's per-partition policy token
        (ISSUE 10): 'defer' holds the elective L0 trigger (below the hard
        debt ceiling), 'urgent' fires it at half the normal threshold and
        lets manual compactions jump the concurrency queue, 'normal' is
        the engine-local behavior. The token expires after ttl_s (default
        PEGASUS_SCHED_TTL_S) back to 'normal' — a dead scheduler can
        never wedge compaction."""
        if policy not in ("defer", "normal", "urgent"):
            raise ValueError(f"bad compaction policy {policy!r}")
        with self._lock:
            changed = self._sched_policy != policy
            self._sched_policy = policy
            self._sched_reasons = tuple(reasons)
            self._sched_expire = time.monotonic() + (
                self._sched_ttl_s if ttl_s is None else float(ttl_s))
            if job:
                self._sched_job = job
        if changed:
            # transitions only: steady-state re-deliveries every tick
            # would be ring noise, a defer->urgent flip is the story
            events.emit("sched.token_apply", policy=policy,
                        reasons=",".join(reasons), engine=self.path)

    def compact_policy(self) -> tuple:
        """-> (policy, reasons, expires_in_s); an expired token reads —
        and resets — as ('normal', [], 0.0)."""
        expired = None
        with self._lock:
            now = time.monotonic()
            if self._sched_policy != "normal" and now >= self._sched_expire:
                expired = self._sched_policy
                self._sched_policy, self._sched_reasons = "normal", ()
                self._sched_job = ""
            out = (self._sched_policy, list(self._sched_reasons),
                   max(0.0, self._sched_expire - now)
                   if self._sched_policy != "normal" else 0.0)
        if expired is not None:
            # a lease running out (vs being replaced) means the scheduler
            # stopped delivering — exactly the kind of transient the
            # flight recorder exists to keep
            events.emit("sched.token_expired", severity="warn",
                        was=expired, engine=self.path)
        return out

    def set_offload_target(self, addr: str, ttl_s: float = None) -> None:
        """Install the scheduler's compaction-offload placement (ISSUE
        14) — the WHERE half of the (when, where) token: while the lease
        is live, this engine's merges ship to the compaction service at
        `addr` (empty = compact locally). A lapsed lease reverts to
        local compaction — a dead scheduler can never strand merges on
        a gone service (and the offload lane guard's cpu fallback covers
        the window where the lease outlives the service)."""
        with self._lock:
            changed = self._offload_addr != (addr or "")
            self._offload_addr = addr or ""
            self._offload_expire = time.monotonic() + (
                self._sched_ttl_s if ttl_s is None else float(ttl_s))
        if changed:
            events.emit("offload.placement", engine=self.path,
                        service=addr or "")

    def offload_target(self):
        """The live placement address, or None (none set / lease
        lapsed)."""
        with self._lock:
            if not self._offload_addr:
                return None
            if time.monotonic() >= self._offload_expire:
                self._offload_addr = ""
                return None
            return self._offload_addr

    def compact_policy_fast(self) -> str:
        """Lock-free policy peek for the per-write admission path (the
        debt throttle keys its slope on whether a defer token is
        deliberately accumulating this debt). Expiry is NOT checked: a
        just-lapsed defer reads as defer until the next trigger-path
        compact_policy() call resets it — at most one extra lenient
        admission window, never a correctness issue."""
        return self._sched_policy  #: unguarded_ok racy admission peek of an atomically-assigned str; compact_policy() under the lock is authoritative

    def compaction_debt(self) -> dict:
        """Compaction-debt fold (ISSUE 10): what the scheduler, the
        beacon gauges, db.stats() and the admission throttle all read —
        L0 file count, debt bytes (L0 bytes + every level's over-budget
        overflow, i.e. the pending-cascade work), and the deferred-
        install depth still riding the pipeline pool."""
        with self._lock:
            over = 0
            for lv in self._levels:
                if self._levels[lv]:
                    over += max(0,
                                self._level_bytes(lv) - self._level_budget(lv))
            return {"l0_files": len(self._l0),
                    "debt_bytes": sum(s.data_bytes for s in self._l0) + over,
                    "pending_installs": sum(
                        1 for f in self._pending_installs if not f.done()),
                    "ceiling_files": self._sched_ceiling}

    def compact_debt_ratio(self) -> float:
        """L0 debt as a fraction of the hard ceiling — the admission
        throttle charges this on EVERY write, so it is a deliberately
        lock-free racy read (a one-file-stale ratio only shifts a delay
        by one write)."""
        return len(self._l0) / float(self._sched_ceiling)  #: unguarded_ok racy admission gauge: len() of a list the trigger path re-snapshots under its locks

    def _traced_compact(self, trigger: str) -> dict:
        """Run compact() as ONE traced background job (ISSUE 16): the
        compaction adopts the id the scheduler's token delivered (so the
        decision, the token apply and this merge share a timeline) or
        mints a local id when the trigger is engine-local. compact() is
        synchronous through its deferred-install drain, so finishing
        here covers the job through the installed SST."""
        from ..runtime.job_trace import JOB_TRACER

        with self._lock:
            token_job, self._sched_job = self._sched_job, ""
        jid = JOB_TRACER.begin("compact", job_id=token_job or None,
                               engine=self.path, pidx=self.opts.pidx)
        JOB_TRACER.note("engine.trigger", job_id=jid, trigger=trigger,
                        l0_files=len(self._l0))  #: unguarded_ok trace attr snapshot; compact() re-snapshots under its locks
        try:
            with JOB_TRACER.adopt(jid):
                stats = self.compact()
        except BaseException:
            JOB_TRACER.finish(jid, status="error")
            raise
        JOB_TRACER.finish(jid,
                          input_records=stats.get("input_records", 0),
                          output_records=stats.get("output_records", 0))
        return stats

    def _maybe_trigger_l0(self) -> bool:
        """Post-flush/ingest L0 trigger behind the scheduler gate
        (ISSUE 10). With no (or an expired) policy token this is exactly
        the old `len(l0) >= trigger -> compact()` — the byte-identical
        engine-local fallback a dead scheduler degrades to. A 'defer'
        token holds the elective trigger until the hard debt ceiling,
        where the engine-local trigger always wins; an 'urgent' token
        fires at half the normal threshold; an elective trigger defers
        while the per-node device gate is at its cap. -> True when a
        compaction actually ran (poke_compaction bounds its per-tick
        work on this)."""
        l0 = len(self._l0)  #: unguarded_ok racy trigger check: compact() re-snapshots under its locks; worst case is one early/late compaction
        policy, _, _ = self.compact_policy()
        if l0 >= self._sched_ceiling:
            # availability floor: the engine-local trigger overrides any
            # defer once debt hits the ceiling (a wedged/dead scheduler
            # can never stall compaction into a write cliff)
            if policy == "defer":
                self._c_sched_ceiling.increment()
            self._traced_compact("ceiling")
            return True
        if policy == "defer":
            if l0 >= self.opts.l0_compaction_trigger:
                self._c_sched_deferred.increment()
            return False
        if policy == "urgent":
            if l0 >= max(1, self.opts.l0_compaction_trigger // 2):
                self._c_sched_urgent.increment()
                self._traced_compact("urgent")
                return True
            return False
        if l0 >= self.opts.l0_compaction_trigger:
            if self.opts.backend == "tpu" and SCHED_GATE.at_cap():
                # the node's device lanes are saturated: hold this
                # elective merge (debt stays; the next flush, the
                # maintenance poke, or the ceiling retries) instead of
                # convoying the TPU lane
                self._c_sched_gate_deferred.increment()
                return False
            self._traced_compact("trigger")
            return True
        return False

    def poke_compaction(self) -> bool:
        """Idle retry of the L0 trigger gate (the replica maintenance
        timer calls this): debt a since-expired defer token or a
        since-freed device gate left above the trigger compacts without
        waiting for the next flush — an idle engine must not carry
        trigger-level read amplification forever. -> True when a
        compaction ran (the caller limits pokes per tick so one
        synchronous merge cannot stall its siblings' maintenance)."""
        return self._maybe_trigger_l0()

    def _bottommost(self, target_level: int) -> bool:
        """Tombstones may only drop when no lower level could hold the key."""
        deeper = any(self._levels.get(lv) for lv in  #: unguarded_ok level membership only changes under the compaction lock, which every caller holds; flush only touches L0
                     range(target_level + 1, self.opts.max_levels + 1))
        return not deeper

    def compact(self, bottommost: bool = None, now: int = None) -> dict:
        """L0 compaction: merge all L0 runs with the overlapping L1 files
        into range-partitioned L1 output — the CompactRange analogue and the
        TPU seam (reference executor: src/server/pegasus_server_impl.cpp:2814).
        Cascades size-triggered single-file compactions down the levels."""
        with self._compaction_lock:
            with self._lock:
                inputs = list(self._l0)
                nonzero = [s for s in inputs if s.n]
                if not nonzero:
                    return {"input_records": 0, "output_records": 0,
                            "dropped": 0}
                lo = min(s.min_key for s in nonzero)
                hi = max(s.max_key for s in nonzero)
                overlap = self._overlapping_locked(1, lo, hi)
            bm = self._bottommost(1) if bottommost is None else bottommost
            gated = self.opts.backend == "tpu"
            if gated:  # device-compaction concurrency accounting (ISSUE 10)
                SCHED_GATE.enter()
            try:
                stats = self._merge_to_level(inputs, overlap, target_level=1,
                                             bottommost=bm, now=now,
                                             deferred=True)
                self._maybe_cascade(now)
            finally:
                if gated:
                    SCHED_GATE.exit()
            self._drain_pending_installs()
            return stats

    def _overlapping_locked(self, level: int, lo: bytes, hi: bytes):  #: requires self._lock
        out = []
        for f in self._levels.get(level, []):
            if f.n == 0 or lo is None:
                out.append(f)
            elif not (f.max_key < lo or f.min_key > hi):
                out.append(f)
        return out

    def _maybe_cascade(self, now=None):
        """While a level exceeds its byte budget, push one file (plus the
        next level's overlap) down — bounded-input leveled compaction.
        Installs are DEFERRED (pipelined): the in-memory level swap is
        immediate (so the next victim selection sees the updated sizes)
        while output k's SST write + manifest + input unlinks ride the
        pipeline pool under the merge of k+1."""
        with self._compaction_lock:
            for lv in range(1, self.opts.max_levels):
                while True:
                    with self._lock:
                        files = list(self._levels.get(lv, []))
                        if (not files
                                or self._level_bytes(lv) <= self._level_budget(lv)):
                            break
                        cursor = self._compact_round.get(lv, 0) % len(files)
                        self._compact_round[lv] = cursor + 1
                        victim = files[cursor]
                        overlap = self._overlapping_locked(
                            lv + 1, victim.min_key, victim.max_key)
                    self._merge_to_level([victim], overlap, target_level=lv + 1,
                                         bottommost=self._bottommost(lv + 1),
                                         now=now, deferred=True)
            self._drain_pending_installs()

    def _level_bytes(self, lv: int) -> int:  #: requires self._lock
        return sum(s.data_bytes for s in self._levels.get(lv, []))

    def _level_budget(self, lv: int) -> int:
        return self.opts.level_base_bytes * (self.opts.level_size_ratio ** (lv - 1))

    def _sharded_mesh(self):  #: requires self._compaction_lock
        """Mesh for multi-chip manual compaction, or None when the engine
        should stay single-chip (knob off, or <2 devices visible)."""
        if self.opts.compaction_mesh is not None:
            mesh = self.opts.compaction_mesh
            return mesh if mesh.devices.size > 1 else None
        if not self.opts.sharded_compaction or self.opts.backend != "tpu":
            return None
        if self._resolved_mesh is _UNRESOLVED:
            try:
                import jax

                from ..parallel import make_mesh

                self._resolved_mesh = (make_mesh(len(jax.devices()))
                                       if len(jax.devices()) > 1 else None)
            except Exception as e:  # no backend: stay single-chip
                from ..runtime.lane_guard import LANE_GUARD

                # breaker=False: a missing/misconfigured mesh is an
                # environment condition, not evidence the device died
                LANE_GUARD.record_device_failure("mesh_resolve", repr(e),
                                                 breaker=False)
                print(f"[engine] sharded compaction unavailable: {e!r}",
                      flush=True)
                self._resolved_mesh = None
        return self._resolved_mesh

    def _merge_to_level(self, newer_files, older_files, target_level: int,
                        bottommost: bool, now=None, sharded: bool = False,
                        deferred: bool = False) -> dict:  #: requires self._compaction_lock
        """Merge newer_files (recency order) over older_files into
        target_level, splitting output at target_file_size_bytes.
        sharded=True (manual_compact only) routes through the multi-chip
        hash-sharded kernel when a >1-device mesh is available.
        deferred=True moves the install's disk work onto the pipeline
        pool (see _install_merge_deferred)."""
        inputs = list(newer_files) + list(older_files)
        input_blocks = [s.block() for s in inputs]
        mesh = self._sharded_mesh() if sharded else None
        opts = CompactOptions(
            now=now,
            pidx=self.opts.pidx,
            partition_mask=self.opts.partition_mask,
            bottommost=bottommost,
            default_ttl=self.opts.default_ttl,
            prefix_u32=self.opts.prefix_u32,
            backend=self.opts.backend,
            runs_sorted=True,
            user_ops=tuple(self.opts.user_ops),
        )
        from ..runtime.perf_counters import counters

        t0 = time.perf_counter()
        # compaction-offload placement (ISSUE 14): a cpu-only engine with
        # a live (when, where) lease ships this merge — elective trigger,
        # cascade or manual — to the rack's compaction service instead of
        # merging locally; the offload lane guard inside falls back to
        # the byte-identical local cpu merge on any service trouble
        offload_addr = (self.offload_target()
                        if mesh is None and self.opts.backend == "cpu"
                        else None)
        from ..runtime.job_trace import JOB_TRACER

        where = ("mesh" if mesh is not None
                 else "offload" if offload_addr else "local")
        with JOB_TRACER.hop("engine.merge", where=where, level=target_level,
                            inputs=len(inputs)):
            if mesh is not None:
                from ..parallel import sharded_compact_block

                result = sharded_compact_block(input_blocks, mesh, opts)
                counters.rate("engine.sharded_compaction_count").increment()
            elif offload_addr:
                from ..replication.compact_offload import offload_compact_blocks

                result = offload_compact_blocks(
                    input_blocks, opts, offload_addr,
                    tenant=f"{self.opts.pidx}@{os.path.basename(self.path)}")
                self._c_offload.increment()
            else:
                device_runs = None
                if self.opts.backend == "tpu":
                    # device-resident run cache: each SST packs+uploads once
                    # in its lifetime; this and every later compaction reads
                    # HBM directly
                    device_runs = [self._device_run_budgeted(s)
                                   for s in inputs]
                result = compact_blocks(input_blocks, opts,
                                        device_runs=device_runs)
        counters.rate("engine.compaction_completed_count").increment()
        counters.percentile("engine.compaction_s").set(time.perf_counter() - t0)
        self._install_merge_output(newer_files, older_files, result.block,
                                   target_level, deferred=deferred)
        return result.stats

    def _install_merge_output(self, newer_files, older_files, out_block,
                              target_level: int,
                              deferred: bool = False) -> None:  #: requires self._compaction_lock
        """Write + atomically swap a merge's output over its inputs —
        shared by _merge_to_level and the node-level batched compaction
        (replica_stub.batched_manual_compact). Caller holds the engine's
        compaction lock. deferred=True swaps in memory immediately and
        moves the disk work onto the pipeline pool."""
        from ..ops.pipeline import pipeline_depth

        out_blocks = _split_block(out_block, self.opts.target_file_size_bytes)
        inputs = list(newer_files) + list(older_files)
        if deferred and pipeline_depth() > 1:
            self._install_merge_deferred(inputs, out_blocks, target_level)
            return
        new_ssts = []
        for ob in out_blocks:
            with self._lock:
                path = os.path.join(self.path, self._alloc_file_locked())
            write_sst(path, ob, {"level": target_level,
                                 "last_flushed_decree": self._durable_decree},  #: unguarded_ok monotone watermark snapshot; the manifest (written under the lock) is authoritative
                      compression=self.opts.compression)
            sst = SSTable(path)
            sst._block = ob  # already in memory: skip the disk re-read
            # compaction output stays device-resident for its NEXT merge
            self._device_run_budgeted(sst)
            new_ssts.append(sst)
        with self._lock:
            self._swap_levels_locked(inputs, new_ssts, target_level)
            self._write_manifest_locked()
        for s in inputs:
            # keep the loaded block cached: a reader that snapshotted this
            # SSTable before we unlink must not re-read the dead path
            # (ADVICE r1 medium); the object drops with its last reference.
            # Its device columns are released NOW: the budget must see the
            # HBM back before the object's last reference dies.
            self._release_device_run(s)
            try:
                os.unlink(s.path)
            except OSError:
                pass

    def _swap_levels_locked(self, inputs, new_ssts, target_level: int):  #: requires self._lock
        """Swap the new files in and every input file out atomically —
        inputs may come from L0 and any level (manual compact); readers
        that snapshotted before this keep their (cached) SSTables."""
        gone = set(id(f) for f in inputs)
        level = [f for f in self._levels.get(target_level, [])
                 if id(f) not in gone]
        level.extend(new_ssts)
        level.sort(key=lambda s: s.min_key or b"")
        self._levels[target_level] = level
        self._l0 = [f for f in self._l0 if id(f) not in gone]
        for lv in list(self._levels):
            if lv != target_level:
                self._levels[lv] = [f for f in self._levels[lv]
                                    if id(f) not in gone]

    def _install_merge_deferred(self, inputs, out_blocks,
                                target_level: int) -> None:  #: requires self._compaction_lock
        """Pipelined install: swap the outputs into the level structure
        NOW (in-memory SSTables serving reads from their cached blocks)
        and move the disk work — write_sst, the device-residency prime,
        the manifest write and the input unlinks — onto the pipeline
        pool, so the NEXT level's merge overlaps this output's write-out.

        Durability invariant: the on-disk manifest only ever references
        fully-written files (_write_manifest_locked defers while any live
        SST is off disk), and inputs are unlinked only after a manifest
        that no longer references them has landed. A crash inside the
        window recovers to the exact pre-merge on-disk state."""
        from ..ops.pipeline import submit_install

        meta = {"level": target_level,
                "last_flushed_decree": self._durable_decree}  #: unguarded_ok monotone watermark snapshot; the manifest (written under the lock) is authoritative
        new_ssts = []
        for ob in out_blocks:
            with self._lock:
                path = os.path.join(self.path, self._alloc_file_locked())
            new_ssts.append(SSTable.from_block(path, ob, meta))
        with self._lock:
            self._swap_levels_locked(inputs, new_ssts, target_level)
            self._manifest_dirty = True
            self._pending_unlinks.extend(inputs)
        for s in inputs:
            # HBM back under the budget before the next merge wants it
            self._release_device_run(s)
        fut = submit_install(self._deferred_install_job, new_ssts)
        with self._lock:
            self._pending_installs = [
                f for f in self._pending_installs if not f.done()]
            self._pending_installs.append(fut)

    def _deferred_install_job(self, new_ssts) -> None:
        """Pool side of a deferred install: land the output files, then
        (when every live SST is on disk) write the manifest and unlink
        the consumed inputs. Device-residency primes go back through
        _prime_async (fire-and-forget): this job must only ever block on
        DISK, so a wedged device can never hang the install drain.
        Runs under the compaction job's adopted context (the pipeline
        pool carries it), so the install hop lands in the SAME timeline
        as the trigger and merge that produced these files."""
        from ..runtime.job_trace import JOB_TRACER

        try:
            with JOB_TRACER.hop("engine.install", ssts=len(new_ssts)):
                for sst in new_ssts:
                    with self._lock:
                        if sst._device_retired:
                            # already consumed as a LATER merge's input
                            # before ever landing: its data is superseded
                            # and nothing references the path — writing it
                            # now would only recreate a file after its
                            # queued unlink ran, leaking an orphan SST
                            # forever
                            sst._on_disk = True
                            continue
                    write_sst(sst.path, sst.block(), sst.meta,
                              compression=self.opts.compression,
                              bloom=(sst.header["bloom"],
                                     sst.header["bloom_log2m"]))
                    with self._lock:
                        sst._on_disk = True
                    self._prime_async(sst)
        finally:
            self._flush_deferred_state()

    def _flush_deferred_state(self) -> None:
        """Write the deferred manifest once every live SST is on disk,
        then unlink consumed inputs it no longer references. Only inputs
        whose own install job has settled (_on_disk) unlink now — a
        consumed-before-landing output stays queued until its job marks
        it, so an in-flight write_sst can never recreate the path after
        the unlink (the job's finally re-runs this to finish the queue)."""
        unlinks = []
        with self._lock:
            if self._manifest_dirty:
                self._write_manifest_locked()
            if not self._manifest_dirty:
                unlinks = [s for s in self._pending_unlinks if s._on_disk]
                self._pending_unlinks = [
                    s for s in self._pending_unlinks if not s._on_disk]
        for s in unlinks:
            try:
                os.unlink(s.path)
            except OSError:
                pass

    def _settle_installs(self) -> None:
        """Light install settle: wait for the CURRENTLY queued install
        futures and flush the deferred manifest, without taking the
        compaction lock (no repair pass — a failed worker's rewrite
        happens in the next full drain). Used by flush(), which must not
        serialize behind an entire in-flight compaction cascade."""
        with self._lock:
            futures = list(self._pending_installs)
        for f in futures:
            f.wait()
        self._flush_deferred_state()

    def _drain_pending_installs(self) -> None:
        """Synchronize with the pipeline pool: wait for in-flight install
        jobs, synchronously rewrite any file a failed worker left
        unwritten (the manifest never referenced it — see the invariant
        in _install_merge_deferred), and flush the deferred manifest +
        unlinks. Public entry points call this so the engine's on-disk
        state is settled when they return. Runs under the compaction
        lock: install jobs are only submitted while it is held, so after
        the waits below no worker can be writing a file the repair pass
        would also write."""
        with self._compaction_lock:
            with self._lock:
                futures, self._pending_installs = self._pending_installs, []
            for f in futures:
                f.wait()
            with self._lock:
                missing = [s for s in self._all_ssts_locked()
                           if not s._on_disk]
            for s in missing:
                # repair pass: a failed deferred write retries once
                # inline; a second failure raises to the caller like a
                # synchronous install would, with the on-disk state
                # still pre-merge
                write_sst(s.path, s.block(), s.meta,
                          compression=self.opts.compression,
                          bloom=(s.header["bloom"],
                                 s.header["bloom_log2m"]))
                with self._lock:
                    s._on_disk = True
            self._flush_deferred_state()
            with self._lock:
                # no install job is in flight any more, so whatever is
                # still queued (dead consumed-before-landing outputs
                # whose job died before marking them) can go now
                leftover, self._pending_unlinks = self._pending_unlinks, []
                settled = not self._manifest_dirty
            if settled:
                for s in leftover:
                    try:
                        os.unlink(s.path)
                    except OSError:
                        pass
            else:
                with self._lock:
                    self._pending_unlinks = leftover + self._pending_unlinks

    def manual_compact(self, bottommost: bool = True, now: int = None,
                       target_level: int = None) -> dict:
        """Full compaction: everything merged into one run at target_level
        (default: the bottommost configured level). Its own traced
        "compact" job (trigger=manual) — nested under an already-active
        job this degrades to a hop, per JobTracer.job()."""
        from ..runtime.job_trace import JOB_TRACER
        with JOB_TRACER.job("compact", engine=self.path,
                            pidx=self.opts.pidx, trigger="manual"):
            return self._manual_compact_traced(bottommost, now, target_level)

    def _manual_compact_traced(self, bottommost, now, target_level) -> dict:
        from ..runtime.tracing import COMPACT_TRACER

        self.flush()
        tl = target_level or self.opts.max_levels
        stats = {"input_records": 0, "output_records": 0, "dropped": 0}
        with self._compaction_lock:
            with self._lock:
                newer = list(self._l0)
                for lv in sorted(self._levels):
                    if lv < tl:
                        newer.extend(self._levels.get(lv, []))
                older = list(self._levels.get(tl, []))
            if newer or older:
                # inputs stay visible to readers until _merge_to_level swaps
                # the output in; a failed merge leaves the levels untouched.
                # The session records the per-stage breakdown (pack / h2d /
                # device / gather / sst_write) into the stats the manual-
                # compact service and shell report.
                gated = self.opts.backend == "tpu"
                if gated:  # device-compaction concurrency accounting
                    SCHED_GATE.enter()
                try:
                    with COMPACT_TRACER.session() as sess:
                        stats = self._merge_to_level(newer, older,
                                                     target_level=tl,
                                                     bottommost=bottommost,
                                                     now=now, sharded=True)
                finally:
                    if gated:
                        SCHED_GATE.exit()
                stats = dict(stats, trace=sess.summary())
        with self._lock:
            # under the engine lock: concurrent writers update _meta's
            # decree key through write()/write_batch() (caught by
            # tools/analyze lock_discipline)
            self._meta[META_LAST_MANUAL_COMPACT_FINISH_TIME] = \
                int(time.time())
            self._write_manifest_locked()
        return stats

    def install_ingested_block(self, block: KVBlock) -> None:
        """Bulk-load install: a sorted, deduped block becomes a fresh L0 run
        (the IngestExternalFile seam, reference rocksdb_wrapper.cpp:185).
        Like RocksDB's default IngestExternalFile, the ingested data gets
        the NEWEST position (a fresh sequence number): it shadows any
        existing version of the same keys, at every level."""
        self.flush()  # RocksDB ingest flushes first so the fresh seqno wins
        with self._lock:
            path = os.path.join(self.path, self._alloc_file_locked())
        write_sst(path, block, {"level": 0, "ingested": True,
                                "last_flushed_decree": self._durable_decree},  #: unguarded_ok monotone watermark snapshot; the manifest (written under the lock) is authoritative
                  compression=self.opts.compression)
        with self._lock:
            self._l0.insert(0, SSTable(path))
            self._write_manifest_locked()
        self._maybe_trigger_l0()

    # ------------------------------------------------------------- checkpoint

    def checkpoint(self, dest_dir: str, flush: bool = True) -> int:
        """Hardlink-based consistent snapshot into dest_dir
        (reference: sync_checkpoint / copy_checkpoint_to_dir_unsafe,
        src/server/pegasus_server_impl.cpp:1666,1863). Returns the decree.
        flush=False snapshots only the durable state (the reference's
        async/no-flush variant)."""
        if flush:
            self.flush()
        with self._lock:
            os.makedirs(dest_dir, exist_ok=True)
            for sst in self._all_ssts_locked():
                dst = os.path.join(dest_dir, os.path.basename(sst.path))
                if os.path.exists(dst):
                    continue
                try:
                    os.link(sst.path, dst)
                except OSError:
                    if sst._block is not None:
                        # a deferred install's output that has not landed
                        # yet (or is mid-write): materialize it into the
                        # checkpoint from its cached block — the snapshot
                        # is self-contained without waiting on (or
                        # excluding) in-flight compactions
                        write_sst(dst, sst._block, sst.meta,
                                  compression=self.opts.compression,
                                  bloom=(sst.header.get("bloom", ""),
                                         sst.header.get("bloom_log2m", 0)))
                    else:
                        shutil.copy2(sst.path, dst)
            with open(os.path.join(dest_dir, MANIFEST), "w") as f:
                json.dump(self._manifest_dict_locked(), f)
            return self.last_durable_decree()

    def sync_checkpoint(self, flush: bool = True) -> int:
        """Create <path>/checkpoint.{decree}; GC old ones. Returns decree."""
        with self.checkpoint_lock:
            decree = self.checkpoint(os.path.join(
                self.path, f"{CHECKPOINT_PREFIX}tmp"), flush=flush)
            final = os.path.join(self.path, f"{CHECKPOINT_PREFIX}{decree}")
            tmp = os.path.join(self.path, f"{CHECKPOINT_PREFIX}tmp")
            if os.path.exists(final):
                shutil.rmtree(tmp)
            else:
                os.replace(tmp, final)
            self.gc_checkpoints()
            return decree

    def async_checkpoint(self):
        """Background NO-FLUSH checkpoint (the reference's async variant,
        pegasus_server_impl.cpp:1744: snapshot durable state only, never
        force a flush). Returns the Thread, or None when the latest
        checkpoint already covers the durable decree or one is running."""
        existing = self.list_checkpoints()
        if existing and existing[-1] >= self.last_durable_decree():
            return None
        if not self.checkpoint_lock.acquire(blocking=False):
            return None  # a checkpoint is already in flight
        self.checkpoint_lock.release()
        from ..runtime.tasking import spawn_thread

        t = spawn_thread(self.sync_checkpoint, flush=False, daemon=True)
        return t

    def list_checkpoints(self) -> list:
        """Sorted decrees of existing checkpoint.{decree} dirs
        (reference parse_checkpoints, pegasus_server_impl.cpp:81)."""
        out = []
        for name in os.listdir(self.path):
            if name.startswith(CHECKPOINT_PREFIX):
                suffix = name[len(CHECKPOINT_PREFIX):]
                if suffix.isdigit():
                    out.append(int(suffix))
        return sorted(out)

    def gc_checkpoints(self) -> int:
        """Drop checkpoints beyond the count/time reserves
        (reference gc_checkpoints, pegasus_server_impl.cpp:120-253)."""
        with self.checkpoint_lock:
            return self._gc_checkpoints_locked()

    def _gc_checkpoints_locked(self) -> int:
        decrees = self.list_checkpoints()
        keep_min = max(1, self.opts.checkpoint_reserve_min_count)
        dropped = 0
        now = time.time()
        pinned = self._pinned_decrees_locked()
        for d in decrees[:-keep_min] if len(decrees) > keep_min else []:
            if d in pinned:
                # an active learn streams this checkpoint's blocks
                # lock-free; dropping the dir would dangle its fetches
                continue
            cdir = os.path.join(self.path, f"{CHECKPOINT_PREFIX}{d}")
            if self.opts.checkpoint_reserve_time_seconds > 0:
                age = now - os.path.getmtime(cdir)
                if age < self.opts.checkpoint_reserve_time_seconds:
                    continue
            shutil.rmtree(cdir, ignore_errors=True)
            dropped += 1
        return dropped

    # ------------------------------------------------- learn-ship pinning

    def pin_checkpoint(self, decree: int, ttl_s: float = 600.0) -> int:
        """Hold checkpoint.{decree} out of gc_checkpoints for one learn
        (ISSUE 13). Each pin is an independent TTL LEASE identified by
        the returned token: renew/unpin act on exactly that lease, so an
        expired learner's reap can never release a LIVE learner's pin on
        the same decree. Fetch activity renews; expiry releases —
        learner death bounds the hold, not learn duration."""
        with self.checkpoint_lock:
            self._pin_token += 1
            token = self._pin_token
            self._ckpt_pins.setdefault(decree, {})[token] = \
                time.monotonic() + ttl_s
            return token

    def renew_checkpoint_pin(self, decree: int, token: int,
                             ttl_s: float) -> None:
        with self.checkpoint_lock:
            pins = self._ckpt_pins.get(decree)
            if pins and token in pins:
                pins[token] = time.monotonic() + ttl_s

    def unpin_checkpoint(self, decree: int, token: int) -> None:
        with self.checkpoint_lock:
            pins = self._ckpt_pins.get(decree)
            if pins:
                pins.pop(token, None)
            if not pins:
                self._ckpt_pins.pop(decree, None)
                self._ckpt_digests.pop(decree, None)

    def _pinned_decrees_locked(self) -> set:  #: requires self.checkpoint_lock
        now = time.monotonic()
        for d in list(self._ckpt_pins):
            live = {t: e for t, e in self._ckpt_pins[d].items() if e > now}
            if live:
                self._ckpt_pins[d] = live
            else:
                self._ckpt_pins.pop(d)
                self._ckpt_digests.pop(d, None)
        return set(self._ckpt_pins)

    def pinned_checkpoints(self) -> dict:
        """{decree: active pin count} (learn-status surface)."""
        with self.checkpoint_lock:
            self._pinned_decrees_locked()
            return {d: len(p) for d, p in self._ckpt_pins.items()}

    def checkpoint_digest(self, decree: int) -> dict:
        """Decree-anchored digest of checkpoint.{decree}'s contents (the
        PR 8 state_digest fold over a read-only engine opened on the
        checkpoint dir) — what a shipped replica must reproduce from its
        staged blocks before swapping them in. Cached per decree, with
        the TTL `now` anchor and ownership mask chosen at first
        computation, so every learner of one checkpoint compares against
        the same instant. Caller must hold a pin (the dir must not GC
        mid-scan)."""
        from ..base.utils import epoch_now

        with self.checkpoint_lock:
            hit = self._ckpt_digests.get(decree)
            if hit is not None:
                return dict(hit)
            cdir = self.get_checkpoint_dir(decree)
        # the scan runs OUTSIDE the checkpoint lock: a multi-second fold
        # must not stall the maintenance timer's sync_checkpoint. Racing
        # computers produce byte-identical folds apart from the `now`
        # anchor; setdefault keeps whichever landed first coherent.
        ver = LsmEngine(cdir, EngineOptions(
            backend="cpu", pidx=self.opts.pidx,
            prefix_u32=self.opts.prefix_u32))
        try:
            d = ver.state_digest(now=epoch_now(),
                                 pmask=self.opts.partition_mask)
        finally:
            ver.close()
        entry = {"digest": d["digest"], "records": d["records"],
                 "now": d["now"], "pmask": self.opts.partition_mask}
        with self.checkpoint_lock:
            return dict(self._ckpt_digests.setdefault(decree, entry))

    def get_checkpoint_dir(self, decree: int = None) -> str:
        """Latest (or specific) checkpoint dir for learner shipping
        (reference get_checkpoint, pegasus_server_impl.cpp:1941)."""
        decrees = self.list_checkpoints()
        if not decrees:
            raise FileNotFoundError("no checkpoints")
        d = decree if decree is not None else decrees[-1]
        return os.path.join(self.path, f"{CHECKPOINT_PREFIX}{d}")

    @classmethod
    def apply_checkpoint(cls, checkpoint_dir: str, dest_path: str,
                         options: "EngineOptions" = None) -> "LsmEngine":
        """Replace dest_path's data with the checkpoint and open it
        (reference storage_apply_checkpoint, pegasus_server_impl.cpp:1970)."""
        if os.path.exists(dest_path):
            shutil.rmtree(dest_path)
        os.makedirs(dest_path)
        for name in os.listdir(checkpoint_dir):
            src = os.path.join(checkpoint_dir, name)
            if os.path.isfile(src):
                try:
                    os.link(src, os.path.join(dest_path, name))
                except OSError:
                    shutil.copy2(src, os.path.join(dest_path, name))
        return cls(dest_path, options)

    # -------------------------------------------------------------- manifest

    def _all_ssts_locked(self):  #: requires self._lock
        out = list(self._l0)
        for lv in sorted(self._levels):
            out.extend(self._levels[lv])
        return out

    def _alloc_file_locked(self) -> str:  #: requires self._lock
        name = f"{self._next_file:06d}.sst"
        self._next_file += 1
        return name

    def _manifest_dict_locked(self) -> dict:  #: requires self._lock
        meta = {k: v for k, v in self._meta.items()}
        meta[META_LAST_FLUSHED_DECREE] = self._durable_decree
        return {
            "next_file": self._next_file,
            "l0": [os.path.basename(s.path) for s in self._l0],
            "levels": {str(lv): [os.path.basename(s.path) for s in fs]
                       for lv, fs in self._levels.items()},
            "meta": meta,
        }

    def _write_manifest_locked(self):  #: requires self._lock
        if any(not s._on_disk for s in self._all_ssts_locked()):
            # deferred installs in flight: the manifest must never
            # reference a file that has not fully landed — the last
            # completing install job (or a drain) writes it
            self._manifest_dirty = True
            return
        data = self._manifest_dict_locked()
        tmp = os.path.join(self.path, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, MANIFEST))
        self._manifest_dirty = False  # only after the replace landed
        self._durable_meta = dict(data["meta"])  #: guarded_by self._lock

    def _load_manifest(self):  #: unguarded_ok construction-time: called only from __init__, before the engine is published to any other thread
        mpath = os.path.join(self.path, MANIFEST)
        if not os.path.exists(mpath):
            self._meta = {META_DATA_VERSION: self.opts.data_version}
            self._durable_meta = {}
            # repair path: adopt orphan SSTs (a replica dir from another
            # build / a manifest lost to a crash) into their header level,
            # newest file id first — the upgrade tier's "new server opens an
            # old dir" requirement (reference: rocksdb repair semantics)
            orphans = sorted(f for f in os.listdir(self.path)
                             if f.endswith(".sst"))
            for fname in orphans:
                try:
                    sst = SSTable(os.path.join(self.path, fname))
                except (ValueError, KeyError, OSError) as e:
                    print(f"[engine] skipping unreadable orphan {fname}: "
                          f"{e!r}", flush=True)
                    continue
                lv = int(sst.meta.get("level", 0))
                if lv <= 0:
                    self._l0.insert(0, sst)
                else:
                    self._levels.setdefault(lv, []).append(sst)
                self._durable_decree = max(
                    self._durable_decree,
                    int(sst.meta.get("last_flushed_decree", 0)))
                num = os.path.splitext(fname)[0]
                if num.isdigit():
                    self._next_file = max(self._next_file, int(num) + 1)
            if orphans:
                for lv in self._levels:
                    self._levels[lv].sort(key=lambda s: s.min_key or b"")
                self._meta[META_LAST_FLUSHED_DECREE] = self._durable_decree
                self._last_committed_decree = self._durable_decree
            self._write_manifest_locked()
            return
        with open(mpath) as f:
            m = json.load(f)
        self._next_file = m["next_file"]
        self._l0 = [SSTable(os.path.join(self.path, n)) for n in m["l0"]]
        self._levels = {int(lv): [SSTable(os.path.join(self.path, n)) for n in fs]
                        for lv, fs in m["levels"].items()}
        self._meta = dict(m["meta"])
        self._durable_meta = dict(m["meta"])
        self._durable_decree = int(self._meta.get(META_LAST_FLUSHED_DECREE, 0))
        self._last_committed_decree = self._durable_decree
        self._mem.last_decree = self._last_committed_decree

    def close(self):
        self._drain_pending_installs()
        HBM_GAUGES.drop(self)

    # ------------------------------------------------------------- statistics

    def device_resident_bytes(self) -> int:
        """HBM bytes pinned by this engine's resident runs — a lock-free
        racy read for attribution paths (beacon refresh, ISSUE 18) that
        must never take the engine lock."""
        return self._device_cache_used  #: unguarded_ok racy gauge read

    def stats(self) -> dict:
        with self._lock:
            debt = self.compaction_debt()  # RLock: nested re-acquire
            policy, reasons, _ = self.compact_policy()
            return {
                "compact_debt_bytes": debt["debt_bytes"],
                "pending_installs": debt["pending_installs"],
                "compact_ceiling_files": debt["ceiling_files"],
                "compact_policy": policy,
                "compact_policy_reasons": reasons,
                "compact_offload": self._offload_addr,
                "memtable_records": len(self._mem),
                "memtable_bytes": self._mem.approximate_bytes,
                "immutable_memtables": len(self._imm),
                "l0_files": len(self._l0),
                "level_files": {lv: len(fs) for lv, fs in self._levels.items() if fs},
                "level_bytes": {lv: self._level_bytes(lv)
                                for lv in self._levels if self._levels[lv]},
                "total_sst_records": sum(s.n for s in self._all_ssts_locked()),
                "last_committed_decree": self._last_committed_decree,
                "last_durable_decree": self.last_durable_decree(),
                "device_resident_bytes": self._device_cache_used,
                "device_resident_ssts": self._device_resident_ssts,
                "read_hot": self._read_hot,
            }


def _split_block(block: KVBlock, target_bytes: int) -> list:
    """Split a sorted block into chunks of ~target_bytes (key+value arenas),
    preserving order; every output chunk holds a disjoint key range."""
    if block.n == 0:
        return [block]
    total = block.key_bytes_total + block.val_bytes_total
    if total <= target_bytes:
        return [block]
    sizes = block.key_len.astype(np.int64) + block.val_len.astype(np.int64)
    cum = np.cumsum(sizes)
    bounds = []
    start = 0
    base = 0
    for _ in range(int(total // target_bytes) + 1):
        cut = np.searchsorted(cum, base + target_bytes, side="left") + 1
        cut = min(int(cut), block.n)
        if cut <= start:
            cut = start + 1
        bounds.append((start, cut))
        if cut >= block.n:
            break
        start = cut
        base = int(cum[cut - 1])
    return [block.gather(np.arange(s, e, dtype=np.int64)) for s, e in bounds]
