"""Per-table write throttling (reference: rDSN throttling_controller
consumed through the `replica.write_throttling[_by_size]` app-envs; the
pegasus surface is the env keys plus the delay/reject perf counters the
collector aggregates, src/server/info_collector.h:73-81).

Env value grammar (the reference's parse_from_env):

    "20000*delay*100"                   delay 100ms once >20000 units/s
    "20000*delay*100,30000*reject*10"   ...and reject (after a 10ms pause)
                                        once >30000 units/s
    "30000"                             bare number: reject above it

Units are requests for `replica.write_throttling`, request-body bytes for
`replica.write_throttling_by_size`. Accounting is a per-second tumbling
window, like the reference's token-refresh-per-second controller.
"""

import threading
import time


class ThrottleReject(Exception):
    """Raised when the reject threshold fires (mapped to ERR_BUSY)."""


class ThrottlingController:
    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.delay_units = 0
        self.delay_ms = 0
        self.reject_units = 0
        self.reject_delay_ms = 0
        self.env_value = ""
        self._window_start = 0
        self._window_units = 0
        # the counters the reference publishes per replica
        self.delayed_count = 0
        self.rejected_count = 0

    def parse_from_env(self, value: str) -> bool:
        """Apply an env string; empty disables. -> False on a malformed
        value (the old setting stays, like the reference's validator)."""
        value = (value or "").strip()
        delay_units = delay_ms = reject_units = reject_delay_ms = 0
        if value:
            try:
                for tok in value.split(","):
                    parts = tok.strip().split("*")
                    if len(parts) == 1:
                        reject_units, reject_delay_ms = int(parts[0]), 0
                    elif len(parts) == 3 and parts[1] == "delay":
                        delay_units, delay_ms = int(parts[0]), int(parts[2])
                    elif len(parts) == 3 and parts[1] == "reject":
                        reject_units = int(parts[0])
                        reject_delay_ms = int(parts[2])
                    else:
                        return False
                    if min(delay_units, delay_ms,
                           reject_units, reject_delay_ms) < 0:
                        return False
            except ValueError:
                return False
        with self._lock:
            self.env_value = value
            self.enabled = bool(value)
            self.delay_units, self.delay_ms = delay_units, delay_ms
            self.reject_units = reject_units
            self.reject_delay_ms = reject_delay_ms
        return True

    def consume(self, units: int = 1) -> None:
        """Charge one request. Sleeps for a delay-throttle; raises
        ThrottleReject for a reject-throttle (after its pause)."""
        if not self.enabled:
            return
        with self._lock:
            now = int(time.monotonic())
            if now != self._window_start:
                self._window_start = now
                self._window_units = 0
            self._window_units += units
            total = self._window_units
            reject = self.reject_units and total > self.reject_units
            delay = self.delay_units and total > self.delay_units
            if reject:
                self.rejected_count += 1
                pause = self.reject_delay_ms / 1000.0
            elif delay:
                self.delayed_count += 1
                pause = self.delay_ms / 1000.0
        if reject:
            if pause:
                time.sleep(pause)
            raise ThrottleReject(
                f"write throttled: {total} units/s > {self.reject_units}")
        if delay and pause:
            time.sleep(pause)
