"""Per-table write throttling (reference: rDSN throttling_controller
consumed through the `replica.write_throttling[_by_size]` app-envs; the
pegasus surface is the env keys plus the delay/reject perf counters the
collector aggregates, src/server/info_collector.h:73-81).

Env value grammar (the reference's parse_from_env):

    "20000*delay*100"                   delay 100ms once >20000 units/s
    "20000*delay*100,30000*reject*10"   ...and reject (after a 10ms pause)
                                        once >30000 units/s
    "30000"                             bare number: reject above it

Units are requests for `replica.write_throttling`, request-body bytes for
`replica.write_throttling_by_size`. Accounting is a per-second tumbling
window, like the reference's token-refresh-per-second controller.

ISSUE 10 adds ``DebtThrottle``: compaction-debt-driven admission control.
The env throttles above bound *rates* an operator configured; the debt
throttle bounds the *engine's* backlog — as L0 debt approaches the hard
ceiling where the engine-local trigger compacts inline on the writer
thread (the stall cliff), writes pick up a graduated, metric-visible
delay so the cliff becomes a measured slope instead of an accident.
"""

import os
import threading
import time


class ThrottleReject(Exception):
    """Raised when the reject threshold fires (mapped to ERR_BUSY)."""


class ThrottlingController:
    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self.delay_units = 0
        self.delay_ms = 0
        self.reject_units = 0
        self.reject_delay_ms = 0
        self.env_value = ""
        self._window_start = 0
        self._window_units = 0
        # the counters the reference publishes per replica
        self.delayed_count = 0
        self.rejected_count = 0

    def parse_from_env(self, value: str) -> bool:
        """Apply an env string; empty disables. -> False on a malformed
        value (the old setting stays, like the reference's validator)."""
        value = (value or "").strip()
        delay_units = delay_ms = reject_units = reject_delay_ms = 0
        if value:
            try:
                for tok in value.split(","):
                    parts = tok.strip().split("*")
                    if len(parts) == 1:
                        reject_units, reject_delay_ms = int(parts[0]), 0
                    elif len(parts) == 3 and parts[1] == "delay":
                        delay_units, delay_ms = int(parts[0]), int(parts[2])
                    elif len(parts) == 3 and parts[1] == "reject":
                        reject_units = int(parts[0])
                        reject_delay_ms = int(parts[2])
                    else:
                        return False
                    if min(delay_units, delay_ms,
                           reject_units, reject_delay_ms) < 0:
                        return False
            except ValueError:
                return False
        with self._lock:
            self.env_value = value
            self.enabled = bool(value)
            self.delay_units, self.delay_ms = delay_units, delay_ms
            self.reject_units = reject_units
            self.reject_delay_ms = reject_delay_ms
        return True

    def consume(self, units: int = 1) -> None:
        """Charge one request. Sleeps for a delay-throttle; raises
        ThrottleReject for a reject-throttle (after its pause)."""
        if not self.enabled:
            return
        with self._lock:
            now = int(time.monotonic())
            if now != self._window_start:
                self._window_start = now
                self._window_units = 0
            self._window_units += units
            total = self._window_units
            reject = self.reject_units and total > self.reject_units
            delay = self.delay_units and total > self.delay_units
            if reject:
                self.rejected_count += 1
                pause = self.reject_delay_ms / 1000.0
            elif delay:
                self.delayed_count += 1
                pause = self.delay_ms / 1000.0
        if reject:
            if pause:
                time.sleep(pause)
            raise ThrottleReject(
                f"write throttled: {total} units/s > {self.reject_units}")
        if delay and pause:
            time.sleep(pause)


class DebtThrottle:
    """Compaction-debt admission control (ISSUE 10): charge every write
    against the engine's L0-debt ratio (debt files / hard ceiling, a
    lock-free racy read — see LsmEngine.compact_debt_ratio) and apply
    graduated backpressure BEFORE the engine hits the stall cliff where
    the ceiling trigger compacts inline on the writer thread:

      ratio < soft                 free
      soft <= ratio < 1.0          delay scaling linearly up to max_ms
      ratio >= reject (if set)     ThrottleReject -> ERR_BUSY

    Knobs (resolved once at construction): PEGASUS_SCHED_THROTTLE
    (``0`` disables — byte-identical admission to the pre-throttle
    engine), PEGASUS_SCHED_THROTTLE_SOFT (ratio where delay starts),
    PEGASUS_SCHED_THROTTLE_MAX_MS (delay at the ceiling edge),
    PEGASUS_SCHED_THROTTLE_REJECT (ratio that rejects; 0 = never).
    Counters: engine.throttle.debt_delay_count / debt_reject_count
    rates + the engine.throttle.debt_delay_ms percentile, plus the
    monotone engine.throttle.debt_delay_ms_total rate whose .total() is
    the process-global delay-ms sum (ISSUE 18: must equal the sum of
    per-table ledger attributions — see tests/test_table_stats.py)."""

    def __init__(self, engine):
        from ..runtime.perf_counters import counters

        self.engine = engine
        self.enabled = os.environ.get("PEGASUS_SCHED_THROTTLE", "1") != "0"
        self.soft = float(os.environ.get("PEGASUS_SCHED_THROTTLE_SOFT",
                                         "0.5"))
        self.max_ms = float(os.environ.get("PEGASUS_SCHED_THROTTLE_MAX_MS",
                                           "50"))
        self.reject_ratio = float(os.environ.get(
            "PEGASUS_SCHED_THROTTLE_REJECT", "0"))
        # plain monotone counters for tests; the registry rates are the
        # operator surface (resolved once — the admission path is per-write)
        self.delayed_count = 0
        self.rejected_count = 0
        self._c_delay = counters.rate("engine.throttle.debt_delay_count")
        self._c_reject = counters.rate("engine.throttle.debt_reject_count")
        self._c_delay_ms = counters.percentile(
            "engine.throttle.debt_delay_ms")
        self._c_delay_ms_total = counters.rate(
            "engine.throttle.debt_delay_ms_total")
        # per-partition attribution (ISSUE 18): the monotone ms sum this
        # one throttle has charged, and an optional per-table ledger the
        # host wires up (set_table_name) so every delayed ms lands on a
        # tenant key at the moment it is charged
        self.delay_ms_total = 0.0
        self.ledger = None
        # flight-recorder edge detection: ONE event per engage/disengage
        # transition, not one per delayed write. Deliberately lock-free
        # (this sits on the per-write admission path); a race can at
        # worst duplicate a transition event, never lose a delay.
        self._engaged = False

    # a DEFER token means the scheduler is deliberately accumulating
    # this debt (a read-hot partition holding its compaction): charging
    # the normal slope there would collapse write throughput as a side
    # effect of a read-side optimization. The throttle instead engages
    # only in the last eighth before the ceiling cliff (the same 7/8
    # convention as the HBM read-hot headroom) — close enough that the
    # imminent ceiling-override compaction still gets its measured
    # slowdown, far enough that the defer window itself is free.
    DEFER_SOFT = 0.875

    def consume(self) -> float:
        """Charge one write; sleeps for the graduated delay, raises
        ThrottleReject past the reject ratio. Called OUTSIDE any engine
        lock (the sleep must never convoy other writers). Returns the
        delay in ms (0.0 on the free paths) so callers can attribute the
        stall to the partition that paid it."""
        if not self.enabled:
            return 0.0
        ratio = self.engine.compact_debt_ratio()
        soft = self.soft
        if ratio >= soft \
                and self.engine.compact_policy_fast() == "defer":
            soft = max(soft, self.DEFER_SOFT)
        if ratio < soft:
            if self._engaged:
                self._engaged = False
                from ..runtime import events

                events.emit("throttle.disengage", ratio=round(ratio, 3))
            return 0.0
        if not self._engaged:
            self._engaged = True
            from ..runtime import events

            events.emit("throttle.engage", severity="warn",
                        ratio=round(ratio, 3))
        if self.reject_ratio and ratio >= self.reject_ratio:
            self.rejected_count += 1
            self._c_reject.increment()
            raise ThrottleReject(
                f"write throttled: compaction debt {ratio:.2f}x of the "
                f"ceiling >= reject ratio {self.reject_ratio:.2f}")
        frac = min(1.0, (ratio - self.soft) / max(1e-9, 1.0 - self.soft))
        delay_ms = self.max_ms * frac
        if delay_ms <= 0:
            return 0.0
        self.delayed_count += 1
        self.delay_ms_total += delay_ms
        self._c_delay.increment()
        self._c_delay_ms.set(delay_ms)
        self._c_delay_ms_total.increment(delay_ms)
        if self.ledger is not None:
            # charged HERE, not by the caller: global total == sum of
            # per-table attributions holds structurally
            self.ledger.charge_throttle_delay(delay_ms)
        time.sleep(delay_ms / 1000.0)
        return delay_ms
