"""Memtable: the mutable in-memory run.

A plain dict keyed by stored key — PacificA serializes writes per partition
(one decree at a time, SURVEY.md §3.2), so no concurrent-writer structure is
needed; newest-write-wins within the dict is exactly RocksDB's
last-sequence-wins inside one memtable. Sorting is deferred to flush, where
it runs as one batched device sort (the memtable-flush offload of
BASELINE.json) instead of RocksDB's per-insert skiplist ordering.
"""

from .block import KVBlock


class Memtable:
    def __init__(self):
        self._data = {}  # key -> (value_bytes, expire_ts, deleted)
        self._bytes = 0
        self.last_decree = 0  # highest decree contained; stamped per write

    def __len__(self):
        return len(self._data)

    @property
    def approximate_bytes(self) -> int:
        return self._bytes

    def put(self, key: bytes, value: bytes, expire_ts: int = 0):
        old = self._data.get(key)
        if old is not None:
            self._bytes -= len(key) + len(old[0])
        self._data[key] = (value, expire_ts, False)
        self._bytes += len(key) + len(value)

    def delete(self, key: bytes):
        old = self._data.get(key)
        if old is not None:
            self._bytes -= len(key) + len(old[0])
        self._data[key] = (b"", 0, True)
        self._bytes += len(key)

    def put_batch(self, items):
        """Insert many (key, value, expire_ts) records in one call — the
        committed-window apply path pays one method dispatch (and one
        attribute walk) per BATCH instead of per record."""
        data = self._data
        delta = 0
        for key, value, expire_ts in items:
            old = data.get(key)
            if old is not None:
                delta -= len(key) + len(old[0])
            data[key] = (value, expire_ts, False)
            delta += len(key) + len(value)
        self._bytes += delta

    def delete_batch(self, keys):
        """Tombstone many keys in one call (put_batch's twin)."""
        data = self._data
        delta = 0
        for key in keys:
            old = data.get(key)
            if old is not None:
                delta -= len(key) + len(old[0])
            data[key] = (b"", 0, True)
            delta += len(key)
        self._bytes += delta

    def get(self, key: bytes):
        """-> (value, expire_ts, deleted) or None if the key was never seen."""
        return self._data.get(key)

    def to_block(self) -> KVBlock:
        """Unsorted columnar snapshot; the flush path sorts it on device."""
        return KVBlock.from_records(
            (k, v, e, d) for k, (v, e, d) in self._data.items()
        )

    def items(self):
        return self._data.items()
