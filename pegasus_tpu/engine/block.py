"""KVBlock: the columnar record batch the whole engine is built around.

The reference engine hands RocksDB one record at a time (WriteBatch entries,
compaction-filter callbacks on single KVs — src/server/rocksdb_wrapper.cpp,
src/server/key_ttl_compaction_filter.h:36). A TPU can't be fed that way: the
unit of work here is a *block* of records in structure-of-arrays layout —
byte arenas for variable-length keys/values plus fixed-width numpy columns
(expire_ts, partition hash, tombstone flag) that stream to HBM without
per-record host work. Flush sorts a block on device; compaction merges many.

Invariants:
  - keys are full stored keys (base.key_schema layout), so np-lexicographic
    byte order == engine key order.
  - hash32 is the low 32 bits of pegasus_key_hash(key): enough for
    partition-ownership masks (partition counts are far below 2^32), avoids
    u64 on device.
  - `deleted` marks tombstones (the engine-level delete marker; the value
    arena entry is empty for them).
"""

from dataclasses import dataclass

import numpy as np

from ..base.crc64 import crc64_batch
from ..base.key_schema import key_hash


def _as_arena(chunks) -> tuple:
    """list[bytes] -> (uint8 arena, int64 offsets, int32 lengths)."""
    lengths = np.fromiter((len(c) for c in chunks), dtype=np.int32, count=len(chunks))
    offsets = np.zeros(len(chunks), dtype=np.int64)
    if len(chunks):
        np.cumsum(lengths[:-1], out=offsets[1:])
    arena = np.frombuffer(b"".join(chunks), dtype=np.uint8).copy() if chunks else np.zeros(0, np.uint8)
    return arena, offsets, lengths


def _gather_arena(arena, offsets, lengths, idx):
    """Vectorized gather of variable-length slices: new compact arena for idx.

    Uniform-length records take numpy's 2D fancy-index (measured faster
    than a per-record memcpy loop on this host); variable-length gathers
    use the native kernel, falling back to the repeat/cumsum construction.
    """
    n = len(lengths)
    if n and len(idx):
        # uniform-length fast path (common: fixed-size records): 2D reshape
        # gather is a straight memcpy per row instead of repeat/cumsum work
        l0 = int(lengths[0])
        if l0 > 0 and int(lengths.min()) == l0 == int(lengths.max()) \
                and len(arena) == n * l0 \
                and offsets[0] == 0 and int(offsets[-1]) == (n - 1) * l0:
            out = arena.reshape(n, l0)[idx].reshape(-1)
            new_off = np.arange(len(idx), dtype=np.int64) * l0
            return out, new_off, np.full(len(idx), l0, np.int32)
        from .. import native

        if native.available():
            out, new_off = native.gather_arena(arena, offsets, lengths, idx)
            return out, new_off, lengths[idx]
    sel_off = offsets[idx]
    sel_len = lengths[idx].astype(np.int64)
    total = int(sel_len.sum())
    new_off = np.zeros(len(idx), dtype=np.int64)
    if len(idx):
        np.cumsum(sel_len[:-1], out=new_off[1:])
    if total == 0:
        return np.zeros(0, np.uint8), new_off, sel_len.astype(np.int32)
    starts = np.repeat(sel_off, sel_len)
    within = np.arange(total, dtype=np.int64) - np.repeat(new_off, sel_len)
    return arena[starts + within], new_off, sel_len.astype(np.int32)


@dataclass
class KVBlock:
    key_arena: np.ndarray  # uint8[total_key_bytes]
    key_off: np.ndarray    # int64[n]
    key_len: np.ndarray    # int32[n]
    val_arena: np.ndarray  # uint8[total_val_bytes]
    val_off: np.ndarray    # int64[n]
    val_len: np.ndarray    # int32[n]
    expire_ts: np.ndarray  # uint32[n]
    hash32: np.ndarray     # uint32[n] — low 32 bits of pegasus_key_hash
    deleted: np.ndarray    # bool[n]

    @property
    def n(self) -> int:
        return len(self.key_off)

    @property
    def key_bytes_total(self) -> int:
        return int(self.key_len.sum())

    @property
    def val_bytes_total(self) -> int:
        return int(self.val_len.sum())

    def key(self, i: int) -> bytes:
        o, l = self.key_off[i], self.key_len[i]
        return self.key_arena[o : o + l].tobytes()

    def value(self, i: int) -> bytes:
        o, l = self.val_off[i], self.val_len[i]
        return self.val_arena[o : o + l].tobytes()

    def keys(self):
        for i in range(self.n):
            yield self.key(i)

    @staticmethod
    def from_records(records, hashes=None) -> "KVBlock":
        """records: iterable of (key, value, expire_ts, deleted).

        hashes: optional precomputed full key hashes (uint64 iterable); if
        absent they are computed with the batched crc64 over the hash_key
        portion (matching base.key_schema.key_hash).
        """
        records = list(records)
        keys = [r[0] for r in records]
        vals = [r[1] for r in records]
        ka, ko, kl = _as_arena(keys)
        va, vo, vl = _as_arena(vals)
        expire = np.fromiter((r[2] for r in records), dtype=np.uint32, count=len(records))
        deleted = np.fromiter((bool(r[3]) for r in records), dtype=np.bool_, count=len(records))
        if hashes is None:
            hashes = _batch_key_hashes(ka, ko, kl)
        else:
            hashes = np.asarray(hashes, dtype=np.uint64)
        return KVBlock(ka, ko, kl, va, vo, vl, expire,
                       (hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32), deleted)

    def lower_bound(self, key: bytes) -> int:
        """First index with self.key(i) >= key (n if none); rows must be
        key-sorted (SSTs and merge outputs are)."""
        lo, hi = 0, self.n
        while lo < hi:
            mid = (lo + hi) // 2
            if self.key(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def uniform_layout(self):
        """(key_len, val_len) when every record has the same key and value
        widths and both arenas are contiguous in row order — the layout
        produced by fixed-width fills and by uniform gathers; None
        otherwise.

        Precondition: offsets are MONOTONIC in row order (true for every
        constructor in this codebase — _as_arena, gather, concat all emit
        ascending offsets). The check probes endpoints plus a midpoint, so
        a hand-built block whose offsets permute rows with matching probe
        points would be misclassified as row-contiguous."""
        n = self.n
        if not n:
            return None
        kl0 = int(self.key_len[0])
        vl0 = int(self.val_len[0])
        mid = n // 2
        if (kl0 > 0 and int(self.key_len.min()) == kl0 == int(self.key_len.max())
                and vl0 > 0
                and int(self.val_len.min()) == vl0 == int(self.val_len.max())
                and len(self.key_arena) == n * kl0
                and len(self.val_arena) == n * vl0
                and self.key_off[0] == 0
                and int(self.key_off[-1]) == (n - 1) * kl0
                and int(self.key_off[mid]) == mid * kl0
                and self.val_off[0] == 0
                and int(self.val_off[-1]) == (n - 1) * vl0
                and int(self.val_off[mid]) == mid * vl0):
            return kl0, vl0
        return None

    def gather(self, idx) -> "KVBlock":
        """New block with rows idx (in that order); arenas compacted."""
        idx = np.asarray(idx, dtype=np.int64)
        count = len(idx)
        # fused one-pass native gather (keys+values+aux together, with
        # source-row prefetch): the separate fancy-index sweeps are
        # DRAM-latency-bound on large random gathers
        if count >= (1 << 15) and self.n < (1 << 31):
            from .. import native

            uni = self.uniform_layout() if native.available() else None
            # the native kernel does unchecked pointer arithmetic; keep
            # numpy's bounds semantics (negatives/OOB fall through to the
            # fancy-index path, which wraps or raises) — two O(n)
            # reductions, negligible next to the gather
            if uni is not None and (int(idx.min()) < 0
                                    or int(idx.max()) >= self.n):
                uni = None
            if uni is not None:
                kl0, vl0 = uni
                out_k = np.empty(count * kl0, np.uint8)
                out_v = np.empty(count * vl0, np.uint8)
                out_e = np.empty(count, np.uint32)
                out_h = np.empty(count, np.uint32)
                out_d = np.empty(count, np.bool_)
                if native.gather_block_uniform(
                        self.key_arena, kl0, self.val_arena, vl0,
                        self.expire_ts, self.hash32, self.deleted,
                        idx.astype(np.int32), out_k, out_v, out_e, out_h,
                        out_d):
                    return KVBlock(
                        out_k, np.arange(count, dtype=np.int64) * kl0,
                        np.full(count, kl0, np.int32),
                        out_v, np.arange(count, dtype=np.int64) * vl0,
                        np.full(count, vl0, np.int32), out_e, out_h, out_d)
        ka, ko, kl = _gather_arena(self.key_arena, self.key_off, self.key_len, idx)
        va, vo, vl = _gather_arena(self.val_arena, self.val_off, self.val_len, idx)
        return KVBlock(ka, ko, kl, va, vo, vl,
                       self.expire_ts[idx], self.hash32[idx], self.deleted[idx])

    @staticmethod
    def concat(blocks) -> "KVBlock":
        blocks = [b for b in blocks if b.n]
        if not blocks:
            return KVBlock.empty()
        key_arena = np.concatenate([b.key_arena for b in blocks])
        val_arena = np.concatenate([b.val_arena for b in blocks])
        k_shift = np.cumsum([0] + [len(b.key_arena) for b in blocks[:-1]])
        v_shift = np.cumsum([0] + [len(b.val_arena) for b in blocks[:-1]])
        return KVBlock(
            key_arena,
            np.concatenate([b.key_off + s for b, s in zip(blocks, k_shift)]),
            np.concatenate([b.key_len for b in blocks]),
            val_arena,
            np.concatenate([b.val_off + s for b, s in zip(blocks, v_shift)]),
            np.concatenate([b.val_len for b in blocks]),
            np.concatenate([b.expire_ts for b in blocks]),
            np.concatenate([b.hash32 for b in blocks]),
            np.concatenate([b.deleted for b in blocks]),
        )

    @staticmethod
    def empty() -> "KVBlock":
        z8, z64, z32 = np.zeros(0, np.uint8), np.zeros(0, np.int64), np.zeros(0, np.int32)
        return KVBlock(z8, z64, z32, z8.copy(), z64.copy(), z32.copy(),
                       np.zeros(0, np.uint32), np.zeros(0, np.uint32), np.zeros(0, np.bool_))


def _batch_key_hashes(key_arena, key_off, key_len) -> np.ndarray:
    """pegasus_key_hash over every stored key in an arena, vectorized.

    Mirrors base.key_schema.key_hash (reference
    src/base/pegasus_key_schema.h:151-167): crc64 over the hash_key portion,
    or over the sort_key when hash_key_len == 0.
    """
    n = len(key_off)
    if n == 0:
        return np.zeros(0, np.uint64)
    # hash_key_len: u16 BE at the key start
    hi = key_arena[key_off].astype(np.uint16)
    lo = key_arena[key_off + 1].astype(np.uint16)
    hklen = ((hi << 8) | lo).astype(np.int64)
    body_off = key_off + 2
    body_len = np.where(hklen > 0, hklen, key_len.astype(np.int64) - 2)
    return crc64_batch(key_arena, body_off, body_len)
