"""Manual-compact service: app-env driven once/periodic full compactions.

Mirror of pegasus_manual_compact_service (src/server/
pegasus_manual_compact_service.{h,cpp}): the meta server distributes
`manual_compact.*` app-envs to every replica; each replica decides locally
whether to run (once trigger newer than last finish; periodic trigger time
of day passed), bounded cluster-wide by `max_concurrent_running_count`
(a process-wide semaphore here standing in for the cluster-wide cap), and
records the finish time into the engine meta store so `query_compact_state`
and once-trigger dedup survive restarts.

Env keys (base.consts, byte-compatible with pegasus_const.cpp):
  manual_compact.disabled                         "true"/"false"
  manual_compact.max_concurrent_running_count     int
  manual_compact.once.trigger_time                unix seconds
  manual_compact.once.target_level                -1 | level
  manual_compact.once.bottommost_level_compaction "force"|"skip"
  manual_compact.periodic.trigger_time            "3:00,21:00" local times
  (periodic.* supports the same target_level / bottommost keys)

Time is injectable (`mock_now`) the way the reference gates
now_timestamp() under PEGASUS_UNIT_TEST (manual_compact_service.h:77-79).
"""

import threading
import time

from ..base import consts
from ..runtime.perf_counters import counters

_QUEUED = "queued"
_RUNNING = "running"
_IDLE = "idle"


class _ConcurrencyGate:
    """Process-wide running-count cap (cluster-wide in the reference,
    enforced by meta-spread envs; one process hosts many replicas here).
    urgent=True bypasses the cap: a partition the cluster compaction
    scheduler marked urgent (slow-request-driving debt) jumps the queue
    instead of waiting behind elective compactions (ISSUE 10)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.running = 0

    def try_acquire(self, limit: int, urgent: bool = False) -> bool:
        with self._lock:
            if limit > 0 and self.running >= limit:
                if not urgent:
                    return False
                # counted HERE, under the lock that decided it: this
                # acquire really did pass a cap that would have blocked
                counters.rate("manual_compact.queue_jump_count").increment()
            self.running += 1
            return True

    def release(self):
        with self._lock:
            self.running -= 1


GATE = _ConcurrencyGate()


class ManualCompactService:
    MIN_INTERVAL_SECONDS = 0  # tests override; reference flag default 0=any

    def __init__(self, server, mock_now: int = None):
        self.server = server
        self._mock_now = mock_now
        self._state = _IDLE
        self._lock = threading.Lock()
        self._enqueue_ms = 0
        self._start_ms = 0
        self._last_finish_ms = int(server.engine.meta_store.get(
            "pegasus_last_manual_compact_finish_time", 0)) * 1000
        self._last_used_ms = 0
        self._last_trace = None  # per-stage breakdown of the last run
        self._last_error = None  # repr of the last FAILED run's exception
        self._last_fail_ms = 0

    # ------------------------------------------------------------------ time

    def now_ms(self) -> int:
        return (self._mock_now * 1000 if self._mock_now is not None
                else int(time.time() * 1000))

    def set_mock_now(self, seconds: int):
        self._mock_now = seconds

    # ------------------------------------------------------------------ envs

    def start_manual_compact_if_needed(self, envs: dict) -> bool:
        """Called on every app-env update (and periodically); returns True
        when a compaction was started."""
        if self._check_disabled(envs):
            return False
        opts = None
        if self._check_once(envs):
            opts = self._extract_opts(envs, consts.MANUAL_COMPACT_ONCE_KEY_PREFIX)
        elif self._check_periodic(envs):
            opts = self._extract_opts(envs,
                                      consts.MANUAL_COMPACT_PERIODIC_KEY_PREFIX)
        if opts is None:
            return False
        limit = int(envs.get(
            consts.MANUAL_COMPACT_MAX_CONCURRENT_RUNNING_COUNT_KEY, 0))
        # urgent scheduler token (ISSUE 10): this partition's debt is
        # driving slow requests — jump the concurrency queue instead of
        # waiting a round behind elective compactions (the gate counts
        # real jumps as manual_compact.queue_jump_count)
        urgent = self.server.engine.compact_policy()[0] == "urgent"
        with self._lock:
            if self._state != _IDLE:
                return False
            if not GATE.try_acquire(limit, urgent=urgent):
                return False
            self._state = _QUEUED
            self._enqueue_ms = self.now_ms()
        counters.rate("manual_compact.enqueue_count").increment()
        try:
            self._run(opts)
        finally:
            GATE.release()
        return True

    def _check_disabled(self, envs) -> bool:
        return str(envs.get(consts.MANUAL_COMPACT_DISABLED_KEY,
                            "false")).lower() == "true"

    def _check_once(self, envs) -> bool:
        t = envs.get(consts.MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY)
        if t is None:
            return False
        trigger_ms = int(t) * 1000
        return trigger_ms > self._last_finish_ms and self.now_ms() >= trigger_ms

    def _check_periodic(self, envs) -> bool:
        spec = envs.get(consts.MANUAL_COMPACT_PERIODIC_TRIGGER_TIME_KEY)
        if not spec:
            return False
        now_s = self.now_ms() // 1000
        lt = time.localtime(now_s)
        midnight = now_s - (lt.tm_hour * 3600 + lt.tm_min * 60 + lt.tm_sec)
        for hhmm in str(spec).split(","):
            hhmm = hhmm.strip()
            if not hhmm:
                continue
            hh, _, mm = hhmm.partition(":")
            trigger = midnight + int(hh) * 3600 + int(mm or 0) * 60
            if now_s >= trigger and trigger * 1000 > self._last_finish_ms:
                return True
        return False

    def _extract_opts(self, envs, prefix) -> dict:
        tl = int(envs.get(prefix + consts.MANUAL_COMPACT_TARGET_LEVEL_KEY, -1))
        bl = envs.get(prefix + consts.MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_KEY,
                      consts.MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_SKIP)
        return {
            "target_level": None if tl <= 0 else tl,
            "bottommost": bl == consts.MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_FORCE,
        }

    # ------------------------------------------------------------------- run

    def _run(self, opts: dict):
        with self._lock:
            self._state = _RUNNING
            self._start_ms = self.now_ms()
        counters.rate("manual_compact.running_count").increment()
        # device-backed compactions get a liveness probe BEFORE the merge
        # (a wedged tunnel should be attributed to pre-existing device
        # state, not to the compaction) and AFTER it (refresh last_ok /
        # catch an in-run wedge the moment the merge returns or raises)
        is_device = getattr(self.server.engine.opts, "backend",
                            "cpu") != "cpu"
        if is_device:
            # start() arms the background probe loop (idempotent): a merge
            # that WEDGES never returns, so only a re-probing loop can
            # accumulate the consecutive failures that flip
            # wedged_at_stage while query_compact_state reports 'running'
            self._watchdog().start()
            self._watchdog().probe()
        error = None
        try:
            stats = self.server.engine.manual_compact(
                bottommost=opts["bottommost"],
                target_level=opts["target_level"],
                now=self._mock_now,
            )
            with self._lock:
                self._last_trace = stats.get("trace")
        except BaseException as e:
            # a FAILED run must not record finish state: persisting
            # `pegasus_last_manual_compact_finish_time` here would dedup
            # the once-trigger as "finished" and the compaction would
            # never be retried. BaseException, not Exception — an
            # interrupt (shutdown SIGINT/SystemExit) mid-compaction must
            # not be recorded as finished either. The failure is recorded
            # for query_compact_state and re-raised to the caller.
            error = e
            if isinstance(e, Exception):
                counters.rate("manual_compact.failure_count").increment()
            raise
        finally:
            if is_device:
                self._watchdog().probe()
            finish = self.now_ms()
            with self._lock:
                self._last_used_ms = finish - self._start_ms
                self._state = _IDLE
                if error is None:
                    self._last_finish_ms = finish
                    self._last_error = None
                else:
                    self._last_fail_ms = finish
                    self._last_error = repr(error)
            if error is None:
                self.server.engine.meta_store[
                    "pegasus_last_manual_compact_finish_time"] = finish // 1000

    @staticmethod
    def _watchdog():
        from ..ops.device_watchdog import WATCHDOG

        return WATCHDOG

    @staticmethod
    def _lane_guard():
        from ..runtime.lane_guard import LANE_GUARD

        return LANE_GUARD

    # ----------------------------------------------------------------- state

    def query_compact_state(self) -> str:
        """Human string like the reference's query_compact_state — plus the
        watchdog's wedge attribution and the lane guard's breaker/fallback
        state, so a stuck or degraded compaction reports WHERE it wedged
        (and that it survived via the cpu lane) instead of just 'running'
        forever."""
        with self._lock:
            if self._state == _RUNNING:
                out = (f"running; started at {self._start_ms} "
                       f"(queued at {self._enqueue_ms})")
            elif self._state == _QUEUED:
                out = f"queued at {self._enqueue_ms}"
            elif self._last_finish_ms:
                out = (f"idle; last finish at {self._last_finish_ms}, "
                       f"used {self._last_used_ms} ms")
            else:
                out = "idle; never compacted"
            if self._last_error is not None:
                out += (f"; last attempt FAILED at {self._last_fail_ms}: "
                        f"{self._last_error}")
        wedged = self._watchdog().wedged_at_stage
        if wedged is not None:
            out += f"; device wedged at stage {wedged}"
        target = getattr(self.server.engine, "offload_target",
                         lambda: None)()
        if target:
            # merges ship to the rack's compaction service (ISSUE 14);
            # surface the wire lane's degradation totals alongside it
            out += f"; compaction offload -> {target}"
            from ..replication.compact_offload import OFFLOAD_LANE_GUARD

            olane = OFFLOAD_LANE_GUARD.state()
            if olane["breaker_open"]:
                out += (f"; offload lane breaker OPEN "
                        f"(cooldown "
                        f"{olane['breaker_cooldown_remaining_s']}s)")
            if olane["fallbacks"]:
                out += f"; offload local fallbacks: {olane['fallbacks']}"
        lane = self._lane_guard().state()
        if lane["breaker_open"]:
            out += (f"; device lane breaker OPEN "
                    f"({lane['breaker_consecutive_failures']} consecutive "
                    f"failures, cooldown "
                    f"{lane['breaker_cooldown_remaining_s']}s)")
        if lane["fallbacks"]:
            out += (f"; cpu fallbacks: {lane['fallbacks']} "
                    f"(retries {lane['retries']}, deadline abandons "
                    f"{lane['deadline_abandons']})")
        return out

    @property
    def last_trace(self):
        """Per-stage breakdown (tracing.TraceSession.summary) of the last
        completed manual compaction, or None."""
        with self._lock:
            return self._last_trace

    @property
    def last_finish_time_ms(self) -> int:
        return self._last_finish_ms
