"""Range-read iteration limiter (src/server/range_read_limiter.h:29-100).

Caps how much work one multi_get / sortkey_count / scan RPC may do:
iteration count, accumulated bytes, and wall time (time is checked every
`time_check_period` iterations like the reference's 10-checks-per-scan).
Exceeded limits make the server return a partial batch with an INCOMPLETE /
continue signal instead of stalling the read thread pool.
"""

import time


class RangeReadLimiter:
    def __init__(self, max_iteration_count: int = 1000,
                 max_iteration_size: int = 4 << 20,
                 max_duration_ms: int = 5000,
                 time_check_period: int = 100):
        self.max_count = max_iteration_count
        self.max_size = max_iteration_size
        self.max_duration_ms = max_duration_ms
        self.period = max(1, time_check_period)
        self._count = 0
        self._size = 0
        self._t0 = time.monotonic()
        self.stopped_by = None  # None | "count" | "size" | "time"

    def add_count(self, n: int = 1) -> None:
        self._count += n

    def add_size(self, nbytes: int) -> None:
        self._size += nbytes

    def valid(self) -> bool:
        if self.max_count > 0 and self._count >= self.max_count:
            self.stopped_by = "count"
            return False
        if self.max_size > 0 and self._size >= self.max_size:
            self.stopped_by = "size"
            return False
        if (self.max_duration_ms > 0 and self._count % self.period == 0
                and (time.monotonic() - self._t0) * 1000 >= self.max_duration_ms):
            self.stopped_by = "time"
            return False
        return True

    @property
    def iterated(self) -> int:
        return self._count
