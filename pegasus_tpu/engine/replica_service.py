"""Replica serverlet: binds rrdb task codes to a PegasusServer per partition.

The reference's pegasus_read_service registration glue + pegasus_service_app
(src/server/pegasus_read_service.h:36-84, pegasus_service_app.h): one
process serves many (app_id, partition) replicas; each RPC is routed by the
header's (app_id, partition_index) and the key's partition hash is sanity-
checked against the partition the way pegasus_server_write does
(src/server/pegasus_server_write.cpp per-request hash check).

Standalone mode commits writes locally with a monotonically increasing
decree (one writer per partition — PacificA's per-partition serialization).
When a replication.ReplicaStub hosts the partition, writes are routed
through PacificA 2PC instead (write_router hook).
"""

import threading
import time

from ..rpc import codec
from ..rpc import messages as msg
from ..rpc.transport import (ERR_BUSY, ERR_INVALID_DATA, ERR_INVALID_STATE,
                             ERR_OBJECT_NOT_FOUND, RpcError)
from . import server_impl
from .server_impl import PegasusServer

# read task codes (src/include/rrdb/rrdb.code.definition.h)
RPC_GET = "RPC_RRDB_RRDB_GET"
RPC_MULTI_GET = "RPC_RRDB_RRDB_MULTI_GET"
RPC_SORTKEY_COUNT = "RPC_RRDB_RRDB_SORTKEY_COUNT"
RPC_TTL = "RPC_RRDB_RRDB_TTL"
RPC_GET_SCANNER = "RPC_RRDB_RRDB_GET_SCANNER"
RPC_SCAN = "RPC_RRDB_RRDB_SCAN"
RPC_CLEAR_SCANNER = "RPC_RRDB_RRDB_CLEAR_SCANNER"

WRITE_CODES = {
    server_impl.RPC_PUT: (msg.UpdateRequest, msg.UpdateResponse),
    server_impl.RPC_REMOVE: (msg.KeyRequest, msg.UpdateResponse),
    server_impl.RPC_MULTI_PUT: (msg.MultiPutRequest, msg.UpdateResponse),
    server_impl.RPC_MULTI_REMOVE: (msg.MultiRemoveRequest, msg.MultiRemoveResponse),
    server_impl.RPC_INCR: (msg.IncrRequest, msg.IncrResponse),
    server_impl.RPC_CHECK_AND_SET: (msg.CheckAndSetRequest, msg.CheckAndSetResponse),
    server_impl.RPC_CHECK_AND_MUTATE: (msg.CheckAndMutateRequest,
                                       msg.CheckAndMutateResponse),
    server_impl.RPC_DUPLICATE: (msg.DuplicateRequest, msg.DuplicateResponse),
    server_impl.RPC_BULK_LOAD_INGEST: (msg.BulkLoadIngestRequest,
                                       msg.BulkLoadIngestResponse),
    server_impl.RPC_TRIGGER_AUDIT: (msg.TriggerAuditRequest,
                                    msg.TriggerAuditResponse),
}


class ReplicaService:
    """Hosts PegasusServer replicas; register with RpcServer.register_serverlet."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas = {}     # (app_id, pidx) -> PegasusServer
        self._wlocks = {}       # (app_id, pidx) -> per-partition write lock
        self._partition_counts = {}  # app_id -> partition count
        self._write_router = None    # set by replication to intercept writes

    def add_replica(self, server: PegasusServer, partition_count: int) -> None:
        with self._lock:
            self._replicas[(server.app_id, server.pidx)] = server
            self._wlocks[(server.app_id, server.pidx)] = threading.Lock()
            self._partition_counts[server.app_id] = partition_count

    def remove_replica(self, app_id: int, pidx: int) -> None:
        with self._lock:
            self._replicas.pop((app_id, pidx), None)
            self._wlocks.pop((app_id, pidx), None)

    def set_write_router(self, fn) -> None:
        """fn(server, code, req) -> response; replaces local commit (PacificA)."""
        self._write_router = fn

    def _replica(self, header) -> PegasusServer:
        srv = self._replicas.get((header.app_id, header.partition_index))
        if srv is None:
            raise RpcError(ERR_OBJECT_NOT_FOUND,
                           f"partition {header.app_id}.{header.partition_index} "
                           f"not served here")
        n = self._partition_counts.get(header.app_id, 1)
        if n > 0 and header.partition_hash \
                and header.partition_index != header.partition_hash % n:
            raise RpcError(ERR_INVALID_STATE,
                           f"partition hash routes to "
                           f"{header.partition_hash % n}, not {header.partition_index}")
        return srv

    # --------------------------------------------------------------- handlers

    def rpc_handlers(self) -> dict:
        h = {
            RPC_GET: self._on_get,
            RPC_MULTI_GET: self._on_multi_get,
            RPC_SORTKEY_COUNT: self._on_sortkey_count,
            RPC_TTL: self._on_ttl,
            RPC_GET_SCANNER: self._on_get_scanner,
            RPC_SCAN: self._on_scan,
            RPC_CLEAR_SCANNER: self._on_clear_scanner,
        }
        for code in WRITE_CODES:
            h[code] = self._on_write
        return h

    def rpc_batch_handlers(self) -> dict:
        """Hot read codes the frame reader coalesces (ISSUE 20). Each
        fn(headers, bodies) returns one result per frame — bytes on
        success, RpcError/Exception carrying the same error the per-frame
        handler would have raised, so the transport encodes
        byte-identical responses either way."""
        return {
            RPC_GET: self._on_get_batch,
            RPC_MULTI_GET: self._batch_loop(self._on_multi_get),
            RPC_SCAN: self._batch_loop(self._on_scan),
        }

    @staticmethod
    def _batch_loop(fn):
        """Per-frame handler -> batch handler: the storage call stays per
        frame, but the wave still pays ONE dispatch + ONE vectored reply
        write instead of len(wave) of each."""
        def run(headers, bodies):
            out = []
            for header, body in zip(headers, bodies):
                try:
                    out.append(fn(header, body))
                except Exception as e:  # noqa: BLE001 - per-frame verdict
                    out.append(e)
            return out
        return run

    def _replica_read(self, header) -> PegasusServer:
        """Resolve + charge the read throttle (reference
        replica.read_throttling env; qps units)."""
        from .throttling import ThrottleReject

        srv = self._replica(header)
        try:
            srv.read_qps_throttler.consume(1)
        except ThrottleReject as e:
            if srv.table_ledger is not None:
                srv.table_ledger.charge_error()
            raise RpcError(ERR_BUSY, str(e))
        return srv

    def _read(self, header, method: str, *args):
        """Serve one read with on-disk corruption surfaced as a TYPED
        rpc error (ISSUE 17): the engine already refused to return bytes
        it cannot verify (and its corruption hook is quarantining the
        replica async) — the client sees a clean retriable error naming
        the cause, never garbage and never a handler-bug repr."""
        from .sstable import CorruptionError

        srv = self._replica_read(header)
        try:
            return getattr(srv, method)(*args)
        except CorruptionError as e:
            if srv.table_ledger is not None:
                srv.table_ledger.charge_error()
            raise RpcError(ERR_INVALID_DATA,
                           f"on-disk corruption: {e.detail} — replica "
                           f"{srv.app_id}.{srv.pidx} is being quarantined; "
                           f"retry after reconfiguration")

    def _on_get(self, header, body) -> bytes:
        req = codec.decode(msg.KeyRequest, body)
        return codec.encode(self._read(header, "on_get", req.key))

    def _on_get_batch(self, headers, bodies) -> list:
        """RPC_GET over a coalesced wave: per-frame admission (decode,
        partition resolve, read throttle — each request is still charged
        individually), then ONE PegasusServer.on_get_batch per distinct
        replica for everything admitted. Per-frame failures become that
        frame's result; a group failure becomes every member's result —
        the exact errors _on_get would have raised."""
        from .sstable import CorruptionError

        results = [None] * len(headers)
        groups = {}  # id(srv) -> (srv, [(frame index, key), ...])
        for i, (header, body) in enumerate(zip(headers, bodies)):
            try:
                req = codec.decode(msg.KeyRequest, body)
                srv = self._replica_read(header)
            except Exception as e:  # noqa: BLE001 - per-frame verdict
                results[i] = e
                continue
            groups.setdefault(id(srv), (srv, []))[1].append((i, req.key))
        for srv, members in groups.values():
            try:
                resps = srv.on_get_batch([k for _, k in members])
                for (i, _), resp in zip(members, resps):
                    results[i] = codec.encode(resp)
            except CorruptionError as e:
                if srv.table_ledger is not None:
                    srv.table_ledger.charge_error()
                err = RpcError(ERR_INVALID_DATA,
                               f"on-disk corruption: {e.detail} — replica "
                               f"{srv.app_id}.{srv.pidx} is being "
                               f"quarantined; retry after reconfiguration")
                for i, _ in members:
                    results[i] = err
            except Exception as e:  # noqa: BLE001 - per-frame verdict
                for i, _ in members:
                    results[i] = e
        return results

    def _on_multi_get(self, header, body) -> bytes:
        req = codec.decode(msg.MultiGetRequest, body)
        return codec.encode(self._read(header, "on_multi_get", req))

    def _on_sortkey_count(self, header, body) -> bytes:
        req = codec.decode(msg.KeyRequest, body)
        return codec.encode(self._read(header, "on_sortkey_count", req.key))

    def _on_ttl(self, header, body) -> bytes:
        req = codec.decode(msg.KeyRequest, body)
        return codec.encode(self._read(header, "on_ttl", req.key))

    def _on_get_scanner(self, header, body) -> bytes:
        req = codec.decode(msg.GetScannerRequest, body)
        return codec.encode(self._read(header, "on_get_scanner", req))

    def _on_scan(self, header, body) -> bytes:
        req = codec.decode(msg.ScanRequest, body)
        return codec.encode(self._read(header, "on_scan", req))

    def _on_clear_scanner(self, header, body) -> bytes:
        req = codec.decode(msg.ScanRequest, body)
        self._replica(header).on_clear_scanner(req.context_id)
        return b""

    def _on_write(self, header, body) -> bytes:
        req_cls, _ = WRITE_CODES[header.code]
        req = codec.decode(req_cls, body)
        srv = self._replica(header)
        # per-table throttling gates the request BEFORE any decree work
        # (reference: rDSN throttling_controller consulted on the primary,
        # env replica.write_throttling[_by_size])
        from .throttling import ThrottleReject

        from ..runtime.perf_counters import counters

        try:
            d0 = (srv.write_qps_throttler.delayed_count
                  + srv.write_size_throttler.delayed_count)
            srv.write_qps_throttler.consume(1)
            srv.write_size_throttler.consume(len(body))
            # compaction-debt admission control (ISSUE 10): graduated
            # delay as L0 debt approaches the stall cliff, reject past
            # the configured ratio — counted on its own
            # engine.throttle.debt_* series by the throttle itself (and,
            # when the replica is table-wired, on the tenant ledger)
            delay_ms = srv.debt_throttler.consume()
            if delay_ms > 0:
                # per-partition delay attribution (ISSUE 18): which
                # partition paid the debt stall, in ms not just counts
                counters.rate(
                    f"app.{srv.app_id}.{srv.pidx}."
                    "recent_write_throttling_delay_ms").increment(delay_ms)
            if (srv.write_qps_throttler.delayed_count
                    + srv.write_size_throttler.delayed_count) > d0:
                counters.rate(
                    f"app.{srv.app_id}.{srv.pidx}."
                    "recent_write_throttling_delay_count").increment()
        except ThrottleReject as e:
            counters.rate(
                f"app.{srv.app_id}.{srv.pidx}."
                "recent_write_throttling_reject_count").increment()
            if srv.table_ledger is not None:
                srv.table_ledger.charge_error()
            raise RpcError(ERR_BUSY, str(e))
        if srv.table_ledger is not None:
            srv.table_ledger.charge_bytes_in(len(body))
        router = self._write_router
        if router is not None:
            resp = router(srv, header.code, req)
        else:
            with self._wlocks[(srv.app_id, srv.pidx)]:
                decree = srv.engine.last_committed_decree() + 1
                resps = srv.on_batched_write_requests(
                    decree, int(time.time() * 1e6), [(header.code, req)])
                resp = resps[0]
        return codec.encode(resp)
