"""Production-sim chaos harness (ISSUE 11).

The pressure tier's scenario engine: a declarative fault schedule
(`scenario.Scenario` — timed/periodic `FaultAction`s with arm/heal pairs
and per-action recovery deadlines) driven by `scenario.ScenarioRunner`
against fault actors (`actors` — node kill+restart, group-worker kill,
remote fail-point arming, mid-load partition split, balancer primary
move, compaction-scheduler token flips, duplication to a second
cluster), with every event landing in an `journal.EventJournal` the run
emits as its artifact.

`tools/pressure_test.py --scenario smoke|full` is the driver: sustained
target-QPS self-verifying load, the scripted fault schedule, periodic
decree-anchored audit rounds (collector.cluster_doctor.AuditRounds), a
cross-cluster digest compare for the duplication leg, and a final
cluster-doctor verdict — exit 0 only when no acked write was lost, every
transient error fell inside a declared fault window, every audit round
was mismatch-free, and the doctor ends healthy.
"""

from .journal import EventJournal
from .scenario import FaultAction, Scenario, ScenarioRunner

__all__ = ["EventJournal", "FaultAction", "Scenario", "ScenarioRunner"]
