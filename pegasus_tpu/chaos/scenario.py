"""Declarative fault schedule + the engine that drives it (ISSUE 11).

A `Scenario` is a list of `FaultAction`s: each names an actor (a key
into the runner's actor dict — see chaos.actors), an arm time, a
duration (heal fires at ``at_s + duration_s``), an optional period for
recurring faults, and a recovery deadline. `ScenarioRunner` walks the
expanded timeline on a background thread: for every occurrence it opens
a declared fault window (chaos.journal.FaultWindows — the interval in
which the load harness classifies transient errors as ALLOWED), arms the
actor, heals it on schedule, then polls ``actor.recovered()`` until the
recovery deadline — a breach is a named journal failure that fails the
run. Windows close only after recovery (+ the action's settle grace), so
the blip between heal and fully-reserving is still inside the declared
window.
"""

import time
from dataclasses import dataclass, field

from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread
from .journal import EventJournal, FaultWindows


@dataclass
class FaultAction:
    """One scripted fault: arm at `at_s`, heal `duration_s` later; with
    `every_s` the pair repeats until the run ends. After heal, the actor
    must report recovered() within `recovery_deadline_s` or the run
    fails with a named breach. `settle_s` extends the declared fault
    window past recovery (failover blips trail the heal)."""

    name: str
    actor: str
    at_s: float
    duration_s: float = 0.0
    every_s: float = None
    recovery_deadline_s: float = 30.0
    settle_s: float = 2.0
    args: dict = field(default_factory=dict)


class ScenarioError(ValueError):
    """A scenario that cannot be scheduled (validation failure)."""


@dataclass
class Scenario:
    name: str
    actions: list = field(default_factory=list)

    def validate(self, actor_keys=None) -> "Scenario":
        """Schedule sanity: unique action names, non-negative times,
        arm/heal pairing (a periodic action's next arm must come after
        the previous heal), positive recovery deadlines, and (when the
        runner's actor set is known) every referenced actor exists."""
        names = set()
        for a in self.actions:
            if a.name in names:
                raise ScenarioError(f"duplicate action name {a.name!r}")
            names.add(a.name)
            if a.at_s < 0 or a.duration_s < 0:
                raise ScenarioError(
                    f"action {a.name!r}: negative at_s/duration_s")
            if a.recovery_deadline_s <= 0:
                raise ScenarioError(
                    f"action {a.name!r}: recovery_deadline_s must be > 0")
            if a.every_s is not None and a.every_s <= a.duration_s:
                raise ScenarioError(
                    f"action {a.name!r}: every_s ({a.every_s}) must exceed "
                    f"duration_s ({a.duration_s}) — the next arm would "
                    "overlap the previous unhealed occurrence")
            if actor_keys is not None and a.actor not in actor_keys:
                raise ScenarioError(
                    f"action {a.name!r} references unknown actor "
                    f"{a.actor!r} (have: {sorted(actor_keys)})")
        return self

    def timeline(self, run_s: float) -> list:
        """Expand to ``[(t, "arm"|"heal", action, occurrence)]`` sorted
        by time — periodic actions repeat every `every_s` while the arm
        still falls inside the run; each occurrence's heal is always
        emitted (a fault armed near the end still heals)."""
        events = []
        for a in self.actions:
            k = 0
            while True:
                t_arm = a.at_s + (k * a.every_s if a.every_s else 0)
                if t_arm >= run_s and k > 0:
                    break
                events.append((t_arm, "arm", a, k))
                events.append((t_arm + a.duration_s, "heal", a, k))
                k += 1
                if not a.every_s:
                    break
        # STABLE sort by time only: each occurrence emits arm-then-heal,
        # so a zero-duration action's heal stays AFTER its arm (a
        # heal-first tiebreak here once inverted every instantaneous
        # action's pair and derailed the whole schedule)
        events.sort(key=lambda e: e[0])
        return events


class ScenarioRunner:
    """Drives one Scenario against a dict of fault actors on a
    background thread. The journal + fault windows are shared with the
    load harness: workers classify their errors against the windows this
    runner opens/closes."""

    def __init__(self, scenario: Scenario, actors: dict,
                 journal: EventJournal, windows: FaultWindows = None):
        self.scenario = scenario.validate(set(actors))
        self.actors = dict(actors)
        self.journal = journal
        self.windows = windows or FaultWindows(journal)
        self._abort = False   #: unguarded_ok checked/written as a plain
        # bool flag (atomic store; the runner only ever flips it on)
        self._thread = None

    def start(self, run_s: float) -> "ScenarioRunner":
        self._thread = spawn_thread(self._run, run_s, daemon=True,
                                    name=f"chaos:{self.scenario.name}")
        return self

    def join(self, timeout: float = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def stop(self) -> None:
        """Abort between events: remaining ARMs are skipped (a teardown
        must never inject new faults into a cluster being stopped),
        already-armed faults still HEAL, and recovery waits are skipped
        so the abort is prompt. A running arm/heal completes first."""
        self._abort = True

    @property
    def failures(self) -> list:
        return self.journal.failures

    # ------------------------------------------------------------- engine

    def _run(self, run_s: float):
        # schedule times are relative to the RUNNER's start (= load
        # start), not the journal's creation — harness build time must
        # not eat the front of the schedule
        epoch = time.monotonic()
        open_windows = {}   # (action.name, k) -> window id; runner-thread-
        # local: only this loop touches it
        arm_failed = set()  # occurrences whose arm() raised — their heal/
        # recovery must not run (healing an unarmed actor cascades one
        # failure into spurious actor.heal + recovery.deadline ones)
        for t, what, action, k in self.scenario.timeline(run_s):
            self._sleep_until(epoch + t)
            occ = f"{action.name}#{k}" if action.every_s else action.name
            actor = self.actors[action.actor]
            if what == "arm":
                if self._abort:
                    continue   # aborted: never arm a NEW fault
                counters.rate("chaos.faults_armed").increment()
                open_windows[(action.name, k)] = self.windows.open(occ)
                self.journal.record("fault.armed", action=occ,
                                    actor=action.actor, scheduled_t=t)
                try:
                    actor.arm(**action.args)
                except Exception as e:  # noqa: BLE001 - an actor that
                    # cannot arm is a harness failure, named and fatal
                    self.journal.fail(f"actor.arm:{occ}", error=repr(e))
                    arm_failed.add((action.name, k))
                continue
            if self._abort and (action.name, k) not in open_windows:
                continue   # aborted before this occurrence armed
            if (action.name, k) in arm_failed:
                # nothing armed: close the declared window and move on
                # promptly instead of stalling the schedule on a recovery
                # wait for a fault that never happened
                arm_failed.discard((action.name, k))
                wid = open_windows.pop((action.name, k), None)
                if wid is not None:
                    self.windows.close(wid, settle_s=action.settle_s)
                continue
            try:
                actor.heal()
            except Exception as e:  # noqa: BLE001 - same: named + fatal
                self.journal.fail(f"actor.heal:{occ}", error=repr(e))
            counters.rate("chaos.faults_healed").increment()
            self.journal.record("fault.healed", action=occ, scheduled_t=t)
            if not self._abort:   # an abort must not block on recovery
                self._await_recovery(action, occ, actor)
            wid = open_windows.pop((action.name, k), None)
            if wid is not None:
                self.windows.close(wid, settle_s=action.settle_s)
        self.journal.record("scenario.done", name=self.scenario.name)

    def _await_recovery(self, action: FaultAction, occ: str, actor) -> None:
        deadline = time.monotonic() + action.recovery_deadline_s
        while True:
            try:
                ok = actor.recovered()
            except Exception:  # noqa: BLE001 - a probe error = not yet
                ok = False
            if ok:
                self.journal.record("fault.recovered", action=occ)
                return
            if time.monotonic() >= deadline:
                counters.rate("chaos.recovery_breach_count").increment()
                self.journal.fail(
                    f"recovery.deadline:{occ}",
                    deadline_s=action.recovery_deadline_s,
                    detail=f"actor {action.actor!r} did not report "
                           f"recovered within "
                           f"{action.recovery_deadline_s:.1f}s of heal")
                return
            time.sleep(0.2)

    def _sleep_until(self, deadline: float) -> None:
        while not self._abort:
            dt = deadline - time.monotonic()
            if dt <= 0:
                return
            time.sleep(min(dt, 0.1))


# ------------------------------------------------------ builtin scenarios
# Actor keys the builders reference; tools/pressure_test.py constructs the
# matching actors for its onebox harness.
A_FAILPOINT = "failpoint"
A_GROUP_KILL = "group_kill"
A_NODE_KILL = "node_kill"
A_SPLIT = "split"
A_BALANCE = "balance"
A_SCHED = "sched_flip"
A_OFFLOAD = "offload_kill"
A_DISK_CORRUPT = "disk_corrupt"


def smoke_scenario() -> Scenario:
    """Tier-1 sized (~12 s of load): one group-worker kill + one remote
    fail-point wedge under load — the bounded chaos smoke."""
    return Scenario("smoke", [
        FaultAction("dispatch-wedge", A_FAILPOINT, at_s=2.0, duration_s=5.0,
                    recovery_deadline_s=10.0, settle_s=1.0,
                    args={"point": "serve.dispatch",
                          "action": "20%sleep(40)"}),
        FaultAction("kill-group", A_GROUP_KILL, at_s=3.0, duration_s=3.0,
                    recovery_deadline_s=25.0, settle_s=2.0),
    ])


def full_scenario() -> Scenario:
    """The production-sim flagship schedule (~30 s of load): scheduler
    token flips, a remote fail-point wedge, a mid-load partition split,
    a group-worker kill, a balancer primary move, a whole-node
    kill+restart, and a mid-ship learn abort planted under the node's
    re-seed window (the block-ship plane must resume, not re-seed from
    scratch or wedge) — everything at once, under periodic audit, with
    a duplication leg (set up by the harness) compared cross-cluster at
    the end."""
    return Scenario("full", [
        FaultAction("sched-defer-urgent", A_SCHED, at_s=2.0, duration_s=4.0,
                    recovery_deadline_s=10.0, settle_s=0.5),
        FaultAction("dispatch-wedge", A_FAILPOINT, at_s=3.0, duration_s=4.0,
                    recovery_deadline_s=10.0, settle_s=1.0,
                    args={"point": "serve.dispatch",
                          "action": "20%sleep(40)"}),
        FaultAction("split-double", A_SPLIT, at_s=6.0, duration_s=0.0,
                    recovery_deadline_s=30.0, settle_s=3.0),
        FaultAction("kill-group", A_GROUP_KILL, at_s=11.0, duration_s=3.0,
                    recovery_deadline_s=30.0, settle_s=2.0),
        FaultAction("primary-move", A_BALANCE, at_s=16.0, duration_s=0.0,
                    recovery_deadline_s=15.0, settle_s=2.0),
        FaultAction("kill-node", A_NODE_KILL, at_s=19.0, duration_s=3.0,
                    recovery_deadline_s=40.0, settle_s=3.0),
        # armed on the surviving nodes between kill-node's arm and heal,
        # so the killed node's first repair learns hit mid-ship aborts
        # and must resume at block granularity. COUNT-bounded (first 3
        # hits per process), not probabilistic: the runner thread blocks
        # in kill-node's recovery wait before this action's heal can
        # run, so the fault must self-exhaust — a lingering %-armed
        # abort would fail every repair learn for the whole window
        FaultAction("learn-ship-abort", A_FAILPOINT, at_s=21.0,
                    duration_s=4.0, recovery_deadline_s=10.0, settle_s=1.0,
                    args={"point": "learn.ship",
                          "action": "3*raise(chaos)"}),
    ])


def offload_scenario(kill_every_s: float = None) -> Scenario:
    """Rack-scale offload leg (ISSUE 14), for a harness that wired an
    offload service + placements: a `compact.offload` wire wedge, then
    a hard service kill mid-merge — both windows must close with the
    nodes' offload lane having degraded to byte-identical local cpu
    merges (zero lost acked writes; the driving test compares post-run
    digests against an un-offloaded control). `kill_every_s` (ROADMAP
    offload follow-on (d): the longer pressure_test soak) repeats the
    service kill on that period for the whole run instead of once —
    must exceed the kill's 4 s heal window."""
    return Scenario("offload", [
        FaultAction("offload-wire-wedge", A_FAILPOINT, at_s=1.0,
                    duration_s=3.0, recovery_deadline_s=10.0, settle_s=1.0,
                    args={"point": "compact.offload",
                          "action": "3*sleep(100)"}),
        FaultAction("kill-offload-service", A_OFFLOAD, at_s=5.0,
                    duration_s=4.0, every_s=kill_every_s,
                    recovery_deadline_s=20.0, settle_s=2.0),
    ])


def corruption_scenario() -> Scenario:
    """Data-integrity leg (ISSUE 17): silent bit-rot under write load.
    First a `scrub.verify` fail-point window proves the background scrub
    itself survives injected verify faults without quarantining healthy
    replicas (lane-guard breakers must stay untouched throughout — the
    driving harness asserts that), then the disk-corrupt actor byte-flips
    a live SST and the window only closes after the full loop: typed
    detection, quarantine with the forensics dir retained, meta re-seed
    via the block-shipped learn, and full re-replication. The harness
    finishes with a conclusive mismatch-free audit round + fsck."""
    return Scenario("corruption", [
        FaultAction("scrub-verify-chaos", A_FAILPOINT, at_s=1.0,
                    duration_s=3.0, recovery_deadline_s=10.0, settle_s=0.5,
                    args={"point": "scrub.verify",
                          "action": "2*raise(chaos)"}),
        FaultAction("disk-corrupt", A_DISK_CORRUPT, at_s=5.0,
                    duration_s=1.0, recovery_deadline_s=40.0, settle_s=2.0),
    ])


SCENARIOS = {"smoke": smoke_scenario, "full": full_scenario,
             "offload": offload_scenario,
             "corruption": corruption_scenario}
