"""Event journal: the chaos run's timeline artifact (ISSUE 11).

Everything the scenario engine, the audit cadence and the load harness
conclude lands here as one timestamped event stream — fault armed/healed,
recovery verified/breached, audit-round trajectory, error-window bounds,
doctor verdicts, and the named failures that decide the exit code. The
run emits it as a JSON artifact so a red run always says WHICH leg
failed and WHEN, not just "exit 1".
"""

import json
import time

from ..runtime import lockrank
from ..runtime.perf_counters import counters


class EventJournal:
    """Thread-safe append-only event timeline. Timestamps are seconds
    relative to the journal's creation (the run's t=0), so a journal
    reads as a timeline, not a wall-clock log."""

    def __init__(self):
        self.t0 = time.monotonic()
        self._wall0 = time.time()
        self._lock = lockrank.named_lock("chaos.journal")
        self._events = []    #: guarded_by self._lock
        self._failures = []  #: guarded_by self._lock
        # optional flight-recorder hook (ISSUE 12): called with the
        # failure event AFTER it is journaled — pressure_test wires an
        # incident capture here so the cluster's recorded past is pulled
        # AT failure time, not after teardown erased it. Set before the
        # run starts; never called under the journal lock.
        self.on_fail = None

    def now(self) -> float:
        return time.monotonic() - self.t0

    def record(self, kind: str, **fields) -> dict:
        ev = {"t": round(self.now(), 3), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)
        return ev

    def fail(self, name: str, **fields) -> dict:
        """A named failure: recorded as an event AND remembered in the
        failure list the run's exit code folds. `name` is the machine-
        readable failure key (e.g. ``recovery.deadline:kill-node``)."""
        counters.rate("chaos.failure_count").increment()
        ev = self.record("failure", failure=name, **fields)
        with self._lock:
            self._failures.append(ev)
        if self.on_fail is not None:
            try:
                self.on_fail(ev)
            except Exception as e:  # noqa: BLE001 - evidence capture must
                # never turn one named failure into two
                self.record("incident.capture_error", error=repr(e))
        return ev

    @property
    def failures(self) -> list:
        with self._lock:
            return list(self._failures)

    def events(self, kind: str = None) -> list:
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def dump(self) -> dict:
        """The artifact: start wall-clock, the full timeline, and the
        failure digest."""
        with self._lock:
            return {"started_at": self._wall0,
                    "events": list(self._events),
                    "failures": list(self._failures)}

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(), f, indent=1)


class FaultWindows:
    """Declared fault windows: the intervals during which transient
    errors are ALLOWED (ISSUE 11 satellite — a failover blip inside an
    armed fault's window must not fail the run; the same error in steady
    state must). A window opens when a fault arms and closes
    ``settle_s`` after it heals (recovery is not instantaneous: a killed
    group re-serves only after restart+replay). Instantaneous faults
    (split, balancer move) still open a bounded window — the client-
    visible reconfiguration blip is part of the declared fault."""

    def __init__(self, journal: EventJournal = None):
        self.journal = journal
        self._lock = lockrank.named_lock("chaos.windows")
        # entries are [start, end|None, name]
        self._windows = []  #: guarded_by self._lock

    def open(self, name: str, settle_s: float = 0.0) -> int:
        """-> window id. settle_s here pads the START backward (unused
        today; symmetry with close)."""
        t = self._now()
        with self._lock:
            self._windows.append([t - settle_s, None, name])
            wid = len(self._windows) - 1
        counters.number("chaos.active_fault_windows").set(self._open_count())
        return wid

    def close(self, wid: int, settle_s: float = 0.0) -> None:
        t = self._now()
        with self._lock:
            if 0 <= wid < len(self._windows):
                self._windows[wid][1] = t + settle_s
        counters.number("chaos.active_fault_windows").set(self._open_count())

    def _now(self) -> float:
        return self.journal.now() if self.journal is not None \
            else time.monotonic()

    def _open_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._windows if w[1] is None)

    def in_window(self, t: float = None) -> bool:
        """Was instant `t` (journal-relative; default now) inside any
        declared fault window?"""
        t = self._now() if t is None else t
        with self._lock:
            return any(s <= t and (e is None or t <= e)
                       for s, e, _ in self._windows)

    def bounds(self) -> list:
        """[{name, start, end}] — the journal artifact's window table."""
        with self._lock:
            return [{"name": n, "start": round(s, 3),
                     "end": None if e is None else round(e, 3)}
                    for s, e, n in self._windows]


# module-import registration keeps the metric-name lint's reverse pass
# honest for the dynamic set() sites above
counters.rate("chaos.failure_count")
counters.number("chaos.active_fault_windows")
