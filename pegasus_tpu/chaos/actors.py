"""Fault actors: the arm/heal implementations the scenario engine drives.

Each actor reuses EXISTING cluster machinery rather than inventing a
parallel one (ISSUE 11): node kill+restart goes through the onebox
cluster handles + the meta's failure detector and repair path,
group-worker kill through `GroupedReplicaNode.kill_group/restart_group`
(exercising the restart replay), fail points through the new
``set-fail-point`` remote command (live arming in remote server
processes), the split through ``RPC_CM_SPLIT_APP``, the primary move
through the balancer's ``RPC_CM_PROPOSE``, and the scheduler flip
through the ``compact-sched-policy`` delivery surface.

Actors hold onebox/MiniCluster handles (``cluster``: an object with
``stubs`` (list of replica nodes), ``meta`` (in-process MetaServer),
``meta_addr`` and ``ddl(code, req, resp_cls)``) — this is the chaos
harness's process, so in-process handles are the honest interface; every
fault they inject still lands on the cluster over real sockets.
"""

import json
import time

from ..meta import messages as mm
from ..meta.meta_server import (RPC_CM_PROPOSE, RPC_CM_QUERY_CONFIG,
                                RPC_CM_SPLIT_APP)
from ..rpc.transport import RpcError


class FaultActor:
    """Base: arm() injects the fault, heal() removes it, recovered()
    reports whether the cluster has fully re-converged after heal (the
    runner polls it against the action's recovery deadline)."""

    def arm(self, **args):
        raise NotImplementedError

    def heal(self):
        pass

    def recovered(self) -> bool:
        return True


def _cluster_state(cluster, caller=None) -> dict:
    """The meta's one-RPC cluster-state snapshot, over the public RPC
    surface (None when the meta cannot answer). A provided `caller`
    (cluster_doctor.ClusterCaller) is REUSED — recovery polls run every
    0.2 s, and opening a fresh TCP connection per poll piles hundreds of
    short-lived sockets onto a recovering cluster."""
    from ..collector.cluster_doctor import ClusterCaller

    if caller is not None:
        return caller.meta_state()
    caller = ClusterCaller([cluster.meta_addr])
    try:
        return caller.meta_state()
    finally:
        caller.close()


def _alive_nodes(cluster, caller=None) -> list:
    state = _cluster_state(cluster, caller) or {}
    return sorted(a for a, n in state.get("nodes", {}).items()
                  if n.get("alive"))


def _fully_replicated(cluster, caller=None) -> bool:
    """Every partition of every app has a live primary and a full live
    member set — the doctor-healthy bar for membership."""
    state = _cluster_state(cluster, caller)
    if state is None:
        return False
    alive = {a for a, n in state.get("nodes", {}).items() if n.get("alive")}
    if not alive:
        return False
    for app in state.get("apps", {}).values():
        want = app.get("replica_count", 0)
        for pc in app.get("partitions", []):
            members = [m for m in [pc.get("primary")]
                       + pc.get("secondaries", []) if m]
            live = [m for m in members if m in alive]
            if not pc.get("primary") or pc["primary"] not in alive:
                return False
            if want and len(live) < want:
                return False
    return True


class NodeKillRestart(FaultActor):
    """Hard-stop one replica NODE (the meta declares it dead and fails
    over), then restart it on the SAME address and drive the meta's
    repair path until every partition is fully replicated again. Works
    for both plain ReplicaStub nodes and grouped nodes (whose group
    workers are real OS processes)."""

    def __init__(self, cluster, node_index: int = -1, caller=None):
        self.cluster = cluster
        self.node_index = node_index
        self.caller = caller
        self._spec = None
        self._node = None
        self._last_repair = 0.0
        self._ship_ok = False

    def arm(self, node_index: int = None):
        self._ship_ok = False
        idx = self.node_index if node_index is None else node_index
        victim = self.cluster.stubs[idx]
        addr = victim.address
        _, _, port = addr.rpartition(":")
        spec = {"addr": addr, "port": int(port), "root": victim.root,
                "metas": list(victim.meta_addrs)}
        if hasattr(victim, "kill_group"):     # GroupedReplicaNode
            spec.update(kind="grouped", groups=victim.groups,
                        base=dict(victim._spec_base))
        else:
            spec.update(kind="stub",
                        options_factory=victim.options_factory,
                        remote_clusters=dict(victim.remote_clusters),
                        cluster_id=victim.cluster_id)
        self._spec = spec
        self.cluster.stubs.remove(victim)
        victim.stop()
        self.cluster.meta.mark_node_dead(addr)

    def heal(self):
        s = self._spec
        # prefer the SAME address (a restarted machine keeps its name);
        # lingering sockets from the killed node's accepted connections
        # can hold the port for a while, so retry, then fall back to a
        # fresh address — a replacement node — and drop the old one's
        # tombstone from the meta so it does not read as dead forever
        node = None
        deadline = time.monotonic() + 8.0
        while node is None:
            try:
                node = self._build(s, s["port"])
            except OSError:
                if time.monotonic() >= deadline:
                    node = self._build(s, 0)
                    self.cluster.meta.forget_node(s["addr"])
                else:
                    time.sleep(0.5)
        self._node = node
        self.cluster.stubs.append(node)

    def _build(self, s, port: int):
        if s["kind"] == "grouped":
            from ..replication.serve_groups import GroupedReplicaNode

            base = s["base"]
            return GroupedReplicaNode(
                s["root"], s["metas"], port=port, groups=s["groups"],
                backend=base["backend"], compression=base["compression"],
                sharded_compaction=base["sharded_compaction"],
                remote_clusters=base["remote_clusters"],
                cluster_id=base["cluster_id"]).start(0.2)
        from ..replication.replica_stub import ReplicaStub

        return ReplicaStub(
            s["root"], s["metas"], port=port,
            options_factory=s["options_factory"],
            remote_clusters=s["remote_clusters"],
            cluster_id=s["cluster_id"]).start(0.2)

    def _restarted_addr(self) -> str:
        return self._node.address

    def recovered(self) -> bool:
        if self._restarted_addr() not in _alive_nodes(self.cluster,
                                                      self.caller):
            return False
        # the rejoined node is alive but partitions lost a member while
        # it was down and nothing re-examines them on a join — re-drive
        # the meta's repair pass (a failed learner seed needs a retry),
        # but not on every 0.2 s poll: each pass scans every partition
        # under the meta lock and persists a ballot bump while a seed
        # keeps failing
        now = time.monotonic()
        if now - self._last_repair >= 1.0:
            self._last_repair = now
            self.cluster.meta.repair_under_replication()
        if not _fully_replicated(self.cluster, self.caller):
            return False
        # recovery is only REAL when the repair re-seeds went through the
        # block-ship learn plane (ISSUE 13): counter-assert the restarted
        # node's monotone learn.ship totals moved — a fully-replicated
        # verdict with zero learns would mean the meta never re-seeded
        # the partitions that lost this member
        return self._block_ship_verified()

    def _block_ship_verified(self) -> bool:
        if self._ship_ok or self.caller is None:
            return True
        try:
            out = json.loads(self.caller.remote_command(
                self._restarted_addr(), "learn-status", []))
        except (RpcError, OSError, ValueError):
            return False
        # delta_skipped counts too: a restarted node whose disk survived
        # legitimately re-ships only what changed while it was down
        self._ship_ok = (out.get("ship.blocks", 0)
                         + out.get("ship.delta_skipped_blocks", 0)) > 0
        return self._ship_ok


class GroupWorkerKill(FaultActor):
    """Hard-kill one partition-group executor PROCESS of a grouped node,
    then restart it (PR 6's restart_group replay: the parent replays its
    cached open-replica state so the group re-serves without waiting for
    the meta's next proposal round)."""

    def __init__(self, cluster, node_index: int = 0, group: int = None):
        self.cluster = cluster
        self.node_index = node_index
        self.group = group
        self._target = None

    def arm(self, node_index: int = None, group: int = None):
        idx = self.node_index if node_index is None else node_index
        stub = self.cluster.stubs[idx]
        if not hasattr(stub, "kill_group"):
            raise RuntimeError(f"node {stub.address} is not group-served "
                               "(need serve_groups >= 2)")
        g = self.group if group is None else group
        if g is None:
            g = stub.groups - 1
        self._target = (stub, g)
        stub.kill_group(g)

    def heal(self):
        stub, g = self._target
        stub.restart_group(g)

    def recovered(self) -> bool:
        stub, g = self._target
        return stub.group_alive(g)


class FailPointActor(FaultActor):
    """Live fail-point arming in REMOTE server processes over the
    ``set-fail-point`` remote command: a grouped node's router fans the
    command to every worker process, so the point arms where the
    serving actually happens. heal() re-arms with ``off()``."""

    def __init__(self, caller, nodes_fn=None):
        """caller: cluster_doctor.ClusterCaller (remote_command surface);
        nodes_fn: () -> target node addresses (default: every alive
        node at arm time)."""
        self.caller = caller
        self.nodes_fn = nodes_fn
        self._armed = None   # (point, [nodes]) while armed

    def arm(self, point: str = "", action: str = "", nodes=None):
        if not point or not action:
            raise ValueError("FailPointActor needs point= and action=")
        targets = list(nodes) if nodes else (self.nodes_fn or list)()
        if not targets:
            raise RuntimeError("no target nodes to arm")
        armed = []
        errors = []
        for n in targets:
            try:
                out = self.caller.remote_command(n, "set-fail-point",
                                                 [point, action])
                if out.startswith("bad fail point"):
                    raise ValueError(out)
                armed.append(n)
            except (RpcError, OSError, ValueError) as e:
                errors.append(f"{n}: {e}")
        self._armed = (point, armed)
        if not armed:
            raise RuntimeError(f"set-fail-point armed nowhere: {errors}")

    def heal(self):
        if self._armed is None:
            return
        point, nodes = self._armed
        self._armed = None
        stubborn = []
        for n in nodes:
            try:
                self.caller.remote_command(n, "set-fail-point",
                                           [point, "off()"])
            except (RpcError, OSError) as e:
                stubborn.append(f"{n}: {e}")
        if stubborn:
            # an unhealed fail point means undeclared faults after the
            # window closes — that must surface as a heal failure
            raise RuntimeError(f"set-fail-point off() failed: {stubborn}")


class SplitActor(FaultActor):
    """Mid-load online partition split: doubles the app's partition
    count through ``RPC_CM_SPLIT_APP`` while the load keeps running;
    clients re-resolve on the partition-hash rejection path."""

    def __init__(self, cluster, app: str, caller=None):
        self.cluster = cluster
        self.app = app
        self.caller = caller
        self._want = None

    def arm(self):
        # the split RPC is synchronous through phase-2 child seeding
        # (full-copy learns, one history source) — under load on a
        # saturated box that legitimately runs past the default 30 s
        # DDL timeout, and a client-side timeout here would abandon a
        # split that IS completing (the harness then mis-reads the
        # doubled partition count as an arm failure). A seeding failure
        # mid-load is retryable by contract: the meta resumes the
        # incomplete split (replica.split_pending marker) instead of
        # doubling again.
        last = None
        for _ in range(4):
            r = self.cluster.ddl(RPC_CM_SPLIT_APP,
                                 mm.SplitAppRequest(self.app),
                                 mm.SplitAppResponse, timeout=180.0)
            if not r.error:
                self._want = r.new_partition_count
                return
            last = r.error_text
            if "re-run split" not in (last or ""):
                break
            time.sleep(2.0)
        raise RuntimeError(f"split failed: {last}")

    def recovered(self) -> bool:
        state = _cluster_state(self.cluster, self.caller)
        if state is None:
            return False
        app = state.get("apps", {}).get(self.app)
        if not app or app.get("partition_count") != self._want:
            return False
        return _fully_replicated(self.cluster, self.caller)


class BalanceActor(FaultActor):
    """Balancer leg: move one partition's primary to a secondary (the
    greedy balancer's move_primary proposal) mid-load."""

    def __init__(self, cluster, app: str, pidx: int = 0, caller=None):
        self.cluster = cluster
        self.app = app
        self.pidx = pidx
        self.caller = caller
        self._want = None

    def arm(self, pidx: int = None):
        p = self.pidx if pidx is None else pidx
        cfg = self.cluster.ddl(RPC_CM_QUERY_CONFIG,
                               mm.QueryConfigRequest(self.app),
                               mm.QueryConfigResponse)
        pc = cfg.partitions[p]
        if not pc.secondaries:
            raise RuntimeError(f"partition {p} has no secondary to move to")
        target = sorted(pc.secondaries)[0]
        r = self.cluster.ddl(RPC_CM_PROPOSE,
                             mm.ProposeRequest(self.app, p, target),
                             mm.ProposeResponse)
        if r.error:
            raise RuntimeError(f"propose failed: {r.error_text}")
        self._want = (p, target)

    def recovered(self) -> bool:
        p, target = self._want
        cfg = self.cluster.ddl(RPC_CM_QUERY_CONFIG,
                               mm.QueryConfigRequest(self.app),
                               mm.QueryConfigResponse)
        return cfg.partitions[p].primary == target \
            and _fully_replicated(self.cluster, self.caller)


class OffloadServiceKill(FaultActor):
    """Hard-stop the rack's compaction-offload service mid-merge
    (ISSUE 14): every cpu-only node whose placement lease still names it
    must degrade through the offload lane guard to its LOCAL cpu merge —
    byte-identical by construction, never a stall, zero lost acked
    writes — then pick the service back up when it restarts. `ctl` is a
    handle with ``stop()`` / ``restart()`` / ``address`` (the harness's
    in-process service or a process wrapper); recovered() = the service
    answers ``offload-status`` on its address again."""

    def __init__(self, ctl, caller=None):
        self.ctl = ctl
        self.caller = caller

    def arm(self):
        self.ctl.stop()

    def heal(self):
        self.ctl.restart()

    def recovered(self) -> bool:
        from ..collector.cluster_doctor import ClusterCaller

        caller = self.caller or ClusterCaller([])
        try:
            out = caller.remote_command(self.ctl.address, "offload-status",
                                        [])
            return bool(json.loads(out).get("address"))
        except (RpcError, OSError, ValueError):
            return False
        finally:
            if self.caller is None:
                caller.close()


class SchedFlipActor(FaultActor):
    """Compaction-scheduler token flips: deliver DEFER tokens for every
    partition of the app at arm (the engines hold elective L0 merges),
    then flip to short-lived URGENT tokens at heal (early-fire + queue
    jump), which lease-expire back to normal — the ``compact-sched-
    policy`` delivery surface the cluster scheduler itself uses."""

    def __init__(self, caller, cluster, app: str):
        self.caller = caller
        self.cluster = cluster
        self.app = app
        self._flip_at = 0.0

    def _deliver(self, policy: str, ttl_s: float):
        state = _cluster_state(self.cluster, self.caller)
        if state is None:
            raise RuntimeError("no cluster state for sched delivery")
        app = state.get("apps", {}).get(self.app)
        if app is None:
            raise RuntimeError(f"no app {self.app!r}")
        decisions = {f"{app['app_id']}.{pc['pidx']}":
                     {"policy": policy, "reasons": ["chaos.flip"]}
                     for pc in app.get("partitions", [])}
        body = json.dumps({"ttl_s": ttl_s, "decisions": decisions})
        delivered = 0
        for node in sorted(a for a, n in state.get("nodes", {}).items()
                           if n.get("alive")):
            try:
                self.caller.remote_command(node, "compact-sched-policy",
                                           [body])
                delivered += 1
            except (RpcError, OSError):
                continue
        if not delivered:
            raise RuntimeError("sched policy delivered to no node")

    def arm(self, ttl_s: float = 30.0):
        self._deliver("defer", ttl_s)

    def heal(self):
        # the flip: urgent with a short lease, then expiry back to normal
        self._deliver("urgent", 3.0)
        self._flip_at = time.monotonic()

    def recovered(self) -> bool:
        # recovered = the urgent lease expired (tokens revert to normal)
        return time.monotonic() - self._flip_at >= 3.0


class DiskCorruptActor(FaultActor):
    """Silent bit-rot (ISSUE 17): byte-flip a live SST of one hosted
    replica on a victim node's disk, then drive the self-healing loop —
    detection (a forced scrub, unless the read path trips first),
    quarantine (the stub pulls the copy into the forensics dir and
    beacons QUARANTINED), and heal (the meta's `repair_quarantined`
    drops the lost member and re-seeds it via the block-shipped learn).
    recovered() only reports True once the quarantine was OBSERVED and
    membership is fully replicated again — a corruption that silently
    disappears is a failed leg, not a recovery."""

    def __init__(self, cluster, node_index: int = 0, caller=None):
        self.cluster = cluster
        self.node_index = node_index
        self.caller = caller
        self._victim = None      # (stub, "app_id.pidx", sst path)
        self._detected = False
        self._last_repair = 0.0

    def arm(self, node_index: int = None):
        import glob
        import os

        self._detected = False
        idx = self.node_index if node_index is None else node_index
        stub = self.cluster.stubs[idx]
        with stub._lock:
            keys = sorted(stub._replicas)
            reps = dict(stub._replicas)
        for (a, p) in keys:
            data = os.path.join(stub.root, f"{a}.{p}", "data")
            ssts = sorted(glob.glob(os.path.join(data, "*.sst")))
            if not ssts:
                # nothing durable yet: force a synchronous memtable flush
                # so the victim partition has an on-disk file to rot
                try:
                    reps[(a, p)].server.engine.flush()
                except Exception:  # noqa: BLE001 - try the next replica
                    continue
                ssts = sorted(glob.glob(os.path.join(data, "*.sst")))
            if not ssts:
                continue
            path = ssts[-1]
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.seek(size // 2)
                chunk = f.read(8)
                f.seek(size // 2)
                f.write(bytes(b ^ 0xFF for b in chunk))
            self._victim = (stub, f"{a}.{p}", path)
            return
        raise RuntimeError(f"node {idx} hosts no SST to corrupt")

    def _quarantined(self, stub, gpid: str) -> bool:
        with stub._lock:
            return gpid in stub._quarantined

    def recovered(self) -> bool:
        stub, gpid, _ = self._victim
        if not self._detected:
            if not self._quarantined(stub, gpid):
                # deterministic detection: force the background scrub's
                # verify pass now (idempotent; a no-op if the read path
                # already quarantined the replica between the checks)
                try:
                    if self.caller is not None:
                        self.caller.remote_command(stub.address,
                                                  "scrub-replica", [gpid])
                    else:
                        stub._cmd_scrub_replica([gpid])
                except (RpcError, OSError):
                    return False
            if not self._quarantined(stub, gpid):
                return False
            self._detected = True
        # heal: the meta treats the beaconed QUARANTINED copy as lost
        # (membership drop + learner re-seed). Same 1 s pacing as the
        # node-kill actor — each pass scans partitions under the meta
        # lock and a failing seed should not ballot-bump every 0.2 s
        now = time.monotonic()
        if now - self._last_repair >= 1.0:
            self._last_repair = now
            self.cluster.meta.repair_quarantined()
            self.cluster.meta.repair_under_replication()
        if self._quarantined(stub, gpid):
            return False  # re-seed has not re-opened the partition here
        return _fully_replicated(self.cluster, self.caller)
