from .meta_server import MetaServer

__all__ = ["MetaServer"]
