"""Leader election + lease for the meta-server replica set.

The reference runs THREE meta servers whose election and state both live
in ZooKeeper: `meta_state_service_type = meta_state_service_zookeeper` +
`distributed_lock_service_zookeeper` (reference
src/server/config.ini:160-167, :380-383) and the onebox boots
META_COUNT=3 (run.sh:509). This build's analogue keeps both halves on
SHARED DURABLE STORAGE — a directory every meta can reach (the onebox
shares the local filesystem; multi-host deployments mount it via NFS or
the block-service providers, exactly the role ZK plays for the
reference):

  - the LEASE FILE is the distributed lock: its content names the
    leader, its mtime is the heartbeat. A leader refreshes it every
    lease/3; anyone finding it older than the lease takes over with an
    atomic replace + settle-and-reread round that resolves concurrent
    takeovers (last writer wins, every racer re-reads after a settle
    delay, losers demote).
  - the shared state.json is the replicated meta state: every mutating
    DDL persists BEFORE acknowledging (meta_server handlers), and a new
    leader reloads it on takeover — so any write the old leader
    acknowledged is visible after its SIGKILL. That is the HA contract
    tests/test_process_kill.py::test_meta_leader_kill asserts.

Followers redirect every RPC except beacons with ERR_FORWARD_TO_PRIMARY;
clients/shell/replicas already fall through their meta list, so
redirection needs no routing table — the leader is whoever doesn't
refuse.
"""

import os
import threading
import time


class MetaElection:
    def __init__(self, lock_path: str, my_addr: str,
                 lease_seconds: float = 6.0, on_acquire=None,
                 on_demote=None, settle_seconds: float = None):
        self.lock_path = lock_path
        self.my_addr = my_addr
        self.lease = lease_seconds
        self.on_acquire = on_acquire
        self.on_demote = on_demote
        # long enough for a concurrent racer's replace to land, short
        # enough to keep failover well under the FD grace
        self.settle = (settle_seconds if settle_seconds is not None
                       else min(0.2, lease_seconds / 10))
        self._leader = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"meta-election:{my_addr}")

    # ------------------------------------------------------------- queries

    def is_leader(self) -> bool:
        return self._leader

    def leader(self):
        """Current lease holder per the lock file (None if no live lease);
        serves as the redirect hint in follower refusals."""
        holder, age = self._read()
        if holder is None or age > self.lease:
            return None
        return holder

    # ----------------------------------------------------------- lifecycle

    def start(self):
        self._tick()  # synchronous first round: a lone meta is leader
        self._thread.start()  # by the time start() returns
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=self.lease)
        if self._leader:
            # graceful release: delete our lease so the next leader does
            # not wait out the staleness window
            holder, _ = self._read()
            if holder == self.my_addr:
                try:
                    os.unlink(self.lock_path)
                except OSError:
                    pass
            self._set_leader(False)

    # ------------------------------------------------------------ internals

    def _loop(self):
        while not self._stop.wait(self.lease / 3):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 - a dead election thread
                # would freeze leadership forever; log and keep ticking
                print(f"[meta-election] {self.my_addr}: {e!r}", flush=True)

    def _tick(self):
        holder, age = self._read()
        if holder == self.my_addr:
            self._refresh()
            # re-read: our refresh and a racer's takeover can interleave
            holder, _ = self._read()
            self._set_leader(holder == self.my_addr)
        elif holder is None or age > self.lease:
            self._try_claim()
        else:
            self._set_leader(False)

    def _read(self):
        """-> (holder_addr | None, age_seconds)."""
        try:
            with open(self.lock_path) as f:
                holder = f.read().strip()
            age = time.time() - os.stat(self.lock_path).st_mtime
            return (holder or None), age
        except OSError:
            return None, float("inf")

    def _refresh(self):
        self._write_lease()

    def _write_lease(self):
        tmp = f"{self.lock_path}.{self.my_addr.replace(':', '_')}.tmp"
        os.makedirs(os.path.dirname(self.lock_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(self.my_addr)
        os.replace(tmp, self.lock_path)

    def _try_claim(self):
        self._write_lease()
        # settle-and-reread: concurrent claimants all replaced the file;
        # exactly one write landed last. Everyone re-reads after a settle
        # delay and only the survivor leads.
        time.sleep(self.settle)
        holder, _ = self._read()
        self._set_leader(holder == self.my_addr)

    def _set_leader(self, value: bool):
        if value == self._leader:
            return
        self._leader = value
        cb = self.on_acquire if value else self.on_demote
        if cb is not None:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 - callback bugs must not
                print(f"[meta-election] {self.my_addr} callback: {e!r}",
                      flush=True)  # kill the election thread
