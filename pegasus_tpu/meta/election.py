"""Leader election + lease for the meta-server replica set.

The reference runs THREE meta servers whose election and state both live
in ZooKeeper: `meta_state_service_type = meta_state_service_zookeeper` +
`distributed_lock_service_zookeeper` (reference
src/server/config.ini:160-167, :380-383) and the onebox boots
META_COUNT=3 (run.sh:509). This build's analogue keeps both halves on
SHARED DURABLE STORAGE — a directory every meta can reach (the onebox
shares the local filesystem; multi-host deployments mount it via NFS or
the block-service providers, exactly the role ZK plays for the
reference):

  - the LEASE FILE is the distributed lock: its content names the
    leader and carries a monotonic EPOCH (fencing token), its mtime is
    the heartbeat. A leader refreshes it every lease/3; anyone finding
    it older than the lease takes over with epoch+1 via an atomic
    replace + settle-and-reread round that resolves concurrent
    takeovers (last writer wins, every racer re-reads after a settle
    delay, losers demote). The epoch fences stale self-believing
    leaders: a leader stalled past its lease (GIL pause, NFS hang) that
    wakes up and tries to persist re-verifies the lease first and
    refuses to clobber state written under a newer epoch
    (verify_for_persist / meta_server._persist_locked).
  - the shared state.json is the replicated meta state: every mutating
    DDL persists BEFORE acknowledging (meta_server handlers), and a new
    leader reloads it on takeover — so any write the old leader
    acknowledged is visible after its SIGKILL. That is the HA contract
    tests/test_process_kill.py::test_meta_leader_kill asserts.

Followers redirect every RPC except beacons with ERR_FORWARD_TO_PRIMARY;
clients/shell/replicas already fall through their meta list, so
redirection needs no routing table — the leader is whoever doesn't
refuse.
"""

import os
import threading
import time

from ..runtime.tasking import spawn_thread


class MetaElection:
    def __init__(self, lock_path: str, my_addr: str,
                 lease_seconds: float = 6.0, on_acquire=None,
                 on_demote=None, settle_seconds: float = None,
                 claim_floor=None):
        self.lock_path = lock_path
        self.my_addr = my_addr
        self.lease = lease_seconds
        self.on_acquire = on_acquire
        self.on_demote = on_demote
        # claim_floor() -> int: a durable lower bound for claim epochs (the
        # meta wires its state-file epoch here). Without it, a graceful
        # release that dropped the lease file would reset the epoch lineage
        # to 0 and every later persist would be fenced by the state file
        # forever — the exact livelock the r5 review caught.
        self.claim_floor = claim_floor or (lambda: 0)
        # long enough for a concurrent racer's replace to land, short
        # enough to keep failover well under the FD grace
        self.settle = (settle_seconds if settle_seconds is not None
                       else min(0.2, lease_seconds / 10))
        self._leader = False
        self.epoch = 0  # fencing token: the epoch we claimed under
        self._stop = threading.Event()
        self._started = False
        self._thread = spawn_thread(self._loop, daemon=True, start=False,
                                    name=f"meta-election:{my_addr}")

    # ------------------------------------------------------------- queries

    def is_leader(self) -> bool:
        return self._leader

    def leader(self):
        """Current lease holder per the lock file (None if no live lease);
        serves as the redirect hint in follower refusals."""
        holder, age, _ = self._read()
        if holder is None or age > self.lease:
            return None
        return holder

    def verify_for_persist(self) -> bool:
        """Re-read the lease immediately before a shared-state persist.
        True only if this meta still holds it; on loss, demote in place so
        the caller's skip and the next tick's callbacks agree."""
        holder, age, epoch = self._read()
        ok = holder == self.my_addr and age <= self.lease
        if not ok:
            self._set_leader(False)
        else:
            # a racer may have bumped the epoch and then crashed before we
            # noticed; never persist under an epoch older than the lease's
            self.epoch = max(self.epoch, epoch)
        return ok

    # ----------------------------------------------------------- lifecycle

    def start(self):
        self._tick()  # synchronous first round: a lone meta is leader
        self._started = True
        self._thread.start()  # by the time start() returns
        return self

    def stop(self):
        self._stop.set()
        if self._started:  # stop() before/after a failed start() must not
            self._thread.join(timeout=self.lease)  # join an unstarted thread
        if self._leader:
            # graceful release: clear the holder so the next leader does
            # not wait out the staleness window — but KEEP the epoch: the
            # lineage must stay monotonic across releases or the next
            # claimant would claim under an epoch the state file has
            # already passed and fence itself forever
            holder, _, epoch = self._read()
            if holder == self.my_addr:
                self.release_lease(max(epoch, self.epoch))
            self._set_leader(False)

    def release_lease(self, epoch: int = None):
        """Write an UNHELD lease carrying the epoch lineage forward."""
        tmp = f"{self.lock_path}.{self.my_addr.replace(':', '_')}.tmp"
        os.makedirs(os.path.dirname(self.lock_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(f"\n{self.epoch if epoch is None else epoch}")
        os.replace(tmp, self.lock_path)

    # ------------------------------------------------------------ internals

    def _loop(self):
        while not self._stop.wait(self.lease / 3):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 - a dead election thread
                # would freeze leadership forever; log and keep ticking
                print(f"[meta-election] {self.my_addr}: {e!r}", flush=True)

    def _tick(self):
        holder, age, epoch = self._read()
        if holder == self.my_addr and age <= self.lease:
            self.epoch = max(self.epoch, epoch)
            self._refresh()
            # re-read: our refresh and a racer's takeover can interleave
            holder, _, _ = self._read()
            self._set_leader(holder == self.my_addr)
        elif holder is None or age > self.lease:
            # holder == us with an EXPIRED lease (a stall outlived our own
            # lease) takes this branch too: resuming with a plain refresh
            # would keep the OLD epoch and could clobber a concurrent
            # claimant's epoch+1 lease inside the settle window (ADVICE
            # r5) — re-claim like anyone else, with a bumped epoch
            self._try_claim(lease_epoch=epoch)
        else:
            self._set_leader(False)

    def _read(self):
        """-> (holder_addr | None, age_seconds, epoch)."""
        try:
            with open(self.lock_path) as f:
                lines = f.read().splitlines()
            holder = lines[0].strip() if lines else ""
            try:
                epoch = int(lines[1]) if len(lines) > 1 else 0
            except ValueError:
                epoch = 0
            age = time.time() - os.stat(self.lock_path).st_mtime
            return (holder or None), age, epoch
        except OSError:
            return None, float("inf"), 0

    def _refresh(self):
        self._write_lease()

    def _write_lease(self, epoch: int = None):
        if epoch is None:
            epoch = self.epoch
        tmp = f"{self.lock_path}.{self.my_addr.replace(':', '_')}.tmp"
        os.makedirs(os.path.dirname(self.lock_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            f.write(f"{self.my_addr}\n{epoch}")
        os.replace(tmp, self.lock_path)

    def _try_claim(self, lease_epoch: int = 0):
        # a claim must exceed BOTH lineages: the lease file's (normal
        # succession) and the durable state's (survives lease-file loss)
        try:
            floor = int(self.claim_floor())
        except Exception:  # noqa: BLE001 - an unreadable floor must not
            floor = 0  # block election; the persist-side fence still holds
        epoch = max(lease_epoch, floor) + 1
        from ..runtime import events

        events.emit("meta.epoch_bump", meta=self.my_addr, epoch=epoch)
        self._write_lease(epoch)
        # settle-and-reread: concurrent claimants all replaced the file;
        # exactly one write landed last. Everyone re-reads after a settle
        # delay and only the survivor leads (with the epoch it wrote or a
        # racer's higher one).
        time.sleep(self.settle)
        holder, _, won_epoch = self._read()
        if holder == self.my_addr:
            self.epoch = max(epoch, won_epoch)
        self._set_leader(holder == self.my_addr)

    def _set_leader(self, value: bool):
        if value == self._leader:
            return
        self._leader = value
        from ..runtime import events

        events.emit("meta.election", severity="warn", meta=self.my_addr,
                    leader=value, epoch=self.epoch)
        cb = self.on_acquire if value else self.on_demote
        if cb is not None:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 - callback bugs must not
                print(f"[meta-election] {self.my_addr} callback: {e!r}",
                      flush=True)  # kill the election thread
