"""Meta server: table DDL, partition->replica mapping, beacon FD, failover.

The rDSN meta-server role this build re-provides (SURVEY.md §2.4 'Meta
server' + 'Failure detector'): app state and partition configs live here
(persisted to a JSON state file standing in for the ZooKeeper-backed
meta_state_service), replica nodes register via beacons with lease/grace
semantics (fd_lease_seconds/fd_grace_seconds, config.ini:232-238), and node
death triggers reconfiguration: promote a surviving secondary, then rebuild
replica count by seeding a learner on an under-loaded node — the
greedy_load_balancer's simplest move set.

Serverlet codes: RPC_CM_* (client/DDL) + RPC_FD_BEACON (nodes), matching
the reference's task-code families.
"""

import json
import os
import threading
import time

from ..rpc import codec
from ..rpc.transport import ConnectionPool, ERR_INVALID_STATE, ERR_OBJECT_NOT_FOUND, RpcError
from . import messages as mm

RPC_CM_CREATE_APP = "RPC_CM_START_CREATE_APP"
RPC_CM_DROP_APP = "RPC_CM_START_DROP_APP"
RPC_CM_LIST_APPS = "RPC_CM_LIST_APPS"
RPC_CM_QUERY_CONFIG = "RPC_CM_QUERY_PARTITION_CONFIG_BY_INDEX"
RPC_CM_SET_APP_ENVS = "RPC_CM_UPDATE_APP_ENV"
RPC_CM_LIST_NODES = "RPC_CM_LIST_NODES"
RPC_CM_SPLIT_APP = "RPC_CM_START_PARTITION_SPLIT"
RPC_CM_BACKUP_APP = "RPC_CM_START_BACKUP_APP"
RPC_CM_RESTORE_APP = "RPC_CM_START_RESTORE"
RPC_CM_START_BULK_LOAD = "RPC_CM_START_BULK_LOAD"
RPC_CM_PROPOSE = "RPC_CM_PROPOSE_BALANCER"
RPC_CM_BALANCE = "RPC_CM_START_BALANCE"
RPC_FD_BEACON = "RPC_FD_FAILURE_DETECTOR_PING"

# meta -> replica node
RPC_OPEN_REPLICA = "RPC_CONFIG_PROPOSAL_OPEN_REPLICA"
RPC_CLOSE_REPLICA = "RPC_CONFIG_PROPOSAL_CLOSE_REPLICA"
RPC_REPLICA_STATE = "RPC_QUERY_REPLICA_STATE"
RPC_COLD_BACKUP = "RPC_COLD_BACKUP"
RPC_BULK_LOAD = "RPC_BULK_LOAD"


class MetaServer:
    def __init__(self, state_path: str, fd_grace_seconds: float = 22.0,
                 replica_count: int = 3):
        self.state_path = state_path
        self.fd_grace = fd_grace_seconds
        self.default_replica_count = replica_count
        self._lock = threading.RLock()
        self._apps = {}          # name -> AppInfo
        self._parts = {}         # app_id -> list[PartitionConfig]
        self._nodes = {}         # addr -> last_beacon_monotonic
        self._next_app_id = 1
        self.pool = ConnectionPool()
        self._load()

    # ----------------------------------------------------------- serverlet

    def rpc_handlers(self) -> dict:
        return {
            RPC_CM_CREATE_APP: self._on_create_app,
            RPC_CM_DROP_APP: self._on_drop_app,
            RPC_CM_LIST_APPS: self._on_list_apps,
            RPC_CM_QUERY_CONFIG: self._on_query_config,
            RPC_CM_SET_APP_ENVS: self._on_set_app_envs,
            RPC_CM_LIST_NODES: self._on_list_nodes,
            RPC_CM_SPLIT_APP: self._on_split_app,
            RPC_CM_BACKUP_APP: self._on_backup_app,
            RPC_CM_RESTORE_APP: self._on_restore_app,
            RPC_CM_START_BULK_LOAD: self._on_start_bulk_load,
            RPC_CM_PROPOSE: self._on_propose,
            RPC_CM_BALANCE: self._on_balance,
            RPC_FD_BEACON: self._on_beacon,
        }

    # ----------------------------------------------------------------- DDL

    def _on_create_app(self, header, body) -> bytes:
        req = codec.decode(mm.CreateAppRequest, body)
        with self._lock:
            if req.app_name in self._apps:
                app = self._apps[req.app_name]
                return codec.encode(mm.CreateAppResponse(app_id=app.app_id))
            alive = self._alive_nodes_locked()
            if not alive:
                return codec.encode(mm.CreateAppResponse(
                    error=1, error_text="no alive replica nodes"))
            # partition counts are powers of two: split doubles them and the
            # ownership filter is a bit mask (hash & (count-1) == pidx), so
            # mask and modulo must agree (reference requires the same)
            pcount = 1
            while pcount < max(1, req.partition_count):
                pcount <<= 1
            app = mm.AppInfo(app_name=req.app_name, app_id=self._next_app_id,
                             partition_count=pcount,
                             replica_count=min(req.replica_count, len(alive)),
                             envs_json=req.envs_json)
            self._next_app_id += 1
            self._apps[req.app_name] = app
            parts = []
            for pidx in range(pcount):
                members = self._pick_nodes_locked(app.replica_count, pidx)
                pc = mm.PartitionConfig(pidx=pidx, ballot=1,
                                        primary=members[0],
                                        secondaries=members[1:])
                parts.append(pc)
            self._parts[app.app_id] = parts
            self._persist_locked()
        for pc in parts:
            self._install_partition(app, pc, learners=())
        return codec.encode(mm.CreateAppResponse(app_id=app.app_id))

    def _on_drop_app(self, header, body) -> bytes:
        req = codec.decode(mm.DropAppRequest, body)
        with self._lock:
            app = self._apps.pop(req.app_name, None)
            if app is None:
                return codec.encode(mm.DropAppResponse(
                    error=1, error_text="no such app"))
            parts = self._parts.pop(app.app_id, [])
            self._persist_locked()
        for pc in parts:
            for node in [pc.primary] + pc.secondaries:
                self._send_to_node(node, RPC_CLOSE_REPLICA,
                                   mm.CloseReplicaRequest(app.app_id, pc.pidx),
                                   ignore_errors=True)
        return codec.encode(mm.DropAppResponse())

    def _on_list_apps(self, header, body) -> bytes:
        with self._lock:
            return codec.encode(mm.ListAppsResponse(
                apps=list(self._apps.values())))

    def _on_query_config(self, header, body) -> bytes:
        req = codec.decode(mm.QueryConfigRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.QueryConfigResponse(
                    error=1, error_text=f"no app {req.app_name}"))
            return codec.encode(mm.QueryConfigResponse(
                app=app, partitions=list(self._parts[app.app_id])))

    def _on_set_app_envs(self, header, body) -> bytes:
        req = codec.decode(mm.SetAppEnvsRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.SetAppEnvsResponse(
                    error=1, error_text="no such app"))
            envs = json.loads(app.envs_json)
            envs.update(json.loads(req.envs_json))
            app.envs_json = json.dumps(envs)
            parts = list(self._parts[app.app_id])
            self._persist_locked()
        # push to every serving node (reference: meta spreads app envs to
        # replicas which hot-apply them, pegasus_server_impl.cpp:2406)
        for pc in parts:
            for node in [pc.primary] + pc.secondaries:
                self._send_to_node(node, RPC_OPEN_REPLICA, mm.OpenReplicaRequest(
                    app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                    ballot=pc.ballot, primary=pc.primary,
                    secondaries=pc.secondaries, envs_json=app.envs_json,
                    partition_count=app.partition_count),
                    ignore_errors=True)
        return codec.encode(mm.SetAppEnvsResponse())

    # ------------------------------------------------------ split/backup/load

    def _on_split_app(self, header, body) -> bytes:
        """Online partition split: double the partition count (SURVEY §2.4
        'Partition split'; reference meta split + engine-side stale-key GC).
        Child partition pidx+n is seeded from parent pidx via the learn
        path on the same member set; every replica then gets
        partition_version = 2n-1 so compaction GCs keys it no longer owns
        (key_ttl_compaction_filter.h:107 analogue)."""
        req = codec.decode(mm.SplitAppRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.SplitAppResponse(error=1,
                                                        error_text="no such app"))
            n = app.partition_count
            parts = self._parts[app.app_id]
            children = []
            for pidx in range(n, 2 * n):
                parent = parts[pidx - n]
                pc = mm.PartitionConfig(pidx=pidx, ballot=1,
                                        primary=parent.primary,
                                        secondaries=list(parent.secondaries))
                parts.append(pc)
                children.append((parent, pc))
            app.partition_count = 2 * n
            parents = list(parts[:n])
            self._persist_locked()
        # Phase 1: parents learn the NEW partition count FIRST, so any write
        # still routed with the old count but belonging to a child half is
        # rejected from here on (client re-resolves). Writes accepted before
        # this point precede the child learn below and are carried by it —
        # no write can fall between the two.
        for pc in parents:
            self._install_partition(app, pc)
        # Phase 2: seed every child from its parent's primary (full-copy
        # learn). Failures are fatal for the split: the stale-key GC mask
        # must not spread unless every child holds its half.
        seeded = True
        for parent, pc in children:
            req_open = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries, envs_json=app.envs_json,
                partition_count=2 * n, learn_from=parent.primary,
                learn_pidx=parent.pidx)
            for node in [pc.primary] + pc.secondaries:
                if self._send_to_node(node, RPC_OPEN_REPLICA, req_open,
                                      ignore_errors=True) is None:
                    seeded = False
        if not seeded:
            return codec.encode(mm.SplitAppResponse(
                error=1, new_partition_count=2 * n,
                error_text="child seeding incomplete; GC mask withheld — "
                           "re-run split to retry"))
        # Phase 3: with every child seeded, spread the ownership mask so
        # compaction GCs keys each partition no longer owns.
        with self._lock:
            envs = json.loads(app.envs_json)
            envs["replica.partition_version"] = str(2 * n - 1)
            app.envs_json = json.dumps(envs)
            all_parts = list(self._parts[app.app_id])
            self._persist_locked()
        for pc in all_parts:
            self._install_partition(app, pc)
        return codec.encode(mm.SplitAppResponse(new_partition_count=2 * n))

    def _on_backup_app(self, header, body) -> bytes:
        """Cold backup: every partition primary checkpoints into the backup
        root (block-service local-FS provider), then backup metadata lands
        beside them (reference cold backup to block service, SURVEY §2.4)."""
        req = codec.decode(mm.BackupAppRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.BackupAppResponse(error=1,
                                                         error_text="no such app"))
            parts = list(self._parts[app.app_id])
        backup_id = int(time.time() * 1000)
        # replicas resolve this path through a block service rooted at "/";
        # absolutize here so a relative root means the same tree everywhere
        base = os.path.join(os.path.abspath(req.backup_root),
                            str(backup_id), req.app_name)
        for pc in parts:
            dest = os.path.join(base, str(pc.pidx))
            out = self._send_to_node(pc.primary, RPC_COLD_BACKUP,
                                     mm.OpenReplicaRequest(
                                         app_id=app.app_id, pidx=pc.pidx,
                                         restore_dir=dest),
                                     ignore_errors=True)
            if out is None:
                return codec.encode(mm.BackupAppResponse(
                    error=1, error_text=f"partition {pc.pidx} backup failed"))
        with open(os.path.join(base, "backup_metadata"), "w") as f:
            json.dump({"app_name": app.app_name, "app_id": app.app_id,
                       "partition_count": app.partition_count,
                       "backup_id": backup_id, "envs_json": app.envs_json}, f)
        return codec.encode(mm.BackupAppResponse(backup_id=backup_id))

    def _on_restore_app(self, header, body) -> bytes:
        """Restore a backup into a NEW table: create the app with the
        backed-up partition count, each replica seeding its engine from the
        backup dir at open (reference restore envs ROCKSDB_ENV_RESTORE_*,
        pegasus_server_impl.cpp:1339-1393)."""
        req = codec.decode(mm.RestoreAppRequest, body)
        backup_root = os.path.abspath(req.backup_root)
        meta_file = os.path.join(backup_root, str(req.backup_id),
                                 req.old_app_name, "backup_metadata")
        try:
            with open(meta_file) as f:
                bmeta = json.load(f)
        except OSError:
            return codec.encode(mm.RestoreAppResponse(
                error=1, error_text=f"no backup metadata at {meta_file}"))
        with self._lock:
            if req.new_app_name in self._apps:
                return codec.encode(mm.RestoreAppResponse(
                    error=1, error_text="app exists"))
            alive = self._alive_nodes_locked()
            if not alive:
                return codec.encode(mm.RestoreAppResponse(
                    error=1, error_text="no alive nodes"))
            app = mm.AppInfo(app_name=req.new_app_name,
                             app_id=self._next_app_id,
                             partition_count=bmeta["partition_count"],
                             replica_count=min(3, len(alive)),
                             envs_json=bmeta.get("envs_json", "{}"))
            self._next_app_id += 1
            self._apps[req.new_app_name] = app
            parts = []
            for pidx in range(app.partition_count):
                members = self._pick_nodes_locked(app.replica_count, pidx)
                parts.append(mm.PartitionConfig(pidx=pidx, ballot=1,
                                                primary=members[0],
                                                secondaries=members[1:]))
            self._parts[app.app_id] = parts
            self._persist_locked()
        for pc in parts:
            src = os.path.join(backup_root, str(req.backup_id),
                               req.old_app_name, str(pc.pidx))
            req_open = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries, envs_json=app.envs_json,
                partition_count=app.partition_count, restore_dir=src)
            for node in [pc.primary] + pc.secondaries:
                self._send_to_node(node, RPC_OPEN_REPLICA, req_open,
                                   ignore_errors=True)
        return codec.encode(mm.RestoreAppResponse(app_id=app.app_id))

    def _on_start_bulk_load(self, header, body) -> bytes:
        """Meta-driven bulk load: validate provider metadata, then each
        partition primary ingests its set (reference bulk-load DDL,
        SURVEY §2.4 'Bulk load framework')."""
        from ..engine import bulk_load as bl

        req = codec.decode(mm.StartBulkLoadRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.StartBulkLoadResponse(
                    error=1, error_text="no such app"))
            parts = list(self._parts[app.app_id])
        provider_root = os.path.abspath(req.provider_root)
        try:
            with open(bl.metadata_path(provider_root, req.app_name)) as f:
                bmeta = json.load(f)
        except OSError:
            return codec.encode(mm.StartBulkLoadResponse(
                error=1, error_text="no bulk_load_metadata"))
        if bmeta["partition_count"] != app.partition_count:
            return codec.encode(mm.StartBulkLoadResponse(
                error=1, error_text="partition count mismatch"))
        from ..rpc import messages as rpc_msg
        from ..rpc.task_codes import RPC_BULK_LOAD_INGEST

        total = 0
        for pc in parts:
            ingest = rpc_msg.BulkLoadIngestRequest(
                provider_root=provider_root, app_name=req.app_name,
                partition_count=app.partition_count)
            # route through the primary's WRITE path: the ingestion command
            # replicates via PacificA so every replica loads the set at the
            # same decree (survives failover)
            out = self._send_to_node(pc.primary, RPC_BULK_LOAD_INGEST, ingest,
                                     app_id=app.app_id, pidx=pc.pidx,
                                     ignore_errors=True)
            if out is None:
                return codec.encode(mm.StartBulkLoadResponse(
                    error=1, error_text=f"partition {pc.pidx} ingest failed"))
            resp = codec.decode(rpc_msg.BulkLoadIngestResponse, out)
            if resp.error:
                return codec.encode(mm.StartBulkLoadResponse(
                    error=1, error_text=f"partition {pc.pidx} ingest error"))
            total += resp.ingested_records
        return codec.encode(mm.StartBulkLoadResponse(ingested_records=total))

    # --------------------------------------------------------------- balance

    def _on_propose(self, header, body) -> bytes:
        """Move one partition's primary to a named secondary (the
        greedy_load_balancer's move_primary proposal, shell `propose`)."""
        req = codec.decode(mm.ProposeRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.ProposeResponse(error=1,
                                                       error_text="no such app"))
            parts = self._parts[app.app_id]
            if not (0 <= req.pidx < len(parts)):
                return codec.encode(mm.ProposeResponse(error=1,
                                                       error_text="bad pidx"))
            pc = parts[req.pidx]
            if req.target not in pc.secondaries:
                return codec.encode(mm.ProposeResponse(
                    error=1, error_text=f"{req.target} is not a secondary"))
            pc.ballot += 1
            pc.secondaries.remove(req.target)
            pc.secondaries.append(pc.primary)
            pc.primary = req.target
            self._persist_locked()
        self._install_partition(app, pc)
        return codec.encode(mm.ProposeResponse())

    def _on_balance(self, header, body) -> bytes:
        """Greedy primary balancing: while the most-loaded node holds 2+
        more primaries than the least-loaded, demote one whose partition
        has a secondary on the lighter node (the greedy_load_balancer's
        primary-count equalization)."""
        moved = 0
        for _ in range(64):  # bounded passes
            with self._lock:
                alive = self._alive_nodes_locked()
                if len(alive) < 2:
                    break
                counts = {a: 0 for a in alive}
                for parts in self._parts.values():
                    for pc in parts:
                        if pc.primary in counts:
                            counts[pc.primary] += 1
                heavy = max(alive, key=lambda a: counts[a])
                light = min(alive, key=lambda a: counts[a])
                if counts[heavy] - counts[light] < 2:
                    break
                move = None
                for app in self._apps.values():
                    for pc in self._parts[app.app_id]:
                        if pc.primary == heavy and light in pc.secondaries:
                            move = (app, pc)
                            break
                    if move:
                        break
                if move is None:
                    break
                app, pc = move
                pc.ballot += 1
                pc.secondaries.remove(light)
                pc.secondaries.append(pc.primary)
                pc.primary = light
                self._persist_locked()
            self._install_partition(app, pc)
            moved += 1
        return codec.encode(mm.BalanceResponse(moved=moved))

    def _on_list_nodes(self, header, body) -> bytes:
        with self._lock:
            nodes = []
            now = time.monotonic()
            for addr, last in self._nodes.items():
                nodes.append(mm.NodeInfo(
                    address=addr, alive=(now - last) < self.fd_grace,
                    last_beacon_ms=int(last * 1000),
                    replica_count=sum(
                        1 for parts in self._parts.values() for pc in parts
                        if pc.primary == addr or addr in pc.secondaries)))
            return codec.encode(mm.ListNodesResponse(nodes=nodes))

    # ------------------------------------------------------------------- FD

    def _on_beacon(self, header, body) -> bytes:
        req = codec.decode(mm.BeaconRequest, body)
        with self._lock:
            known = req.node in self._nodes
            self._nodes[req.node] = time.monotonic()
        if not known:
            self._persist()
        return codec.encode(mm.BeaconResponse(allowed=True))

    def check_leases(self) -> list:
        """Expire dead nodes and reconfigure their partitions. Returns the
        list of nodes declared dead. Call from a timer (or tests)."""
        now = time.monotonic()
        with self._lock:
            dead = [a for a, last in self._nodes.items()
                    if (now - last) >= self.fd_grace]
        for node in dead:
            self._handle_node_death(node)
        return dead

    def mark_node_dead(self, addr: str) -> None:
        """Force-expire (tests / admin)."""
        with self._lock:
            if addr in self._nodes:
                self._nodes[addr] = -1e18
        self._handle_node_death(addr)

    # ---------------------------------------------------------- failover

    def _handle_node_death(self, node: str) -> None:
        with self._lock:
            moves = []
            for app in self._apps.values():
                for pc in self._parts[app.app_id]:
                    if pc.primary == node or node in pc.secondaries:
                        moves.append((app, pc))
        for app, pc in moves:
            self._reconfigure_partition(app, pc, dead=node)

    def _reconfigure_partition(self, app: mm.AppInfo, pc: mm.PartitionConfig,
                               dead: str) -> None:
        with self._lock:
            members = [m for m in [pc.primary] + pc.secondaries if m != dead]
            if not members:
                pc.primary = ""
                pc.secondaries = []
                self._persist_locked()
                return
            pc.ballot += 1
            if pc.primary == dead:
                # promote the secondary with the longest prepared log
                best, best_state = None, (-1, -1)
                for m in members:
                    st = self._query_replica_state(m, app.app_id, pc.pidx)
                    if st is not None and (st.ballot, st.last_prepared) > best_state:
                        best, best_state = m, (st.ballot, st.last_prepared)
                pc.primary = best or members[0]
            pc.secondaries = [m for m in members if m != pc.primary]
            # rebuild replica count on a fresh node
            learners = []
            alive = self._alive_nodes_locked()
            candidates = [n for n in alive if n not in members]
            if len(members) < app.replica_count and candidates:
                new_node = min(candidates, key=self._node_load_locked)
                learners = [new_node]
            self._persist_locked()
        self._install_partition(app, pc, learners=learners)
        if learners:
            with self._lock:
                for ln in learners:
                    if ln not in pc.secondaries:
                        pc.secondaries.append(ln)
                self._persist_locked()
            # Re-push the updated view so the primary's in-memory membership
            # includes the new member and it starts receiving prepares;
            # without this the learner is fresh only as of the learn snapshot
            # while meta reports it as a full secondary.
            self._install_partition(app, pc)

    def _install_partition(self, app, pc: mm.PartitionConfig, learners=()):
        """Push the view to every member (primary first), seed learners."""
        req = mm.OpenReplicaRequest(
            app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
            ballot=pc.ballot, primary=pc.primary, secondaries=pc.secondaries,
            envs_json=app.envs_json, partition_count=app.partition_count)
        for node in [pc.primary] + pc.secondaries:
            if node:
                self._send_to_node(node, RPC_OPEN_REPLICA, req,
                                   ignore_errors=True)
        for node in learners:
            lreq = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries + [node],
                learn_from=pc.primary, envs_json=app.envs_json,
                partition_count=app.partition_count)
            self._send_to_node(node, RPC_OPEN_REPLICA, lreq, ignore_errors=True)

    # ------------------------------------------------------------- helpers

    def _query_replica_state(self, node, app_id, pidx):
        try:
            body = self._send_to_node(node, RPC_REPLICA_STATE,
                                      mm.ReplicaStateRequest(app_id, pidx))
            return codec.decode(mm.ReplicaStateResponse, body)
        except (RpcError, OSError):
            return None

    def _send_to_node(self, node: str, code: str, req, ignore_errors=False,
                      app_id: int = 0, pidx: int = 0):
        host, _, port = node.rpartition(":")
        try:
            conn = self.pool.get((host, int(port)))
            _, body = conn.call(code, codec.encode(req), timeout=60.0,
                                app_id=app_id, partition_index=pidx)
            return body
        except (RpcError, OSError):
            if ignore_errors:
                return None
            raise

    def _alive_nodes_locked(self) -> list:
        now = time.monotonic()
        return sorted(a for a, last in self._nodes.items()
                      if (now - last) < self.fd_grace)

    def _node_load_locked(self, addr: str) -> int:
        return sum(1 for parts in self._parts.values() for pc in parts
                   if pc.primary == addr or addr in pc.secondaries)

    def _pick_nodes_locked(self, count: int, seed: int) -> list:
        alive = self._alive_nodes_locked()
        ordered = sorted(alive, key=lambda a: (self._node_load_locked(a), a))
        rot = ordered[seed % len(ordered):] + ordered[:seed % len(ordered)]
        return rot[:count]

    # ------------------------------------------------------------ persistence

    def _persist(self):
        with self._lock:
            self._persist_locked()

    def _persist_locked(self):
        state = {
            "next_app_id": self._next_app_id,
            "apps": {n: vars(a) for n, a in self._apps.items()},
            "parts": {str(aid): [vars(pc) for pc in parts]
                      for aid, parts in self._parts.items()},
            "nodes": list(self._nodes),
        }
        tmp = self.state_path + ".tmp"
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.state_path)

    def _load(self):
        if not os.path.exists(self.state_path):
            return
        with open(self.state_path) as f:
            state = json.load(f)
        self._next_app_id = state["next_app_id"]
        self._apps = {n: mm.AppInfo(**a) for n, a in state["apps"].items()}
        self._parts = {int(aid): [mm.PartitionConfig(**pc) for pc in parts]
                       for aid, parts in state["parts"].items()}
        # nodes must re-beacon after a meta restart
        self._nodes = {}
