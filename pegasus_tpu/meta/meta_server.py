"""Meta server: table DDL, partition->replica mapping, beacon FD, failover.

The rDSN meta-server role this build re-provides (SURVEY.md §2.4 'Meta
server' + 'Failure detector'): app state and partition configs live here
(persisted to a JSON state file standing in for the ZooKeeper-backed
meta_state_service), replica nodes register via beacons with lease/grace
semantics (fd_lease_seconds/fd_grace_seconds, config.ini:232-238), and node
death triggers reconfiguration: promote a surviving secondary, then rebuild
replica count by seeding a learner on an under-loaded node — the
greedy_load_balancer's simplest move set.

Serverlet codes: RPC_CM_* (client/DDL) + RPC_FD_BEACON (nodes), matching
the reference's task-code families.
"""

import json
import os
import threading
import time

from ..rpc import codec
from ..runtime.tasking import spawn_thread
from ..rpc.transport import (ConnectionPool, ERR_FORWARD_TO_PRIMARY,
                             ERR_INVALID_STATE, ERR_OBJECT_NOT_FOUND, RpcError)
from . import messages as mm

RPC_CM_CREATE_APP = "RPC_CM_START_CREATE_APP"
RPC_CM_DROP_APP = "RPC_CM_START_DROP_APP"
RPC_CM_LIST_APPS = "RPC_CM_LIST_APPS"
RPC_CM_QUERY_CONFIG = "RPC_CM_QUERY_PARTITION_CONFIG_BY_INDEX"
RPC_CM_SET_APP_ENVS = "RPC_CM_UPDATE_APP_ENV"
RPC_CM_LIST_NODES = "RPC_CM_LIST_NODES"
RPC_CM_SPLIT_APP = "RPC_CM_START_PARTITION_SPLIT"
RPC_CM_BACKUP_APP = "RPC_CM_START_BACKUP_APP"
RPC_CM_RESTORE_APP = "RPC_CM_START_RESTORE"
RPC_CM_START_BULK_LOAD = "RPC_CM_START_BULK_LOAD"
RPC_CM_QUERY_BULK_LOAD = "RPC_CM_QUERY_BULK_LOAD_STATUS"
RPC_CM_CONTROL_BULK_LOAD = "RPC_CM_CONTROL_BULK_LOAD"
RPC_CM_QUERY_RESTORE = "RPC_CM_QUERY_RESTORE_STATUS"
RPC_CM_PROPOSE = "RPC_CM_PROPOSE_BALANCER"
RPC_CM_BALANCE = "RPC_CM_START_BALANCE"
RPC_CM_ADD_DUPLICATION = "RPC_CM_ADD_DUPLICATION"
RPC_CM_QUERY_DUPLICATION = "RPC_CM_QUERY_DUPLICATION"
RPC_CM_MODIFY_DUPLICATION = "RPC_CM_MODIFY_DUPLICATION"
RPC_CM_ADD_BACKUP_POLICY = "RPC_CM_ADD_BACKUP_POLICY"
RPC_CM_LS_BACKUP_POLICY = "RPC_CM_QUERY_BACKUP_POLICY"
RPC_CM_MODIFY_BACKUP_POLICY = "RPC_CM_MODIFY_BACKUP_POLICY"
RPC_CM_RECOVER = "RPC_CM_START_RECOVERY"
RPC_CM_RECALL_APP = "RPC_CM_RECALL_APP"
RPC_CM_CONTROL_META = "RPC_CM_CONTROL_META"

# meta function levels (reference meta_function_level enum, shell
# rebalance.cpp:27-31: stopped/blind/freezed/steady/lively; get/set_meta_level)
META_LEVELS = ("stopped", "blind", "freezed", "steady", "lively")
# stopped: reject everything, queries included — full operator lockdown;
#          only control_meta (the way out) and beacons (liveness must
#          never be blinded) still served
# blind:   reject every state-changing DDL (reference meta_function_level
#          FL_blind); reads/queries still served
# freezed: DDL allowed but no meta-initiated data movement (no learner
#          rebuild on node death)
# steady:  failover rebuild but no balancing
# lively:  everything, including balance
RPC_CM_DDD_DIAGNOSE = "RPC_CM_DDD_DIAGNOSE"
RPC_CM_QUERY_CLUSTER_STATE = "RPC_CM_QUERY_CLUSTER_STATE"
RPC_FD_BEACON = "RPC_FD_FAILURE_DETECTOR_PING"

# meta -> replica node
RPC_OPEN_REPLICA = "RPC_CONFIG_PROPOSAL_OPEN_REPLICA"
RPC_CLOSE_REPLICA = "RPC_CONFIG_PROPOSAL_CLOSE_REPLICA"
RPC_REPLICA_STATE = "RPC_QUERY_REPLICA_STATE"
RPC_COLD_BACKUP = "RPC_COLD_BACKUP"
RPC_BULK_LOAD = "RPC_BULK_LOAD"
RPC_QUERY_REPLICA_INFO = "RPC_QUERY_REPLICA_INFO"


class MetaServer:
    def __init__(self, state_path: str, fd_grace_seconds: float = 22.0,
                 replica_count: int = 3, election=None):
        self.state_path = state_path
        self.fd_grace = fd_grace_seconds
        self.default_replica_count = replica_count
        # meta HA (meta/election.py): state_path must live on storage every
        # meta shares; None = single-meta mode, always leader
        self.election = election
        self._lock = threading.RLock()
        self._apps = {}          # name -> AppInfo
        self._parts = {}         # app_id -> list[PartitionConfig]
        self._nodes = {}         # addr -> last_beacon_monotonic
        self._node_replicas = {} # addr -> ["app_id.pidx"] from the last beacon
        self._node_states = {}   # addr -> {gpid: lag/audit state} (beacon)
        self._node_tables = {}   # addr -> {tables@pid:N: tenant-ledger frag}
        self._dups = {}          # app_id -> list[dict] duplication entries
        self._policies = {}      # name -> dict (BackupPolicyInfo fields)
        self._dropped = {}       # app_id -> {"app","parts","expire_ts"}
        self._bulk_loads = {}    # app_id -> bulk-load session dict
        self._restores = {}      # new_app_name -> restore status dict
        self.level = "lively"    # freezed | steady | lively (see META_LEVELS)
        self._next_app_id = 1
        self._next_dupid = 1
        self._state_epoch = 0    # epoch the loaded state file was written under
        self._state_fp = None    # (ino, mtime_ns, size) of the state file as
                                 # last read/written by THIS process — guards
                                 # the cached epoch (ADVICE r5: no full json
                                 # re-parse per acked DDL)
        self.pool = ConnectionPool()
        self._load()

    # ----------------------------------------------------------- serverlet

    # codes still served at level "blind" (pure queries + liveness):
    # everything read-only, the beacon (liveness must not be blinded), and
    # control_meta itself (the way back out)
    _BLIND_ALLOWED = frozenset({
        RPC_CM_LIST_APPS, RPC_CM_QUERY_CONFIG, RPC_CM_LIST_NODES,
        RPC_CM_QUERY_DUPLICATION, RPC_CM_LS_BACKUP_POLICY,
        RPC_CM_QUERY_BULK_LOAD, RPC_CM_QUERY_RESTORE, RPC_CM_CONTROL_META,
        RPC_CM_QUERY_CLUSTER_STATE, RPC_FD_BEACON,
    })

    # codes still served at level "stopped" (full lockdown): only the way
    # back out and liveness
    _STOPPED_ALLOWED = frozenset({RPC_CM_CONTROL_META, RPC_FD_BEACON})

    def _guard_level(self, code, fn):
        def wrapped(header, body):
            if (self.election is not None and not self.election.is_leader()
                    and code != RPC_FD_BEACON):
                # followers still absorb beacons (a warm liveness map makes
                # takeover instant); everything else goes to the leader —
                # clients/shell/replicas fall through their meta list
                leader = self.election.leader()
                raise RpcError(ERR_FORWARD_TO_PRIMARY,
                               f"not the meta leader (leader: "
                               f"{leader or 'unknown'})")
            if self.level == "stopped" and code not in self._STOPPED_ALLOWED:
                raise RpcError(ERR_INVALID_STATE,
                               f"meta level is stopped; {code} refused "
                               "(set_meta_level to unlock)")
            if self.level == "blind" and code not in self._BLIND_ALLOWED:
                raise RpcError(ERR_INVALID_STATE,
                               f"meta level is blind; {code} refused "
                               "(set_meta_level to unlock)")
            return fn(header, body)
        return wrapped

    def rpc_handlers(self) -> dict:
        handlers = self._raw_rpc_handlers()
        return {code: self._guard_level(code, fn)
                for code, fn in handlers.items()}

    def _raw_rpc_handlers(self) -> dict:
        return {
            RPC_CM_CREATE_APP: self._on_create_app,
            RPC_CM_DROP_APP: self._on_drop_app,
            RPC_CM_LIST_APPS: self._on_list_apps,
            RPC_CM_QUERY_CONFIG: self._on_query_config,
            RPC_CM_SET_APP_ENVS: self._on_set_app_envs,
            RPC_CM_LIST_NODES: self._on_list_nodes,
            RPC_CM_SPLIT_APP: self._on_split_app,
            RPC_CM_BACKUP_APP: self._on_backup_app,
            RPC_CM_RESTORE_APP: self._on_restore_app,
            RPC_CM_START_BULK_LOAD: self._on_start_bulk_load,
            RPC_CM_QUERY_BULK_LOAD: self._on_query_bulk_load,
            RPC_CM_CONTROL_BULK_LOAD: self._on_control_bulk_load,
            RPC_CM_QUERY_RESTORE: self._on_query_restore,
            RPC_CM_PROPOSE: self._on_propose,
            RPC_CM_BALANCE: self._on_balance,
            RPC_CM_ADD_DUPLICATION: self._on_add_dup,
            RPC_CM_QUERY_DUPLICATION: self._on_query_dup,
            RPC_CM_MODIFY_DUPLICATION: self._on_modify_dup,
            RPC_CM_ADD_BACKUP_POLICY: self._on_add_backup_policy,
            RPC_CM_LS_BACKUP_POLICY: self._on_ls_backup_policy,
            RPC_CM_MODIFY_BACKUP_POLICY: self._on_modify_backup_policy,
            RPC_CM_RECOVER: self._on_recover,
            RPC_CM_RECALL_APP: self._on_recall_app,
            RPC_CM_CONTROL_META: self._on_control_meta,
            RPC_CM_DDD_DIAGNOSE: self._on_ddd_diagnose,
            RPC_CM_QUERY_CLUSTER_STATE: self._on_query_cluster_state,
            RPC_FD_BEACON: self._on_beacon,
        }

    # ----------------------------------------------------------------- DDL

    def _on_create_app(self, header, body) -> bytes:
        req = codec.decode(mm.CreateAppRequest, body)
        with self._lock:
            if req.app_name in self._apps:
                app = self._apps[req.app_name]
                return codec.encode(mm.CreateAppResponse(app_id=app.app_id))
            alive = self._alive_nodes_locked()
            if not alive:
                return codec.encode(mm.CreateAppResponse(
                    error=1, error_text="no alive replica nodes"))
            # partition counts are powers of two: split doubles them and the
            # ownership filter is a bit mask (hash & (count-1) == pidx), so
            # mask and modulo must agree (reference requires the same)
            pcount = 1
            while pcount < max(1, req.partition_count):
                pcount <<= 1
            app = mm.AppInfo(app_name=req.app_name, app_id=self._next_app_id,
                             partition_count=pcount,
                             replica_count=min(req.replica_count, len(alive)),
                             envs_json=req.envs_json)
            self._next_app_id += 1
            self._apps[req.app_name] = app
            parts = []
            for pidx in range(pcount):
                members = self._pick_nodes_locked(app.replica_count, pidx)
                pc = mm.PartitionConfig(pidx=pidx, ballot=1,
                                        primary=members[0],
                                        secondaries=members[1:])
                parts.append(pc)
            self._parts[app.app_id] = parts
            self._persist_locked()
        for pc in parts:
            self._install_partition(app, pc, learners=())
        return codec.encode(mm.CreateAppResponse(app_id=app.app_id))

    def _on_drop_app(self, header, body) -> bytes:
        """drop [-r reserve_seconds]: reserve_seconds > 0 soft-drops — the
        app disappears from routing/DDL but its replicas' data stays on
        disk and recall_app can restore it until the hold expires
        (reference drop/recall with hold_seconds_for_dropped_app)."""
        req = codec.decode(mm.DropAppRequest, body)
        with self._lock:
            app = self._apps.pop(req.app_name, None)
            if app is None:
                return codec.encode(mm.DropAppResponse(
                    error=1, error_text="no such app"))
            parts = self._parts.pop(app.app_id, [])
            if req.reserve_seconds > 0:
                app.status = "AS_DROPPED"
                self._dropped[app.app_id] = {
                    "app": vars(app), "parts": [vars(pc) for pc in parts],
                    "expire_ts": int(time.time()) + req.reserve_seconds}
            self._persist_locked()
        for pc in parts:
            for node in [pc.primary] + pc.secondaries:
                self._send_to_node(node, RPC_CLOSE_REPLICA,
                                   mm.CloseReplicaRequest(app.app_id, pc.pidx),
                                   ignore_errors=True)
        return codec.encode(mm.DropAppResponse())

    def _on_recall_app(self, header, body) -> bytes:
        """recall <app_id> [new_name]: restore a soft-dropped app; replicas
        reopen from their preserved on-disk state."""
        req = codec.decode(mm.RecallAppRequest, body)
        with self._lock:
            ent = self._dropped.get(req.app_id)
            if ent is None:
                return codec.encode(mm.RecallAppResponse(
                    error=1, error_text=f"no dropped app with id "
                                        f"{req.app_id} [or hold expired]"))
            name = req.new_app_name or ent["app"]["app_name"]
            if name in self._apps:
                return codec.encode(mm.RecallAppResponse(
                    error=1, error_text=f"app {name} already exists"))
            del self._dropped[req.app_id]
            app = mm.AppInfo(**ent["app"])
            app.app_name = name
            app.status = "AS_AVAILABLE"
            parts = [mm.PartitionConfig(**pc) for pc in ent["parts"]]
            for pc in parts:
                pc.ballot += 1
            self._apps[name] = app
            self._parts[app.app_id] = parts
            self._persist_locked()
        for pc in parts:
            self._install_partition(app, pc)
        return codec.encode(mm.RecallAppResponse(app_name=name))

    def _on_control_meta(self, header, body) -> bytes:
        """get/set the meta function level (reference meta_function_level
        + shell get_meta_level/set_meta_level): `freezed` stops every
        meta-initiated data movement (balancing AND redundancy rebuild —
        primaries still promote so writes survive), `steady` allows
        failover rebuild but no balancing, `lively` enables auto-balance."""
        req = codec.decode(mm.ControlMetaRequest, body)
        with self._lock:
            if req.set_level:
                if req.set_level not in META_LEVELS:
                    return codec.encode(mm.ControlMetaResponse(
                        error=1,
                        error_text=f"bad level {req.set_level} "
                                   f"(choose from {'/'.join(META_LEVELS)})"))
                self.level = req.set_level
                self._persist_locked()
            return codec.encode(mm.ControlMetaResponse(level=self.level))

    def purge_expired_dropped(self, now: int = None) -> list:
        """Forget soft-dropped apps past their hold (timer tick); their
        data dirs on replica nodes become garbage for operator GC."""
        now = int(time.time()) if now is None else now
        with self._lock:
            gone = [aid for aid, e in self._dropped.items()
                    if e["expire_ts"] <= now]
            for aid in gone:
                del self._dropped[aid]
            if gone:
                self._persist_locked()
        return gone

    def _on_list_apps(self, header, body) -> bytes:
        with self._lock:
            return codec.encode(mm.ListAppsResponse(
                apps=list(self._apps.values())))

    def _on_query_config(self, header, body) -> bytes:
        req = codec.decode(mm.QueryConfigRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.QueryConfigResponse(
                    error=1, error_text=f"no app {req.app_name}"))
            return codec.encode(mm.QueryConfigResponse(
                app=app, partitions=list(self._parts[app.app_id])))

    def _on_set_app_envs(self, header, body) -> bytes:
        req = codec.decode(mm.SetAppEnvsRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.SetAppEnvsResponse(
                    error=1, error_text="no such app"))
            envs = json.loads(app.envs_json)
            envs.update(json.loads(req.envs_json))
            app.envs_json = json.dumps(envs)
            parts = list(self._parts[app.app_id])
            self._persist_locked()
        self._push_app_envs(app, parts)
        return codec.encode(mm.SetAppEnvsResponse())

    def _push_app_envs(self, app, parts) -> None:
        """Spread app envs to every serving node (reference: meta spreads
        app envs to replicas which hot-apply them,
        pegasus_server_impl.cpp:2406)."""
        for pc in parts:
            for node in [pc.primary] + pc.secondaries:
                if not node:
                    continue
                self._send_to_node(node, RPC_OPEN_REPLICA, mm.OpenReplicaRequest(
                    app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                    ballot=pc.ballot, primary=pc.primary,
                    secondaries=pc.secondaries, envs_json=app.envs_json,
                    partition_count=app.partition_count),
                    ignore_errors=True)

    # ------------------------------------------------------ split/backup/load

    def _on_split_app(self, header, body) -> bytes:
        """Online partition split: double the partition count (SURVEY §2.4
        'Partition split'; reference meta split + engine-side stale-key GC).
        Child partition pidx+n is seeded from parent pidx via the learn
        path on the same member set; every replica then gets
        partition_version = 2n-1 so compaction GCs keys it no longer owns
        (key_ttl_compaction_filter.h:107 analogue)."""
        req = codec.decode(mm.SplitAppRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.SplitAppResponse(error=1,
                                                        error_text="no such app"))
            parts = self._parts[app.app_id]
            envs = json.loads(app.envs_json)
            pending = envs.get("replica.split_pending")
            if pending is not None:
                # RESUME an incomplete split (the retry the seeding-failure
                # error text promises): the count is already doubled and
                # the child configs installed — re-drive phase 2 for the
                # existing children instead of doubling again
                old_n, new_n = int(pending), app.partition_count
                children = [(parts[p - old_n], parts[p])
                            for p in range(old_n, new_n)]
            else:
                old_n = app.partition_count
                new_n = 2 * old_n
                children = []
                for pidx in range(old_n, new_n):
                    parent = parts[pidx - old_n]
                    pc = mm.PartitionConfig(
                        pidx=pidx, ballot=1, primary=parent.primary,
                        secondaries=list(parent.secondaries))
                    parts.append(pc)
                    children.append((parent, pc))
                app.partition_count = new_n
                # the resume marker rides the app envs (persisted with
                # the config) until phase 3 declares seeding complete
                envs["replica.split_pending"] = str(old_n)
                app.envs_json = json.dumps(envs)
            parents = list(parts[:old_n])
            self._persist_locked()
        n = old_n
        from ..runtime import events

        events.emit("split.phase", severity="warn",
                    phase="resume" if pending is not None else "start",
                    app=req.app_name, old_n=old_n, new_n=2 * old_n)
        # Phase 1: parents learn the NEW partition count FIRST, so any write
        # still routed with the old count but belonging to a child half is
        # rejected from here on (client re-resolves). Writes accepted before
        # this point precede the child learn below and are carried by it —
        # no write can fall between the two.
        for pc in parents:
            self._install_partition(app, pc)
        # Phase 2: seed each child's PRIMARY from the parent's primary
        # (full-copy learn), then each child SECONDARY from the child
        # primary — ONE history source. Seeding every member from the
        # parent directly looks equivalent but is not under live load:
        # the parent advances between the independent learns, so two
        # members could snapshot different parent decrees and the gap
        # mutations exist in neither the later learner's checkpoint nor
        # the child primary's plog — decrees align again through the
        # prepare stream while the CONTENT stays divergent forever (the
        # decree-anchored audit caught exactly this under chaos load).
        # Failures are fatal for the split: the stale-key GC mask must
        # not spread unless every child holds its half.
        seeded = True
        for parent, pc in children:
            req_primary = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries, envs_json=app.envs_json,
                partition_count=2 * n, learn_from=parent.primary,
                learn_pidx=parent.pidx)
            if self._send_to_node(pc.primary, RPC_OPEN_REPLICA, req_primary,
                                  ignore_errors=True) is None:
                seeded = False
                continue
            req_secondary = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries, envs_json=app.envs_json,
                partition_count=2 * n, learn_from=pc.primary,
                learn_pidx=pc.pidx)
            for node in pc.secondaries:
                if self._send_to_node(node, RPC_OPEN_REPLICA, req_secondary,
                                      ignore_errors=True) is None:
                    seeded = False
        if not seeded:
            events.emit("split.phase", severity="error",
                        phase="seed_incomplete", app=req.app_name,
                        new_n=2 * n)
            return codec.encode(mm.SplitAppResponse(
                error=1, new_partition_count=2 * n,
                error_text="child seeding incomplete; GC mask withheld — "
                           "re-run split to retry"))
        # Phase 3: with every child seeded, spread the ownership mask so
        # compaction GCs keys each partition no longer owns.
        with self._lock:
            envs = json.loads(app.envs_json)
            envs.pop("replica.split_pending", None)
            envs["replica.partition_version"] = str(2 * n - 1)
            app.envs_json = json.dumps(envs)
            all_parts = list(self._parts[app.app_id])
            self._persist_locked()
        for pc in all_parts:
            self._install_partition(app, pc)
        events.emit("split.phase", phase="complete", app=req.app_name,
                    new_n=2 * n)
        return codec.encode(mm.SplitAppResponse(new_partition_count=2 * n))

    def _on_backup_app(self, header, body) -> bytes:
        """Cold backup: every partition primary checkpoints into the backup
        root (block-service local-FS provider), then backup metadata lands
        beside them (reference cold backup to block service, SURVEY §2.4)."""
        req = codec.decode(mm.BackupAppRequest, body)
        err, backup_id = self._do_backup(req.app_name, req.backup_root)
        if err:
            return codec.encode(mm.BackupAppResponse(error=1, error_text=err))
        return codec.encode(mm.BackupAppResponse(backup_id=backup_id))

    def _do_backup(self, app_name: str, backup_root: str,
                   backup_id: int = None):
        """-> (error_text or None, backup_id). One full app backup into
        backup_root/<backup_id>/<app_name>/<pidx>/ + backup_metadata."""
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return "no such app", 0
            parts = list(self._parts[app.app_id])
        backup_id = backup_id or int(time.time() * 1000)
        # replicas resolve this path through a block service rooted at "/";
        # absolutize here so a relative root means the same tree everywhere
        base = os.path.join(os.path.abspath(backup_root),
                            str(backup_id), app_name)
        for pc in parts:
            dest = os.path.join(base, str(pc.pidx))
            out = self._send_to_node(pc.primary, RPC_COLD_BACKUP,
                                     mm.OpenReplicaRequest(
                                         app_id=app.app_id, pidx=pc.pidx,
                                         restore_dir=dest),
                                     ignore_errors=True)
            if out is None:
                return f"partition {pc.pidx} backup failed", 0
        with open(os.path.join(base, "backup_metadata"), "w") as f:
            json.dump({"app_name": app.app_name, "app_id": app.app_id,
                       "partition_count": app.partition_count,
                       "backup_id": backup_id, "envs_json": app.envs_json}, f)
        return None, backup_id

    def _on_restore_app(self, header, body) -> bytes:
        """Restore a backup into a NEW table: create the app with the
        backed-up partition count, each replica seeding its engine from the
        backup dir at open (reference restore envs ROCKSDB_ENV_RESTORE_*,
        pegasus_server_impl.cpp:1339-1393)."""
        req = codec.decode(mm.RestoreAppRequest, body)
        backup_root = os.path.abspath(req.backup_root)
        meta_file = os.path.join(backup_root, str(req.backup_id),
                                 req.old_app_name, "backup_metadata")
        try:
            with open(meta_file) as f:
                bmeta = json.load(f)
        except OSError:
            return codec.encode(mm.RestoreAppResponse(
                error=1, error_text=f"no backup metadata at {meta_file}"))
        with self._lock:
            if req.new_app_name in self._apps:
                return codec.encode(mm.RestoreAppResponse(
                    error=1, error_text="app exists"))
            alive = self._alive_nodes_locked()
            if not alive:
                return codec.encode(mm.RestoreAppResponse(
                    error=1, error_text="no alive nodes"))
            app = mm.AppInfo(app_name=req.new_app_name,
                             app_id=self._next_app_id,
                             partition_count=bmeta["partition_count"],
                             replica_count=min(3, len(alive)),
                             envs_json=bmeta.get("envs_json", "{}"))
            self._next_app_id += 1
            self._apps[req.new_app_name] = app
            parts = []
            for pidx in range(app.partition_count):
                members = self._pick_nodes_locked(app.replica_count, pidx)
                parts.append(mm.PartitionConfig(pidx=pidx, ballot=1,
                                                primary=members[0],
                                                secondaries=members[1:]))
            self._parts[app.app_id] = parts
            self._persist_locked()
        self._restores[app.app_name] = {
            "status": "restoring", "backup_id": req.backup_id,
            "old_app": req.old_app_name, "done": 0,
            "total": app.partition_count}
        for pc in parts:
            src = os.path.join(backup_root, str(req.backup_id),
                               req.old_app_name, str(pc.pidx))
            req_open = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries, envs_json=app.envs_json,
                partition_count=app.partition_count, restore_dir=src)
            for node in [pc.primary] + pc.secondaries:
                self._send_to_node(node, RPC_OPEN_REPLICA, req_open,
                                   ignore_errors=True)
            self._restores[app.app_name]["done"] = pc.pidx + 1
        self._restores[app.app_name]["status"] = "ok"
        return codec.encode(mm.RestoreAppResponse(app_id=app.app_id))

    def _on_start_bulk_load(self, header, body) -> bytes:
        """Meta-driven bulk load: validate provider metadata, then each
        partition primary ingests its set (reference bulk-load DDL,
        SURVEY §2.4 'Bulk load framework'). async_start runs the partition
        walk as a controllable session (pause/restart/cancel/query, the
        reference's bulk-load state machine surface, shell bulk_load.cpp);
        the default stays synchronous."""
        from ..engine import bulk_load as bl

        req = codec.decode(mm.StartBulkLoadRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.StartBulkLoadResponse(
                    error=1, error_text="no such app"))
            sess = self._bulk_loads.get(app.app_id)
            if sess and sess["status"] in ("downloading", "ingesting",
                                           "paused"):
                return codec.encode(mm.StartBulkLoadResponse(
                    error=1, error_text="bulk load already in progress"))
        provider_root = os.path.abspath(req.provider_root)
        try:
            with open(bl.metadata_path(provider_root, req.app_name)) as f:
                bmeta = json.load(f)
        except OSError:
            return codec.encode(mm.StartBulkLoadResponse(
                error=1, error_text="no bulk_load_metadata"))
        if bmeta["partition_count"] != app.partition_count:
            return codec.encode(mm.StartBulkLoadResponse(
                error=1, error_text="partition count mismatch"))
        sess = {"status": "ingesting", "done": 0,
                "total": app.partition_count, "ingested": 0,
                "error_text": "", "provider_root": provider_root,
                "app_name": req.app_name}
        with self._lock:
            self._bulk_loads[app.app_id] = sess
        if req.async_start:
            spawn_thread(self._bulk_load_worker, app, sess, daemon=True)
            return codec.encode(mm.StartBulkLoadResponse())
        self._bulk_load_worker(app, sess)
        if sess["status"] != "succeed":
            return codec.encode(mm.StartBulkLoadResponse(
                error=1, error_text=sess["error_text"] or sess["status"]))
        return codec.encode(mm.StartBulkLoadResponse(
            ingested_records=sess["ingested"]))

    def _bulk_load_worker(self, app, sess) -> None:
        """Walk the partitions, honoring pause/cancel between them."""
        from ..rpc import messages as rpc_msg
        from ..rpc.task_codes import RPC_BULK_LOAD_INGEST

        while True:
            with self._lock:
                if sess["status"] == "canceled":
                    return
                if sess["status"] == "paused":
                    pass  # poll below, outside the lock
                elif sess["done"] >= sess["total"]:
                    sess["status"] = "succeed"
                    return
                pidx = sess["done"]
                parts = list(self._parts[app.app_id])
                status = sess["status"]
            if status == "paused":
                time.sleep(0.05)
                continue
            pc = parts[pidx]
            ingest = rpc_msg.BulkLoadIngestRequest(
                provider_root=sess["provider_root"],
                app_name=sess["app_name"],
                partition_count=app.partition_count)
            # route through the primary's WRITE path: the ingestion command
            # replicates via PacificA so every replica loads the set at the
            # same decree (survives failover)
            out = self._send_to_node(pc.primary, RPC_BULK_LOAD_INGEST, ingest,
                                     app_id=app.app_id, pidx=pc.pidx,
                                     ignore_errors=True)
            resp = (codec.decode(rpc_msg.BulkLoadIngestResponse, out)
                    if out is not None else None)
            with self._lock:
                if resp is None or resp.error:
                    sess["status"] = "failed"
                    sess["error_text"] = (f"partition {pc.pidx} ingest "
                                          + ("failed" if resp is None
                                             else "error"))
                    return
                sess["ingested"] += resp.ingested_records
                sess["done"] += 1

    def _on_query_bulk_load(self, header, body) -> bytes:
        req = codec.decode(mm.QueryBulkLoadRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.QueryBulkLoadResponse(
                    error=1, error_text="no such app"))
            sess = self._bulk_loads.get(app.app_id)
            if sess is None:
                return codec.encode(mm.QueryBulkLoadResponse(status="none"))
            return codec.encode(mm.QueryBulkLoadResponse(
                status=sess["status"], done_partitions=sess["done"],
                total_partitions=sess["total"],
                ingested_records=sess["ingested"],
                error_text=sess["error_text"]))

    def _on_query_restore(self, header, body) -> bytes:
        """query_restore_status <new_app> (reference restore.cpp
        query_restore_status)."""
        req = codec.decode(mm.QueryRestoreRequest, body)
        with self._lock:
            info = self._restores.get(req.app_name)
        if info is None:
            return codec.encode(mm.QueryRestoreResponse(status="none"))
        return codec.encode(mm.QueryRestoreResponse(
            status=info["status"], backup_id=info["backup_id"],
            old_app_name=info["old_app"], done_partitions=info["done"],
            total_partitions=info["total"]))

    def _on_control_bulk_load(self, header, body) -> bytes:
        """pause_bulk_load / restart_bulk_load / cancel_bulk_load
        (reference shell bulk_load.cpp control verbs)."""
        req = codec.decode(mm.ControlBulkLoadRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.ControlBulkLoadResponse(
                    error=1, error_text="no such app"))
            sess = self._bulk_loads.get(app.app_id)
            if sess is None:
                return codec.encode(mm.ControlBulkLoadResponse(
                    error=1, error_text="no bulk load session"))
            cur = sess["status"]
            if req.action == "pause":
                if cur != "ingesting":
                    return codec.encode(mm.ControlBulkLoadResponse(
                        error=1, error_text=f"cannot pause ({cur})"))
                sess["status"] = "paused"
            elif req.action == "restart":
                if cur != "paused":
                    return codec.encode(mm.ControlBulkLoadResponse(
                        error=1, error_text=f"cannot restart ({cur})"))
                sess["status"] = "ingesting"
            elif req.action == "cancel":
                if cur not in ("ingesting", "paused", "failed"):
                    return codec.encode(mm.ControlBulkLoadResponse(
                        error=1, error_text=f"cannot cancel ({cur})"))
                sess["status"] = "canceled"
            else:
                return codec.encode(mm.ControlBulkLoadResponse(
                    error=1, error_text=f"unknown action {req.action!r}"))
        return codec.encode(mm.ControlBulkLoadResponse())

    # --------------------------------------------------------------- balance

    def _on_propose(self, header, body) -> bytes:
        """Move one partition's primary to a named secondary (the
        greedy_load_balancer's move_primary proposal, shell `propose`)."""
        req = codec.decode(mm.ProposeRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.ProposeResponse(error=1,
                                                       error_text="no such app"))
            parts = self._parts[app.app_id]
            if not (0 <= req.pidx < len(parts)):
                return codec.encode(mm.ProposeResponse(error=1,
                                                       error_text="bad pidx"))
            pc = parts[req.pidx]
            if req.target not in pc.secondaries:
                return codec.encode(mm.ProposeResponse(
                    error=1, error_text=f"{req.target} is not a secondary"))
            pc.ballot += 1
            pc.secondaries.remove(req.target)
            pc.secondaries.append(pc.primary)
            pc.primary = req.target
            self._persist_locked()
        self._install_partition(app, pc)
        return codec.encode(mm.ProposeResponse())

    def _on_balance(self, header, body) -> bytes:
        """Greedy primary balancing: while the most-loaded node holds 2+
        more primaries than the least-loaded, demote one whose partition
        has a secondary on the lighter node (the greedy_load_balancer's
        primary-count equalization)."""
        with self._lock:
            if self.level != "lively":
                return codec.encode(mm.BalanceResponse(
                    error=1, moved=0,
                    error_text=f"meta level is {self.level}; balancing "
                               "needs lively (set_meta_level lively)"))
        moved = 0
        for _ in range(64):  # bounded passes
            with self._lock:
                alive = self._alive_nodes_locked()
                if len(alive) < 2:
                    break
                counts = {a: 0 for a in alive}
                for parts in self._parts.values():
                    for pc in parts:
                        if pc.primary in counts:
                            counts[pc.primary] += 1
                heavy = max(alive, key=lambda a: counts[a])
                light = min(alive, key=lambda a: counts[a])
                if counts[heavy] - counts[light] < 2:
                    break
                move = None
                for app in self._apps.values():
                    for pc in self._parts[app.app_id]:
                        if pc.primary == heavy and light in pc.secondaries:
                            move = (app, pc)
                            break
                    if move:
                        break
                if move is None:
                    break
                app, pc = move
                pc.ballot += 1
                pc.secondaries.remove(light)
                pc.secondaries.append(pc.primary)
                pc.primary = light
                self._persist_locked()
            self._install_partition(app, pc)
            moved += 1
        moved += self._balance_copy_secondary()
        return codec.encode(mm.BalanceResponse(moved=moved))

    def _balance_copy_secondary(self) -> int:
        """Total-replica equalization (greedy_load_balancer's copy_secondary
        stage): while the most-loaded node holds 2+ more REPLICAS than the
        least-loaded, migrate one secondary heavy->light — seed the light
        node as a learner (synchronous checkpoint+log-tail learn), admit it
        as a secondary, then drop the heavy copy. Primary moves alone
        equalize leadership but leave replica-count (disk/IO) skew."""
        moved = 0
        for _ in range(64):
            with self._lock:
                alive = self._alive_nodes_locked()
                if len(alive) < 2:
                    break
                loads = {a: self._node_load_locked(a) for a in alive}
                heavy = max(alive, key=lambda a: loads[a])
                light = min(alive, key=lambda a: loads[a])
                if loads[heavy] - loads[light] < 2:
                    break
                move = None
                for app in self._apps.values():
                    for pc in self._parts[app.app_id]:
                        if (heavy in pc.secondaries and pc.primary != light
                                and light not in pc.secondaries):
                            move = (app, pc)
                            break
                    if move:
                        break
                if move is None:
                    break
                app, pc = move
                pc.ballot += 1
                self._persist_locked()
            # seed the light node (learn is synchronous inside the RPC),
            # then admit it and re-push so it starts receiving prepares
            self._install_partition(app, pc, learners=[light])
            with self._lock:
                pc.secondaries.append(light)
                self._persist_locked()
            self._install_partition(app, pc)
            # now drop the heavy copy
            with self._lock:
                pc.ballot += 1
                pc.secondaries.remove(heavy)
                self._persist_locked()
            self._install_partition(app, pc)
            self._send_to_node(heavy, RPC_CLOSE_REPLICA,
                               mm.CloseReplicaRequest(app.app_id, pc.pidx),
                               ignore_errors=True)
            moved += 1
        return moved

    # ---------------------------------------------------------- duplication

    def _refresh_dup_env_locked(self, app) -> None:
        """Mirror the app's dup entries into the reserved app-env; replicas
        reconcile their shippers from it on every view/env install."""
        from ..base import consts

        envs = json.loads(app.envs_json)
        # always present (possibly "[]"): replica-side env application is a
        # MERGE, so deleting the key would leave stale entries live forever
        envs[consts.ENV_DUPLICATION_KEY] = json.dumps(
            self._dups.get(app.app_id, []))
        app.envs_json = json.dumps(envs)

    def _on_add_dup(self, header, body) -> bytes:
        """add_dup <app> <remote_cluster> [freeze] (reference
        duplication.cpp:32-96 via meta_duplication_service::add_duplication).
        freeze=True creates the dup in DS_INIT: registered but not shipping
        until start_dup."""
        req = codec.decode(mm.AddDuplicationRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.AddDuplicationResponse(
                    error=1, error_text="no such app"))
            dups = self._dups.setdefault(app.app_id, [])
            for e in dups:
                if e["remote"] == req.remote_cluster:
                    return codec.encode(mm.AddDuplicationResponse(
                        error=1,
                        error_text=f"duplication to {req.remote_cluster} "
                                   f"already exists (dupid {e['dupid']})"))
            dupid = self._next_dupid
            self._next_dupid += 1
            entry = {"dupid": dupid, "remote": req.remote_cluster,
                     "status": "init" if req.freeze else "start",
                     "fail_mode": "slow",
                     "create_ts_ms": int(time.time() * 1000)}
            dups.append(entry)
            self._refresh_dup_env_locked(app)
            parts = list(self._parts[app.app_id])
            self._persist_locked()
        self._push_app_envs(app, parts)
        return codec.encode(mm.AddDuplicationResponse(
            app_id=app.app_id, dupid=dupid))

    def _on_query_dup(self, header, body) -> bytes:
        req = codec.decode(mm.QueryDuplicationRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.QueryDuplicationResponse(
                    error=1, error_text="no such app"))
            entries = [mm.DupEntry(dupid=e["dupid"], remote=e["remote"],
                                   status=e["status"],
                                   fail_mode=e["fail_mode"],
                                   create_ts_ms=e["create_ts_ms"])
                       for e in self._dups.get(app.app_id, [])]
        return codec.encode(mm.QueryDuplicationResponse(
            app_id=app.app_id, entries=entries))

    def _on_modify_dup(self, header, body) -> bytes:
        """start_dup / pause_dup / remove_dup / set_dup_fail_mode
        (reference change_dup_status + set_dup_fail_mode,
        duplication.cpp:174-260)."""
        req = codec.decode(mm.ModifyDuplicationRequest, body)
        with self._lock:
            app = self._apps.get(req.app_name)
            if app is None:
                return codec.encode(mm.ModifyDuplicationResponse(
                    error=1, error_text="no such app"))
            dups = self._dups.get(app.app_id, [])
            entry = next((e for e in dups if e["dupid"] == req.dupid), None)
            if entry is None:
                return codec.encode(mm.ModifyDuplicationResponse(
                    error=1, error_text=f"no dup {req.dupid} [duplication "
                                        "not found]"))
            # validate EVERYTHING before mutating anything: a half-applied
            # modify must not survive in memory after an error response
            if req.status and req.status not in ("start", "pause", "removed"):
                return codec.encode(mm.ModifyDuplicationResponse(
                    error=1, error_text=f"bad status {req.status}"))
            if req.fail_mode and req.fail_mode not in ("slow", "skip"):
                return codec.encode(mm.ModifyDuplicationResponse(
                    error=1, error_text=f"bad fail_mode {req.fail_mode}"))
            if req.status == "removed":
                dups.remove(entry)
            elif req.status:
                entry["status"] = req.status
            if req.fail_mode:
                entry["fail_mode"] = req.fail_mode
            self._refresh_dup_env_locked(app)
            parts = list(self._parts[app.app_id])
            self._persist_locked()
        self._push_app_envs(app, parts)
        return codec.encode(mm.ModifyDuplicationResponse())

    def push_dup_envs(self) -> None:
        """Periodic refresh of dup entries (incl. beacon-folded confirmed
        decrees) to every replica of dup'd apps — the reference's dup-sync
        cadence. Without this, secondaries' plog-GC floors only advance on
        view changes and the log pins at the dup-creation decree forever."""
        with self._lock:
            targets = [(self._apps_by_id_locked(aid), entries)
                       for aid, entries in self._dups.items() if entries]
            targets = [(app, list(self._parts[app.app_id]))
                       for app, entries in targets if app is not None]
            for app, _ in targets:
                self._refresh_dup_env_locked(app)
            self._persist_locked()
        for app, parts in targets:
            self._push_app_envs(app, parts)

    def _apps_by_id_locked(self, app_id: int):
        return next((a for a in self._apps.values() if a.app_id == app_id),
                    None)

    # ------------------------------------------------------- backup policies

    def _on_add_backup_policy(self, header, body) -> bytes:
        req = codec.decode(mm.AddBackupPolicyRequest, body)
        p = req.policy
        with self._lock:
            if p.name in self._policies:
                return codec.encode(mm.AddBackupPolicyResponse(
                    error=1, error_text=f"policy {p.name} exists"))
            if not p.name or not p.backup_root or not p.apps:
                return codec.encode(mm.AddBackupPolicyResponse(
                    error=1, error_text="name, backup_root and apps required"))
            missing = [a for a in p.apps if a not in self._apps]
            if missing:
                return codec.encode(mm.AddBackupPolicyResponse(
                    error=1, error_text=f"no such app(s): {missing}"))
            self._policies[p.name] = {
                "name": p.name, "backup_root": p.backup_root,
                "apps": list(p.apps),
                "interval_seconds": max(1, p.interval_seconds),
                "history_count": max(1, p.history_count),
                "enabled": bool(p.enabled),
                "next_backup_ts": int(p.next_backup_ts),
                "recent_backup_ids": []}
            self._persist_locked()
        return codec.encode(mm.AddBackupPolicyResponse())

    def _on_ls_backup_policy(self, header, body) -> bytes:
        req = codec.decode(mm.LsBackupPolicyRequest, body)
        with self._lock:
            if req.name:
                pols = [self._policies[req.name]] \
                    if req.name in self._policies else []
                if not pols:
                    return codec.encode(mm.LsBackupPolicyResponse(
                        error=1, error_text=f"no policy {req.name}"))
            else:
                pols = list(self._policies.values())
            return codec.encode(mm.LsBackupPolicyResponse(
                policies=[mm.BackupPolicyInfo(**p) for p in pols]))

    def _on_modify_backup_policy(self, header, body) -> bytes:
        req = codec.decode(mm.ModifyBackupPolicyRequest, body)
        with self._lock:
            p = self._policies.get(req.name)
            if p is None:
                return codec.encode(mm.ModifyBackupPolicyResponse(
                    error=1, error_text=f"no policy {req.name}"))
            if req.enabled in (0, 1):
                p["enabled"] = bool(req.enabled)
            if req.interval_seconds > 0:
                p["interval_seconds"] = req.interval_seconds
            if req.history_count > 0:
                p["history_count"] = req.history_count
            for a in req.add_apps:
                if a not in self._apps:
                    return codec.encode(mm.ModifyBackupPolicyResponse(
                        error=1, error_text=f"no such app {a}"))
                if a not in p["apps"]:
                    p["apps"].append(a)
            for a in req.remove_apps:
                if a in p["apps"]:
                    p["apps"].remove(a)
            self._persist_locked()
        return codec.encode(mm.ModifyBackupPolicyResponse())

    def run_backup_policies(self, now: int = None) -> list:
        """Execute every enabled policy that is due; prune history beyond
        history_count (reference policy scheduler in meta backup_service,
        SURVEY §2.4 'Cold backup'). Called from the meta app's timer (and
        directly by tests with a pinned `now`). Returns [(policy, app,
        backup_id or None)]."""
        import shutil

        now = int(time.time()) if now is None else now
        ran = []
        with self._lock:
            due = [dict(p) for p in self._policies.values()
                   if p["enabled"] and p["next_backup_ts"] <= now]
        for p in due:
            # one backup_id per policy run, shared by all its apps (the
            # reference's per-policy backup_id), so retention prunes runs;
            # derived from `now` so tests with a pinned clock stay stable.
            # Each policy backs up under backup_root/<policy_name>/ so two
            # policies sharing a root can never collide on a run id and
            # retention-prune each other's trees.
            run_id = now * 1000
            root = os.path.join(p["backup_root"], p["name"])
            new_ids = []
            for app_name in p["apps"]:
                err, bid = self._do_backup(app_name, root,
                                           backup_id=run_id)
                ran.append((p["name"], app_name, None if err else bid))
                if err:
                    print(f"[backup-policy {p['name']}] {app_name}: {err}",
                          flush=True)
                else:
                    new_ids.append(bid)
            with self._lock:
                live = self._policies.get(p["name"])
                if live is None:
                    continue
                ids = sorted(set(live["recent_backup_ids"]) | set(new_ids))
                # retention: newest history_count backups stay on disk
                while len(ids) > live["history_count"]:
                    victim = ids.pop(0)
                    shutil.rmtree(os.path.join(
                        os.path.abspath(live["backup_root"]), live["name"],
                        str(victim)), ignore_errors=True)
                live["recent_backup_ids"] = ids
                live["next_backup_ts"] = now + live["interval_seconds"]
                self._persist_locked()
        return ran

    # -------------------------------------------------- disaster recovery

    def _on_recover(self, header, body) -> bytes:
        """Rebuild app + partition state from the replicas the given nodes
        actually hold — the reference `recover` command for a meta that
        lost its state (recovery.cpp / meta_service recover-from-replicas).
        Only apps unknown to this meta are recovered; the member with the
        highest (ballot, last_committed) becomes primary."""
        req = codec.decode(mm.RecoverRequest, body)
        reports = {}
        for node in req.nodes:
            out = self._send_to_node(node, RPC_QUERY_REPLICA_INFO,
                                     mm.QueryReplicaInfoRequest(),
                                     ignore_errors=True)
            if out is None:
                continue
            resp = codec.decode(mm.QueryReplicaInfoResponse, out)
            with self._lock:
                self._nodes.setdefault(node, time.monotonic())
            for ri in resp.replicas:
                reports.setdefault(ri.app_id, {}).setdefault(
                    ri.pidx, []).append((node, ri))
        recovered = []
        with self._lock:
            known_ids = {a.app_id for a in self._apps.values()}
            for app_id in sorted(reports):
                if app_id in known_ids:
                    continue
                by_pidx = reports[app_id]
                any_ri = next(iter(by_pidx.values()))[0][1]
                if not any_ri.app_name or any_ri.app_name in self._apps:
                    continue
                pcount = max(r.partition_count
                             for rs in by_pidx.values() for _, r in rs)
                pcount = max(pcount, max(by_pidx) + 1)
                app = mm.AppInfo(app_name=any_ri.app_name, app_id=app_id,
                                 partition_count=pcount,
                                 replica_count=max(len(rs) for rs
                                                   in by_pidx.values()),
                                 envs_json=any_ri.envs_json)
                parts = []
                for pidx in range(pcount):
                    holders = sorted(
                        by_pidx.get(pidx, []),
                        key=lambda t: (t[1].ballot, t[1].last_committed),
                        reverse=True)
                    if holders:
                        primary = holders[0][0]
                        ballot = holders[0][1].ballot + 1
                        secondaries = [n for n, _ in holders[1:]]
                    else:
                        primary, ballot, secondaries = "", 1, []
                    parts.append(mm.PartitionConfig(
                        pidx=pidx, ballot=ballot, primary=primary,
                        secondaries=secondaries))
                self._apps[app.app_name] = app
                self._parts[app_id] = parts
                self._next_app_id = max(self._next_app_id, app_id + 1)
                recovered.append(app.app_name)
            self._persist_locked()
        for name in recovered:
            app = self._apps[name]
            for pc in self._parts[app.app_id]:
                if pc.primary:
                    self._install_partition(app, pc)
        return codec.encode(mm.RecoverResponse(recovered_apps=recovered))

    def _on_ddd_diagnose(self, header, body) -> bytes:
        """Diagnose 'double-dead' partitions — every member lost, primary
        left empty by reconfiguration — and (with force) promote the
        best-qualified holder among currently-alive nodes (reference
        ddd_diagnose, shell/commands/recovery.cpp + ddd_partition_info)."""
        req = codec.decode(mm.DddDiagnoseRequest, body)
        with self._lock:
            if req.app_name and req.app_name not in self._apps:
                # a typo with force=True must NOT widen to a cluster-wide fix
                return codec.encode(mm.DddDiagnoseResponse(
                    error=1, error_text=f"no such app {req.app_name}"))
            apps = ([self._apps[req.app_name]] if req.app_name
                    else list(self._apps.values()))
            alive = self._alive_nodes_locked()
            dead_parts = []
            for app in apps:
                for pc in self._parts[app.app_id]:
                    members = [m for m in [pc.primary] + pc.secondaries if m]
                    if not members or not any(m in alive for m in members):
                        dead_parts.append((app, pc))
        out = []
        for app, pc in dead_parts:
            info = mm.DddPartitionInfo(
                app_name=app.app_name, pidx=pc.pidx,
                reason="no alive member in config")
            holders = []
            for node in alive:
                key = f"{app.app_id}.{pc.pidx}"
                with self._lock:
                    has = key in self._node_replicas.get(node, ())
                if not has:
                    continue
                st = self._query_replica_state(node, app.app_id, pc.pidx)
                if st is not None and not st.error:
                    holders.append((node, st))
                    info.candidates.append(
                        f"{node} ballot={st.ballot} lc={st.last_committed}")
            if req.force and holders:
                holders.sort(key=lambda t: (t[1].ballot, t[1].last_committed),
                             reverse=True)
                best = holders[0][0]
                with self._lock:
                    pc.ballot = max(pc.ballot,
                                    max(st.ballot for _, st in holders)) + 1
                    pc.primary = best
                    pc.secondaries = [n for n, _ in holders[1:]]
                    self._persist_locked()
                self._install_partition(app, pc)
                info.action = f"promoted {best}"
            out.append(info)
        return codec.encode(mm.DddDiagnoseResponse(partitions=out))

    def _on_query_cluster_state(self, header, body) -> bytes:
        """One-RPC cluster-observability snapshot (ISSUE 8): node liveness,
        every app's partition config, and the beacon-folded per-replica
        lag/audit states — everything the cluster doctor folds that the
        meta already knows. Served at `blind` level too (pure query)."""
        with self._lock:
            now = time.monotonic()
            nodes = {addr: {"alive": (now - last) < self.fd_grace,
                            "last_beacon_ago_s": round(now - last, 3)}
                     for addr, last in self._nodes.items()}
            apps = {}
            for app in self._apps.values():
                apps[app.app_name] = {
                    "app_id": app.app_id,
                    "partition_count": app.partition_count,
                    "replica_count": app.replica_count,
                    "partitions": [{
                        "pidx": pc.pidx, "ballot": pc.ballot,
                        "primary": pc.primary,
                        "secondaries": list(pc.secondaries)}
                        for pc in self._parts[app.app_id]]}
            # duplication entries ride the snapshot too (deep-copied: the
            # beacon fold mutates `confirmed` concurrently) — the
            # cross-cluster audit (ISSUE 11) anchors its digest compare
            # at these beacon-folded confirmed decrees
            dups = {str(aid): [dict(e, confirmed=dict(e.get("confirmed", {})))
                               for e in entries]
                    for aid, entries in self._dups.items() if entries}
            state = {"nodes": nodes, "apps": apps,
                     "replica_states": {n: dict(s) for n, s
                                        in self._node_states.items()},
                     "dups": dups,
                     "meta_level": self.level}
        return codec.encode(mm.QueryClusterStateResponse(
            state_json=json.dumps(state)))

    def _on_list_nodes(self, header, body) -> bytes:
        with self._lock:
            nodes = []
            now = time.monotonic()
            for addr, last in self._nodes.items():
                nodes.append(mm.NodeInfo(
                    address=addr, alive=(now - last) < self.fd_grace,
                    last_beacon_ms=int(last * 1000),
                    replica_count=sum(
                        1 for parts in self._parts.values() for pc in parts
                        if pc.primary == addr or addr in pc.secondaries)))
            return codec.encode(mm.ListNodesResponse(nodes=nodes))

    # ------------------------------------------------------------------- FD

    def _on_beacon(self, header, body) -> bytes:
        req = codec.decode(mm.BeaconRequest, body)
        with self._lock:
            known = req.node in self._nodes
            self._nodes[req.node] = time.monotonic()
            # what the node actually holds — ddd_diagnose candidate source
            self._node_replicas[req.node] = set(req.alive_replicas)
            # per-replica lag/audit states (the cluster doctor's input);
            # in-memory only, like the liveness map — re-beacons rebuild it
            states = {}
            tables = {}
            for item in req.replica_states:
                try:
                    st = json.loads(item)
                    if st.get("status") == "TABLE_STATS":
                        # tenant-ledger fragments (ISSUE 18) ride the
                        # beacon but are NOT replica states — divert them
                        # so every per-gpid consumer (doctor lag fold,
                        # quarantine repair, scheduler debt) keeps its
                        # replicas-only invariant
                        tables[st["gpid"]] = st
                    else:
                        states[st["gpid"]] = st
                except (ValueError, KeyError, TypeError):
                    continue
            self._node_states[req.node] = states
            self._node_tables[req.node] = tables
            # fold primary-reported dup confirmed decrees into the entries
            # (reference duplication progress sync); not persisted per
            # beacon — losing it on meta restart only means extra plog
            # retention + at-least-once re-shipping, both safe
            for item in req.dup_progress:
                try:
                    ids, decree = item.split(":")
                    app_id, pidx, dupid = (int(x) for x in ids.split("."))
                    decree = int(decree)
                except ValueError:
                    continue
                for e in self._dups.get(app_id, []):
                    if e["dupid"] == dupid:
                        conf = e.setdefault("confirmed", {})
                        conf[str(pidx)] = max(conf.get(str(pidx), 0), decree)
        # deliberately NO _persist() here: beacons reach followers too
        # (the leader-only RPC guard exempts RPC_FD_BEACON so takeover
        # starts with a warm liveness map), and _load() rebuilds _nodes
        # from re-beacons anyway — a follower persisting its stale DDL
        # snapshot on first sight of a node would clobber every DDL the
        # leader acked since the follower's last reload
        return codec.encode(mm.BeaconResponse(allowed=True))

    def reload_state(self) -> None:
        """Takeover path: re-read the shared state file so every DDL the
        previous leader acknowledged (persist-before-ack) is visible here.
        The liveness map is kept — followers absorb beacons, so takeover
        does not re-declare every node dead."""
        with self._lock:
            nodes, node_reps = self._nodes, self._node_replicas
            self._apps, self._parts = {}, {}
            self._dups, self._policies, self._dropped = {}, {}, {}
            self._load()
            self._nodes, self._node_replicas = nodes, node_reps

    def check_leases(self) -> list:
        """Expire dead nodes and reconfigure their partitions. Returns the
        list of nodes declared dead. Call from a timer (or tests)."""
        if self.level == "stopped":
            return []
        now = time.monotonic()
        with self._lock:
            dead = [a for a, last in self._nodes.items()
                    if (now - last) >= self.fd_grace]
        for node in dead:
            self._handle_node_death(node)
        return dead

    def mark_node_dead(self, addr: str) -> None:
        """Force-expire (tests / admin)."""
        with self._lock:
            if addr in self._nodes:
                self._nodes[addr] = -1e18
        self._handle_node_death(addr)

    def forget_node(self, addr: str) -> None:
        """Drop a DEAD node from the liveness map entirely (admin /
        chaos heal): the node was replaced by one on a new address
        rather than restarted, so its tombstone must not read as a
        permanent 'node dead' health cause. A forgotten node that
        beacons again simply re-registers."""
        with self._lock:
            self._nodes.pop(addr, None)
            self._node_replicas.pop(addr, None)
            self._node_states.pop(addr, None)
            self._node_tables.pop(addr, None)

    # ---------------------------------------------------------- failover

    def _handle_node_death(self, node: str) -> None:
        with self._lock:
            # drop the dead node's beacon-folded lag/audit states: frozen
            # values would otherwise feed the doctor's lag fold forever
            # (a rejoining node re-beacons them). _node_replicas is KEPT —
            # ddd_diagnose hunts candidates on dead nodes through it.
            self._node_states.pop(node, None)
            self._node_tables.pop(node, None)
            moves = []
            for app in self._apps.values():
                for pc in self._parts[app.app_id]:
                    if pc.primary == node or node in pc.secondaries:
                        moves.append((app, pc))
        for app, pc in moves:
            self._reconfigure_partition(app, pc, dead=node)

    def _reconfigure_partition(self, app: mm.AppInfo, pc: mm.PartitionConfig,
                               dead: str) -> None:
        with self._lock:
            members = [m for m in [pc.primary] + pc.secondaries if m != dead]
            if not members:
                pc.primary = ""
                pc.secondaries = []
                self._persist_locked()
                return
            pc.ballot += 1
            if pc.primary == dead:
                # promote the secondary with the longest prepared log
                best, best_state = None, (-1, -1)
                for m in members:
                    st = self._query_replica_state(m, app.app_id, pc.pidx)
                    if st is not None and (st.ballot, st.last_prepared) > best_state:
                        best, best_state = m, (st.ballot, st.last_prepared)
                pc.primary = best or members[0]
            pc.secondaries = [m for m in members if m != pc.primary]
            # rebuild replica count on a fresh node — unless the operator
            # froze meta-initiated data movement (get/set_meta_level)
            learners = []
            alive = self._alive_nodes_locked()
            candidates = [n for n in alive if n not in members]
            if (self.level != "freezed"
                    and len(members) < app.replica_count and candidates):
                new_node = min(candidates, key=self._node_load_locked)
                learners = [new_node]
            self._persist_locked()
        self._install_partition(app, pc, learners=learners)
        if learners:
            with self._lock:
                for ln in learners:
                    if ln not in pc.secondaries:
                        pc.secondaries.append(ln)
                self._persist_locked()
            # Re-push the updated view so the primary's in-memory membership
            # includes the new member and it starts receiving prepares;
            # without this the learner is fresh only as of the learn snapshot
            # while meta reports it as a full secondary.
            self._install_partition(app, pc)

    def repair_under_replication(self) -> int:
        """Re-seed lost replicas onto alive nodes — the healing half of
        `_reconfigure_partition`'s learner path, runnable on demand
        (reference meta's partition-guardian cure role). A node death
        with no spare node leaves partitions under-replicated forever:
        at death time every alive node was already a member, and nothing
        re-examines the partition when a replacement (or the restarted
        node itself) later joins. The chaos harness's node-kill actor
        calls this after the killed node rejoins, so a kill+restart leg
        can end with the doctor HEALTHY instead of pinned degraded.
        Returns the number of partitions a learner was seeded for."""
        if self.level in ("stopped", "blind", "freezed"):
            return 0
        with self._lock:
            work = [(app, pc) for app in self._apps.values()
                    for pc in self._parts[app.app_id]]
        repaired = 0
        for app, pc in work:
            with self._lock:
                alive = self._alive_nodes_locked()
                if not pc.primary or pc.primary not in alive:
                    continue  # dead primary is _handle_node_death's job
                members = [m for m in [pc.primary] + pc.secondaries if m]
                live = [m for m in members if m in alive]
                candidates = [n for n in alive if n not in members]
                if len(live) >= app.replica_count or not candidates:
                    continue
                new_node = min(candidates, key=self._node_load_locked)
                pc.ballot += 1
                self._persist_locked()
            # learn is synchronous inside the open RPC: the learner copies
            # the primary's checkpoint + log tail before we admit it — a
            # failed seed (target mid-restart) must NOT be admitted, or a
            # hollow "secondary" reads as healthy and a later promotion
            # loses acked writes; the next repair pass retries
            if not self._install_partition(app, pc, learners=[new_node]):
                continue
            with self._lock:
                if new_node not in pc.secondaries:
                    pc.secondaries.append(new_node)
                self._persist_locked()
            # re-push the view so the primary's in-memory membership
            # includes the admitted member (same reason as the failover
            # learner path above)
            self._install_partition(app, pc)
            repaired += 1
        return repaired

    def repair_quarantined(self) -> int:
        """Heal quarantined replicas (ISSUE 17): a beacon state with
        status QUARANTINED means that node pulled its copy off the
        serving path after a corruption hit and moved the data dir into
        forensics — the copy is gone. Treat it exactly like a lost
        replica: drop the node from the partition's membership
        (`_reconfigure_partition`), which re-seeds a learner from the
        healthy primary via the block-shipped learn. The quarantined
        node itself is alive and now a non-member, so it is usually the
        re-seed target — the heal lands a fresh dir on the same node.
        Membership is the dedup: once dropped, the still-QUARANTINED
        beacon state no longer names a member, so a heal fires once.
        Returns the number of partitions reconfigured."""
        if self.level in ("stopped", "blind", "freezed"):
            return 0
        with self._lock:
            apps_by_id = {app.app_id: app for app in self._apps.values()}
            hits = []
            for node, states in self._node_states.items():
                for gpid, st in states.items():
                    if st.get("status") != "QUARANTINED":
                        continue
                    a, _, p = gpid.partition(".")
                    try:
                        app_id, pidx = int(a), int(p)
                    except ValueError:
                        continue
                    app = apps_by_id.get(app_id)
                    pcs = self._parts.get(app_id) or []
                    if app is None or pidx >= len(pcs):
                        continue
                    pc = pcs[pidx]
                    if pc.primary == node or node in pc.secondaries:
                        hits.append((app, pc, node))
        from ..runtime import events

        healed = 0
        for app, pc, node in hits:
            events.emit("meta.heal_quarantine", "warn",
                        gpid=f"{app.app_id}.{pc.pidx}", node=node)
            # ack the quarantine BEFORE reconfiguring: the close clears
            # the node's beaconed QUARANTINED record (otherwise it
            # reports the lost copy forever and the doctor stays
            # degraded on a healed partition). The quarantined node is
            # alive and usually the reconfigure's re-seed target — an
            # after-the-fact close would tear down the replica the
            # re-seed just landed on that same node.
            self._send_to_node(node, RPC_CLOSE_REPLICA,
                               mm.CloseReplicaRequest(app.app_id, pc.pidx),
                               ignore_errors=True)
            with self._lock:
                # drop the folded state we just acted on: a second
                # repair tick inside one beacon interval must not read
                # the stale QUARANTINED entry and nuke the re-seeded
                # copy; the next beacon repopulates the truth
                st = self._node_states.get(node)
                if st:
                    st.pop(f"{app.app_id}.{pc.pidx}", None)
            self._reconfigure_partition(app, pc, dead=node)
            healed += 1
        return healed

    def _install_partition(self, app, pc: mm.PartitionConfig, learners=()):
        """Push the view to every member (primary first), seed learners.
        -> True when every learner's seeding open succeeded (the learn is
        synchronous inside the open RPC, so a non-error reply means the
        checkpoint + log tail were copied); member pushes stay
        best-effort."""
        with self._lock:
            # fresh dup entries (incl. beacon-folded confirmed decrees) ride
            # every install: a promoted primary starts its shippers at the
            # meta-confirmed floor instead of re-shipping from zero
            if self._dups.get(app.app_id) is not None:
                self._refresh_dup_env_locked(app)
        req = mm.OpenReplicaRequest(
            app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
            ballot=pc.ballot, primary=pc.primary, secondaries=pc.secondaries,
            envs_json=app.envs_json, partition_count=app.partition_count)
        for node in [pc.primary] + pc.secondaries:
            if node:
                self._send_to_node(node, RPC_OPEN_REPLICA, req,
                                   ignore_errors=True)
        seeded = True
        for node in learners:
            lreq = mm.OpenReplicaRequest(
                app_name=app.app_name, app_id=app.app_id, pidx=pc.pidx,
                ballot=pc.ballot, primary=pc.primary,
                secondaries=pc.secondaries + [node],
                learn_from=pc.primary, envs_json=app.envs_json,
                partition_count=app.partition_count)
            try:
                self._send_to_node(node, RPC_OPEN_REPLICA, lreq)
            except (RpcError, OSError) as e:
                # seed failures are retried by the caller's next pass, but
                # never silently: an operator chasing "why does this
                # partition stay under-replicated" needs the learner's
                # actual error (PEGASUS_REPAIR_DEBUG=1)
                if os.environ.get("PEGASUS_REPAIR_DEBUG"):
                    print(f"[meta] seed {app.app_name}.{pc.pidx} learner "
                          f"{node} failed: {e!r}"[:400], flush=True)
                seeded = False
        return seeded

    # ------------------------------------------------------------- helpers

    def _query_replica_state(self, node, app_id, pidx):
        try:
            body = self._send_to_node(node, RPC_REPLICA_STATE,
                                      mm.ReplicaStateRequest(app_id, pidx))
            return codec.decode(mm.ReplicaStateResponse, body)
        except (RpcError, OSError):
            return None

    def _send_to_node(self, node: str, code: str, req, ignore_errors=False,
                      app_id: int = 0, pidx: int = 0):
        # per-partition lifecycle requests carry their own (app_id, pidx);
        # lift them into the RPC header so a partition-group serving node
        # (replication/serve_groups.py) routes the frame without decoding
        # the body
        if app_id == 0 and pidx == 0:
            app_id = getattr(req, "app_id", 0) or 0
            pidx = getattr(req, "pidx", 0) or 0
        host, _, port = node.rpartition(":")
        try:
            conn = self.pool.get((host, int(port)))
            _, body = conn.call(code, codec.encode(req), timeout=60.0,
                                app_id=app_id, partition_index=pidx)
            return body
        except (RpcError, OSError):
            if ignore_errors:
                return None
            raise

    def _alive_nodes_locked(self) -> list:
        now = time.monotonic()
        return sorted(a for a, last in self._nodes.items()
                      if (now - last) < self.fd_grace)

    def _node_load_locked(self, addr: str) -> int:
        return sum(1 for parts in self._parts.values() for pc in parts
                   if pc.primary == addr or addr in pc.secondaries)

    def _pick_nodes_locked(self, count: int, seed: int) -> list:
        alive = self._alive_nodes_locked()
        ordered = sorted(alive, key=lambda a: (self._node_load_locked(a), a))
        rot = ordered[seed % len(ordered):] + ordered[:seed % len(ordered)]
        return rot[:count]

    # ------------------------------------------------------------ persistence

    def _persist(self):
        with self._lock:
            self._persist_locked()

    def _persist_locked(self):
        if self.election is not None:
            # fencing: a leader stalled past its lease (GIL pause, NFS
            # hang) must not clobber state a newer leader wrote. Re-verify
            # the lease at the last moment, and refuse to overwrite a
            # state file carrying a newer epoch than ours. Both fences
            # RAISE: the caller is an acking DDL handler and persist-
            # before-ack is the HA contract — a swallowed fence would ack
            # a write that never became durable. The RPC layer turns the
            # raise into an error reply; clients retry against the real
            # leader.
            if not self.election.verify_for_persist():
                print(f"[meta] {self.election.my_addr}: persist fenced — "
                      "lease lost", flush=True)
                raise RuntimeError("meta persist fenced: lease lost")
            disk_epoch = self._disk_state_epoch_locked()
            if disk_epoch > self.election.epoch:
                print(f"[meta] {self.election.my_addr}: persist fenced — "
                      f"state epoch {disk_epoch} > lease epoch "
                      f"{self.election.epoch}", flush=True)
                self.election._set_leader(False)
                # release the lease carrying the NEWER lineage forward so
                # the next claim (ours or anyone's) exceeds the state
                # epoch and can persist again — fence-and-hold would
                # livelock: the lease still names us, every tick would
                # re-promote, every persist would re-fence
                self.election.release_lease(disk_epoch)
                raise RuntimeError(
                    f"meta persist fenced: state epoch {disk_epoch} newer")
        state = {
            "epoch": (self.election.epoch if self.election is not None
                      else self._state_epoch),
            "next_app_id": self._next_app_id,
            "next_dupid": self._next_dupid,
            "apps": {n: vars(a) for n, a in self._apps.items()},
            "parts": {str(aid): [vars(pc) for pc in parts]
                      for aid, parts in self._parts.items()},
            "nodes": list(self._nodes),
            "dups": {str(aid): entries for aid, entries in self._dups.items()},
            "policies": self._policies,
            "dropped": {str(aid): e for aid, e in self._dropped.items()},
            "level": self.level,
        }
        tmp = self.state_path + ".tmp"
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            st = os.fstat(f.fileno())
        os.replace(tmp, self.state_path)
        self._state_epoch = int(state["epoch"])
        # fingerprint from the fd we WROTE, never a path re-stat: a racer's
        # replace landing between our os.replace and a stat would get
        # fingerprinted with OUR cached epoch and permanently disarm the
        # persist fence (rename keeps tmp's inode, so fstat matches the
        # file now at state_path — unless someone else already replaced it,
        # which is exactly the case that must MISS the cache)
        self._state_fp = (st.st_ino, st.st_mtime_ns, st.st_size)

    def _disk_state_epoch_locked(self) -> int:
        """The on-disk state epoch for the persist fence, WITHOUT re-parsing
        the whole state file on every acked DDL (ADVICE r5: that parse is
        O(state size) per persist). The cached epoch is valid as long as the
        file's stat fingerprint still matches what this process last
        read/wrote; any external write (a newer leader's persist, a manual
        edit) changes inode/mtime/size and forces one full re-read — so the
        epoch fence still catches exactly the writes it existed for."""
        try:
            st = os.stat(self.state_path)
            fp = (st.st_ino, st.st_mtime_ns, st.st_size)
        except OSError:
            return 0
        if fp != self._state_fp:
            self._state_epoch = self._read_state_epoch()
            self._state_fp = fp
        return self._state_epoch

    def _read_state_epoch(self) -> int:
        try:
            with open(self.state_path) as f:
                return int(json.load(f).get("epoch", 0))
        except (OSError, ValueError):
            return 0

    def _load(self):
        if not os.path.exists(self.state_path):
            return
        with open(self.state_path) as f:
            state = json.load(f)
            st = os.fstat(f.fileno())  # the file we READ, race-free
        self._state_epoch = int(state.get("epoch", 0))
        self._state_fp = (st.st_ino, st.st_mtime_ns, st.st_size)
        self._next_app_id = state["next_app_id"]
        self._next_dupid = state.get("next_dupid", 1)
        self._apps = {n: mm.AppInfo(**a) for n, a in state["apps"].items()}
        self._parts = {int(aid): [mm.PartitionConfig(**pc) for pc in parts]
                       for aid, parts in state["parts"].items()}
        self._dups = {int(aid): entries
                      for aid, entries in state.get("dups", {}).items()}
        self._policies = state.get("policies", {})
        self._dropped = {int(aid): e
                         for aid, e in state.get("dropped", {}).items()}
        self.level = state.get("level", "lively")
        # nodes must re-beacon after a meta restart
        self._nodes = {}
