"""Meta-server wire messages (the replication_ddl_client / meta surface).

Mirrors the rDSN meta contract Pegasus consumes (SURVEY.md §2.4 'Meta
server'): table DDL, partition-config queries, app-envs, and the beacon
failure detector (config.ini:232-238). Addresses travel as "host:port"
strings.
"""

from dataclasses import dataclass, field
from typing import List


@dataclass
class PartitionConfig:
    pidx: int = 0
    ballot: int = 0
    primary: str = ""                 # "" = unassigned
    secondaries: List[str] = field(default_factory=list)


@dataclass
class AppInfo:
    app_name: str = ""
    app_id: int = 0
    partition_count: int = 0
    replica_count: int = 3
    status: str = "AS_AVAILABLE"
    envs_json: str = "{}"


@dataclass
class CreateAppRequest:
    app_name: str = ""
    partition_count: int = 8
    replica_count: int = 3
    envs_json: str = "{}"


@dataclass
class CreateAppResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0


@dataclass
class DropAppRequest:
    app_name: str = ""
    reserve_seconds: int = 0          # >0: soft-drop, recallable this long


@dataclass
class DropAppResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class ControlMetaRequest:
    set_level: str = ""               # "" = just read; freezed|steady|lively


@dataclass
class ControlMetaResponse:
    error: int = 0
    error_text: str = ""
    level: str = ""


@dataclass
class RecallAppRequest:
    app_id: int = 0
    new_app_name: str = ""            # "" = original name


@dataclass
class RecallAppResponse:
    error: int = 0
    error_text: str = ""
    app_name: str = ""


@dataclass
class ListAppsRequest:
    pass


@dataclass
class ListAppsResponse:
    error: int = 0
    apps: List[AppInfo] = field(default_factory=list)


@dataclass
class QueryConfigRequest:
    app_name: str = ""


@dataclass
class QueryConfigResponse:
    error: int = 0
    error_text: str = ""
    app: AppInfo = field(default_factory=AppInfo)
    partitions: List[PartitionConfig] = field(default_factory=list)


@dataclass
class SetAppEnvsRequest:
    app_name: str = ""
    envs_json: str = "{}"


@dataclass
class SetAppEnvsResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class BeaconRequest:
    node: str = ""                    # replica node address
    alive_replicas: List[str] = field(default_factory=list)  # "app_id.pidx"
    # per-partition duplication confirmed decrees from this node's primaries:
    # "app_id.pidx.dupid:decree" — the meta folds them into its dup entries
    # (the reference's duplication_info.progress sync)
    dup_progress: List[str] = field(default_factory=list)
    # per-replica lag/audit state, one JSON object per hosted replica
    # ({"gpid","status","ballot","committed","applied","prepared",
    #   "audit":{...}}) — the meta folds these into its cluster-state view
    # so the doctor reads lag AND decree-anchored digests from ONE place
    replica_states: List[str] = field(default_factory=list)


@dataclass
class BeaconResponse:
    error: int = 0
    allowed: bool = True              # lease granted


@dataclass
class ProposeRequest:
    """Move a partition's primary (the balancer's move_primary action)."""

    app_name: str = ""
    pidx: int = 0
    target: str = ""                  # must be a current secondary


@dataclass
class ProposeResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class BalanceRequest:
    pass


@dataclass
class BalanceResponse:
    error: int = 0
    error_text: str = ""
    moved: int = 0


@dataclass
class NodeInfo:
    address: str = ""
    alive: bool = True
    last_beacon_ms: int = 0
    replica_count: int = 0


@dataclass
class ListNodesRequest:
    pass


@dataclass
class ListNodesResponse:
    error: int = 0
    nodes: List[NodeInfo] = field(default_factory=list)


@dataclass
class SplitAppRequest:
    app_name: str = ""


@dataclass
class SplitAppResponse:
    error: int = 0
    error_text: str = ""
    new_partition_count: int = 0


@dataclass
class BackupAppRequest:
    app_name: str = ""
    backup_root: str = ""             # block-service path (local FS provider)


@dataclass
class BackupAppResponse:
    error: int = 0
    error_text: str = ""
    backup_id: int = 0


@dataclass
class RestoreAppRequest:
    backup_root: str = ""
    backup_id: int = 0
    old_app_name: str = ""
    new_app_name: str = ""


@dataclass
class RestoreAppResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0


@dataclass
class StartBulkLoadRequest:
    app_name: str = ""
    provider_root: str = ""
    # async session (reference semantics): the response reports the session
    # started; progress comes from query_bulk_load_status. Default stays
    # synchronous for in-process callers.
    async_start: bool = False


@dataclass
class StartBulkLoadResponse:
    error: int = 0
    error_text: str = ""
    ingested_records: int = 0


@dataclass
class QueryBulkLoadRequest:
    app_name: str = ""


@dataclass
class QueryBulkLoadResponse:
    error: int = 0
    error_text: str = ""
    # downloading | ingesting | paused | canceled | failed | succeed | none
    status: str = "none"
    done_partitions: int = 0
    total_partitions: int = 0
    ingested_records: int = 0


@dataclass
class QueryRestoreRequest:
    app_name: str = ""


@dataclass
class QueryRestoreResponse:
    error: int = 0
    error_text: str = ""
    status: str = "none"   # restoring | ok | none
    backup_id: int = 0
    old_app_name: str = ""
    done_partitions: int = 0
    total_partitions: int = 0


@dataclass
class ControlBulkLoadRequest:
    app_name: str = ""
    action: str = ""      # pause | restart | cancel


@dataclass
class ControlBulkLoadResponse:
    error: int = 0
    error_text: str = ""


# --- meta -> replica node commands ---

@dataclass
class OpenReplicaRequest:
    app_name: str = ""
    app_id: int = 0
    pidx: int = 0
    ballot: int = 0
    primary: str = ""
    secondaries: List[str] = field(default_factory=list)
    learn_from: str = ""              # non-empty: seed from this node first
    envs_json: str = "{}"
    partition_count: int = 0          # for partition-hash routing checks
    learn_pidx: int = -1              # learn from a DIFFERENT pidx (split)
    restore_dir: str = ""             # seed a fresh engine from this dir


@dataclass
class OpenReplicaResponse:
    error: int = 0
    error_text: str = ""
    last_committed: int = 0
    last_prepared: int = 0


@dataclass
class CloseReplicaRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class ReplicaStateRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class ReplicaStateResponse:
    error: int = 0
    status: str = ""
    ballot: int = 0
    last_committed: int = 0
    last_prepared: int = 0
    last_durable: int = 0
    # what the ENGINE applied — diverges from last_committed exactly when
    # the replica is behind on apply (appended last: codec append-only rule)
    last_applied: int = 0


# --- replica <-> replica (2PC + learn) ---

@dataclass
class PrepareRequest:
    app_id: int = 0
    pidx: int = 0
    ballot: int = 0
    committed_decree: int = 0
    mutation: bytes = b""             # codec-encoded LogMutation
    # decree-pipelined window [d1..dk]: one prepare RPC carries every
    # mutation of the round (codec-encoded LogMutations, decree order).
    # Appended last per the codec's append-only evolution rule; when
    # non-empty it supersedes `mutation`.
    mutations: List[bytes] = field(default_factory=list)


@dataclass
class PrepareResponse:
    error: int = 0
    reason: str = ""                  # "", "gap", "stale_ballot"
    last_prepared: int = 0


@dataclass
class FileBlob:
    name: str = ""
    data: bytes = b""


@dataclass
class LearnRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class LearnResponse:
    error: int = 0
    files: List[FileBlob] = field(default_factory=list)
    tail: List[bytes] = field(default_factory=list)   # encoded LogMutations
    last_committed: int = 0
    ballot: int = 0


# --- duplication lifecycle DDL (reference duplication.cpp:32-260) ---

@dataclass
class DupEntry:
    dupid: int = 0
    remote: str = ""                  # remote cluster name
    status: str = "init"              # init | start | pause  (removed = gone)
    fail_mode: str = "slow"           # slow | skip
    create_ts_ms: int = 0


@dataclass
class AddDuplicationRequest:
    app_name: str = ""
    remote_cluster: str = ""
    freeze: bool = False              # start in DS_INIT (no shipping yet)


@dataclass
class AddDuplicationResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0
    dupid: int = 0


@dataclass
class QueryDuplicationRequest:
    app_name: str = ""


@dataclass
class QueryDuplicationResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0
    entries: List[DupEntry] = field(default_factory=list)


@dataclass
class ModifyDuplicationRequest:
    app_name: str = ""
    dupid: int = 0
    status: str = ""                  # "" = keep; start | pause | removed
    fail_mode: str = ""               # "" = keep; slow | skip


@dataclass
class ModifyDuplicationResponse:
    error: int = 0
    error_text: str = ""


# --- periodic backup policies (reference cold_backup.cpp policy surface) ---

@dataclass
class BackupPolicyInfo:
    name: str = ""
    backup_root: str = ""
    apps: List[str] = field(default_factory=list)
    interval_seconds: int = 86400
    history_count: int = 3            # retention: newest N backups kept
    enabled: bool = True
    next_backup_ts: int = 0           # unix seconds; 0 = due immediately
    recent_backup_ids: List[int] = field(default_factory=list)


@dataclass
class AddBackupPolicyRequest:
    policy: BackupPolicyInfo = field(default_factory=BackupPolicyInfo)


@dataclass
class AddBackupPolicyResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class LsBackupPolicyRequest:
    name: str = ""                    # "" = all


@dataclass
class LsBackupPolicyResponse:
    error: int = 0
    error_text: str = ""
    policies: List[BackupPolicyInfo] = field(default_factory=list)


@dataclass
class ModifyBackupPolicyRequest:
    name: str = ""
    enabled: int = -1                 # -1 keep, 0 disable, 1 enable
    interval_seconds: int = 0         # 0 = keep
    history_count: int = 0            # 0 = keep
    add_apps: List[str] = field(default_factory=list)
    remove_apps: List[str] = field(default_factory=list)


@dataclass
class ModifyBackupPolicyResponse:
    error: int = 0
    error_text: str = ""


# --- disaster recovery (reference recovery.cpp `recover`, ddd_diagnose) ---

@dataclass
class ReplicaInfo:
    """One replica as reported by a node (RPC_QUERY_REPLICA_INFO)."""

    app_name: str = ""
    app_id: int = 0
    pidx: int = 0
    partition_count: int = 0
    ballot: int = 0
    last_committed: int = 0
    last_prepared: int = 0
    last_durable: int = 0
    envs_json: str = "{}"
    # engine-applied decree (appended last: codec append-only evolution)
    last_applied: int = 0


@dataclass
class QueryReplicaInfoRequest:
    pass


@dataclass
class QueryReplicaInfoResponse:
    error: int = 0
    replicas: List[ReplicaInfo] = field(default_factory=list)


@dataclass
class RecoverRequest:
    nodes: List[str] = field(default_factory=list)   # addr list to rebuild from


@dataclass
class RecoverResponse:
    error: int = 0
    error_text: str = ""
    recovered_apps: List[str] = field(default_factory=list)


@dataclass
class DddPartitionInfo:
    app_name: str = ""
    pidx: int = 0
    reason: str = ""
    candidates: List[str] = field(default_factory=list)  # "addr ballot=N lc=N"
    action: str = ""                  # "" or "promoted <addr>"


@dataclass
class QueryClusterStateRequest:
    """Cluster-observability snapshot (ISSUE 8): liveness + partition
    configs + the beacon-folded per-replica lag/audit states, in one RPC
    — the cluster doctor's primary input."""

    pass


@dataclass
class QueryClusterStateResponse:
    error: int = 0
    # {"nodes": {addr: {"alive", "last_beacon_ago_s"}},
    #  "apps": {name: {"app_id", "partition_count",
    #                  "partitions": [{"pidx","ballot","primary",
    #                                  "secondaries"}]}},
    #  "replica_states": {addr: {gpid: state}}}
    state_json: str = "{}"


@dataclass
class DddDiagnoseRequest:
    app_name: str = ""                # "" = all apps
    force: bool = False               # actually promote the best candidate


@dataclass
class DddDiagnoseResponse:
    error: int = 0
    error_text: str = ""
    partitions: List[DddPartitionInfo] = field(default_factory=list)
