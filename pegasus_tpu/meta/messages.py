"""Meta-server wire messages (the replication_ddl_client / meta surface).

Mirrors the rDSN meta contract Pegasus consumes (SURVEY.md §2.4 'Meta
server'): table DDL, partition-config queries, app-envs, and the beacon
failure detector (config.ini:232-238). Addresses travel as "host:port"
strings.
"""

from dataclasses import dataclass, field
from typing import List


@dataclass
class PartitionConfig:
    pidx: int = 0
    ballot: int = 0
    primary: str = ""                 # "" = unassigned
    secondaries: List[str] = field(default_factory=list)


@dataclass
class AppInfo:
    app_name: str = ""
    app_id: int = 0
    partition_count: int = 0
    replica_count: int = 3
    status: str = "AS_AVAILABLE"
    envs_json: str = "{}"


@dataclass
class CreateAppRequest:
    app_name: str = ""
    partition_count: int = 8
    replica_count: int = 3
    envs_json: str = "{}"


@dataclass
class CreateAppResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0


@dataclass
class DropAppRequest:
    app_name: str = ""


@dataclass
class DropAppResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class ListAppsRequest:
    pass


@dataclass
class ListAppsResponse:
    error: int = 0
    apps: List[AppInfo] = field(default_factory=list)


@dataclass
class QueryConfigRequest:
    app_name: str = ""


@dataclass
class QueryConfigResponse:
    error: int = 0
    error_text: str = ""
    app: AppInfo = field(default_factory=AppInfo)
    partitions: List[PartitionConfig] = field(default_factory=list)


@dataclass
class SetAppEnvsRequest:
    app_name: str = ""
    envs_json: str = "{}"


@dataclass
class SetAppEnvsResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class BeaconRequest:
    node: str = ""                    # replica node address
    alive_replicas: List[str] = field(default_factory=list)  # "app_id.pidx"


@dataclass
class BeaconResponse:
    error: int = 0
    allowed: bool = True              # lease granted


@dataclass
class ProposeRequest:
    """Move a partition's primary (the balancer's move_primary action)."""

    app_name: str = ""
    pidx: int = 0
    target: str = ""                  # must be a current secondary


@dataclass
class ProposeResponse:
    error: int = 0
    error_text: str = ""


@dataclass
class BalanceRequest:
    pass


@dataclass
class BalanceResponse:
    error: int = 0
    moved: int = 0


@dataclass
class NodeInfo:
    address: str = ""
    alive: bool = True
    last_beacon_ms: int = 0
    replica_count: int = 0


@dataclass
class ListNodesRequest:
    pass


@dataclass
class ListNodesResponse:
    error: int = 0
    nodes: List[NodeInfo] = field(default_factory=list)


@dataclass
class SplitAppRequest:
    app_name: str = ""


@dataclass
class SplitAppResponse:
    error: int = 0
    error_text: str = ""
    new_partition_count: int = 0


@dataclass
class BackupAppRequest:
    app_name: str = ""
    backup_root: str = ""             # block-service path (local FS provider)


@dataclass
class BackupAppResponse:
    error: int = 0
    error_text: str = ""
    backup_id: int = 0


@dataclass
class RestoreAppRequest:
    backup_root: str = ""
    backup_id: int = 0
    old_app_name: str = ""
    new_app_name: str = ""


@dataclass
class RestoreAppResponse:
    error: int = 0
    error_text: str = ""
    app_id: int = 0


@dataclass
class StartBulkLoadRequest:
    app_name: str = ""
    provider_root: str = ""


@dataclass
class StartBulkLoadResponse:
    error: int = 0
    error_text: str = ""
    ingested_records: int = 0


# --- meta -> replica node commands ---

@dataclass
class OpenReplicaRequest:
    app_name: str = ""
    app_id: int = 0
    pidx: int = 0
    ballot: int = 0
    primary: str = ""
    secondaries: List[str] = field(default_factory=list)
    learn_from: str = ""              # non-empty: seed from this node first
    envs_json: str = "{}"
    partition_count: int = 0          # for partition-hash routing checks
    learn_pidx: int = -1              # learn from a DIFFERENT pidx (split)
    restore_dir: str = ""             # seed a fresh engine from this dir


@dataclass
class OpenReplicaResponse:
    error: int = 0
    error_text: str = ""
    last_committed: int = 0
    last_prepared: int = 0


@dataclass
class CloseReplicaRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class ReplicaStateRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class ReplicaStateResponse:
    error: int = 0
    status: str = ""
    ballot: int = 0
    last_committed: int = 0
    last_prepared: int = 0
    last_durable: int = 0


# --- replica <-> replica (2PC + learn) ---

@dataclass
class PrepareRequest:
    app_id: int = 0
    pidx: int = 0
    ballot: int = 0
    committed_decree: int = 0
    mutation: bytes = b""             # codec-encoded LogMutation


@dataclass
class PrepareResponse:
    error: int = 0
    reason: str = ""                  # "", "gap", "stale_ballot"
    last_prepared: int = 0


@dataclass
class FileBlob:
    name: str = ""
    data: bytes = b""


@dataclass
class LearnRequest:
    app_id: int = 0
    pidx: int = 0


@dataclass
class LearnResponse:
    error: int = 0
    files: List[FileBlob] = field(default_factory=list)
    tail: List[bytes] = field(default_factory=list)   # encoded LogMutations
    last_committed: int = 0
    ballot: int = 0
