"""Redis protocol proxy: RESP server mapped onto the pegasus client.

Mirror of src/redis_protocol (proxy_lib/redis_parser.cpp command table
:41-53, session layer proxy_layer.h): speaks RESP over TCP, translating
SET/GET/DEL/SETEX/TTL/PTTL/INCR[BY]/DECR[BY] onto KV ops (redis key =
hash_key, sort_key = "") and GEOADD/GEODIST/GEOPOS/GEORADIUS[BYMEMBER]
onto the geo client's dual-table index. Any redis client (redis-cli,
libraries) can talk to a pegasus-tpu cluster through it.
"""

import socket
import socketserver
import threading

from ..client import PegasusClient, PegasusError
from ..geo.geo_client import GeoClient
from ..runtime.tasking import spawn_thread

EMPTY_SK = b""


# ------------------------------------------------------------- RESP codec

def _encode_simple(s: str) -> bytes:
    return b"+" + s.encode() + b"\r\n"


def _encode_error(s: str) -> bytes:
    return b"-ERR " + s.encode() + b"\r\n"


def _encode_int(n: int) -> bytes:
    return b":" + str(n).encode() + b"\r\n"


def _encode_bulk(v) -> bytes:
    if v is None:
        return b"$-1\r\n"
    return b"$" + str(len(v)).encode() + b"\r\n" + v + b"\r\n"


def _encode_array(items) -> bytes:
    if items is None:
        return b"*-1\r\n"
    out = b"*" + str(len(items)).encode() + b"\r\n"
    for it in items:
        if isinstance(it, (list, tuple)):
            out += _encode_array(it)
        elif isinstance(it, int):
            out += _encode_int(it)
        else:
            out += _encode_bulk(it)
    return out


def _read_line(rfile) -> bytes:
    line = rfile.readline()
    if not line:
        raise ConnectionError("peer closed")
    return line.rstrip(b"\r\n")


def read_command(rfile) -> list:
    """One RESP command -> list[bytes] (arrays + inline forms)."""
    line = _read_line(rfile)
    if not line:
        return []
    if line[:1] == b"*":
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = _read_line(rfile)
            if hdr[:1] != b"$":
                raise ValueError("expected bulk string")
            ln = int(hdr[1:])
            data = rfile.read(ln + 2)[:-2]
            args.append(data)
        return args
    return line.split()  # inline command


# ---------------------------------------------------------------- proxy


class RedisProxy:
    def __init__(self, client: PegasusClient, geo: GeoClient = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client
        self.geo = geo
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                self.request.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                while True:
                    try:
                        args = read_command(self.rfile)
                    except (ConnectionError, ValueError, OSError):
                        return
                    if not args:
                        continue
                    try:
                        out = outer.dispatch(args)
                    except PegasusError as e:
                        out = _encode_error(str(e))
                    except (ValueError, IndexError) as e:
                        out = _encode_error(f"wrong arguments: {e}")
                    try:
                        self.wfile.write(out)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server((host, port), Handler)
        self.address = self._srv.server_address
        self._thread = spawn_thread(self._srv.serve_forever, daemon=True,
                                    start=False)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    # ------------------------------------------------------------ dispatch

    def dispatch(self, args: list) -> bytes:
        cmd = args[0].upper().decode()
        fn = getattr(self, f"cmd_{cmd.lower()}", None)
        if fn is None:
            return _encode_error(f"unknown command '{cmd}'")
        return fn(args[1:])

    def cmd_ping(self, a):
        return _encode_simple("PONG")

    def cmd_set(self, a):
        ttl = 0
        i = 2
        while i < len(a):
            opt = a[i].upper()
            if opt == b"EX" and i + 1 < len(a):
                ttl = int(a[i + 1])
                i += 2
            else:
                # NX/XX/PX/KEEPTTL would silently change semantics if
                # answered OK as a plain SET — refuse instead
                return _encode_error(f"unsupported SET option {opt.decode()}")
        self.client.set(a[0], EMPTY_SK, a[1], ttl_seconds=ttl)
        return _encode_simple("OK")

    def cmd_setex(self, a):
        self.client.set(a[0], EMPTY_SK, a[2], ttl_seconds=int(a[1]))
        return _encode_simple("OK")

    def cmd_get(self, a):
        return _encode_bulk(self.client.get(a[0], EMPTY_SK))

    def cmd_del(self, a):
        n = 0
        for key in a:
            if self.client.exist(key, EMPTY_SK):
                self.client.delete(key, EMPTY_SK)
                n += 1
        return _encode_int(n)

    def cmd_exists(self, a):
        return _encode_int(sum(1 for k in a if self.client.exist(k, EMPTY_SK)))

    def cmd_ttl(self, a):
        t = self.client.ttl(a[0], EMPTY_SK)
        return _encode_int(-2 if t is None else (-1 if t < 0 else t))

    def cmd_pttl(self, a):
        t = self.client.ttl(a[0], EMPTY_SK)
        return _encode_int(-2 if t is None else (-1 if t < 0 else t * 1000))

    def cmd_incr(self, a):
        return _encode_int(self.client.incr(a[0], EMPTY_SK, 1))

    def cmd_incrby(self, a):
        return _encode_int(self.client.incr(a[0], EMPTY_SK, int(a[1])))

    def cmd_decr(self, a):
        return _encode_int(self.client.incr(a[0], EMPTY_SK, -1))

    def cmd_decrby(self, a):
        return _encode_int(self.client.incr(a[0], EMPTY_SK, -int(a[1])))

    # geo ------------------------------------------------------------------

    def _need_geo(self):
        if self.geo is None:
            raise ValueError("geo commands not configured")
        return self.geo

    def cmd_geoadd(self, a):
        geo = self._need_geo()
        key, n = a[0], 0
        for i in range(1, len(a) - 2, 3):
            lng, lat, member = float(a[i]), float(a[i + 1]), a[i + 2]
            geo.set_geo_data(lat, lng, key, member, b"||||||")
            n += 1
        return _encode_int(n)

    def cmd_geodist(self, a):
        geo = self._need_geo()
        d = geo.distance(a[0], a[1], a[0], a[2])
        if d is None:
            return _encode_bulk(None)
        unit = a[3].lower() if len(a) > 3 else b"m"
        scale = {b"m": 1.0, b"km": 1000.0}.get(unit, 1.0)
        return _encode_bulk(repr(round(d / scale, 4)).encode())

    def cmd_geopos(self, a):
        geo = self._need_geo()
        out = []
        for member in a[1:]:
            v = geo.get(a[0], member)
            ll = geo.codec.decode(v) if v is not None else None
            out.append(None if ll is None
                       else [repr(ll[1]).encode(), repr(ll[0]).encode()])
        return _encode_array(out)

    def cmd_georadius(self, a):
        geo = self._need_geo()
        lng, lat, radius = float(a[1]), float(a[2]), float(a[3])
        radius *= {b"m": 1, b"km": 1000}.get(a[4].lower() if len(a) > 4 else b"m", 1)
        rows = geo.search_radial(lat, lng, radius)
        return _encode_array([sk for _, hk, sk, _ in rows if hk == a[0]])

    def cmd_georadiusbymember(self, a):
        geo = self._need_geo()
        radius = float(a[2]) * {b"m": 1, b"km": 1000}.get(
            a[3].lower() if len(a) > 3 else b"m", 1)
        rows = geo.search_radial_by_key(a[0], a[1], radius)
        return _encode_array([sk for _, hk, sk, _ in rows if hk == a[0]])
