from .proxy import RedisProxy

__all__ = ["RedisProxy"]
